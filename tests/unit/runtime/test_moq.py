"""MoQ (quantize_training) tests — reference model:
``tests/unit/runtime/half_precision/test_moq.py`` (TestQuantizedTraining)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import gpt2_model
from deepspeed_tpu.runtime.quantize import MoQQuantizer


def _unique_count(arr):
    return len(np.unique(np.round(np.asarray(arr, np.float64), 6)))


class TestMoQQuantizer:

    def test_bit_annealing_schedule(self):
        q = MoQQuantizer({"enabled": True,
                          "quantize_bits": {"start_bits": 8, "target_bits": 4},
                          "quantize_schedule": {"quantize_period": 2},
                          "quantize_groups": 1})
        w = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8))
        params = {"blocks": {"fc_in": {"kernel": w}}}
        bits_seen = []
        for _ in range(30):
            params = q.quantize(params, overflow=False)
            bits_seen.append(q._bits.copy())
        # anneal 8->4 with doubling periods: drops at steps 2, 6(2+4), 14(6+8), 30
        assert bits_seen[1][0] == 7 and bits_seen[5][0] == 6
        assert bits_seen[13][0] == 5 and bits_seen[29][0] == 4
        # at 4 bits symmetric the kernel takes at most 16 distinct values/group
        assert _unique_count(params["blocks"]["fc_in"]["kernel"][0]) <= 16

    def test_ternary_and_binary_forms(self):
        # annealing passes through ternary, which zeroes small weights, so
        # the binary stage sees exact zeros and keeps them (sign(0) == 0):
        # both end states are {-alpha, 0, +alpha}
        for target in (2, 1):
            q = MoQQuantizer({"enabled": True,
                              "quantize_bits": {"start_bits": 3,
                                                "target_bits": target},
                              "quantize_schedule": {"quantize_period": 1},
                              "quantize_groups": 1})
            params = {"blocks": {"fc_in": {"kernel": jax.random.normal(
                jax.random.PRNGKey(1), (1, 16, 16))}}}
            for _ in range(20):
                params = q.quantize(params)
            assert int(q._bits[0]) == target
            vals = np.unique(np.round(np.asarray(
                params["blocks"]["fc_in"]["kernel"], np.float64), 8))
            assert len(vals) <= 3
            assert np.allclose(vals + vals[::-1], 0)  # symmetric around 0

    def test_eigenvalue_stretches_period(self):
        q = MoQQuantizer({"enabled": True,
                          "quantize_bits": {"start_bits": 8, "target_bits": 4},
                          "quantize_schedule": {"quantize_period": 2},
                          "eigenvalue": {"enabled": True}})
        w = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4))
        params = {"blocks": {"fc": {"kernel": w}}}
        params = q.quantize(params)  # step 1: nothing due
        params = q.quantize(params, eigenvalues=np.array([0.0, 1.0]))
        # layer 0: period doubles to 4; layer 1 (high curvature): 4 * 5 = 20
        assert q._period.tolist() == [4, 20]
        assert q._bits.tolist() == [7, 7]

    def test_overflow_skips_without_eigenvalue(self):
        q = MoQQuantizer({"enabled": True,
                          "quantize_bits": {"start_bits": 8, "target_bits": 4},
                          "quantize_schedule": {"quantize_period": 1}})
        params = {"blocks": {"fc": {"kernel": jnp.ones((1, 4, 4))}}}
        out = q.quantize(params, overflow=True)
        assert q.qsteps == 0 and out is params

    def test_state_roundtrip(self):
        q = MoQQuantizer({"enabled": True,
                          "quantize_bits": {"start_bits": 8, "target_bits": 4},
                          "quantize_schedule": {"quantize_period": 2}})
        params = {"blocks": {"fc": {"kernel": jnp.ones((2, 4, 4))}}}
        for _ in range(5):
            params = q.quantize(params)
        q2 = MoQQuantizer({"enabled": True,
                           "quantize_bits": {"start_bits": 8, "target_bits": 4},
                           "quantize_schedule": {"quantize_period": 2}})
        q2.load_state_dict(q.state_dict())
        assert q2.qsteps == q.qsteps and q2._bits.tolist() == q._bits.tolist()


def test_moq_through_engine(eight_devices):
    """quantize_training in the engine config: training proceeds, loss
    decreases, and the weights end up on the quantization grid."""
    m = gpt2_model("gpt2-tiny", max_seq_len=16, vocab_size=128, remat=False,
                   dtype=jnp.float32)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "quantize_training": {
                    "enabled": True,
                    "quantize_bits": {"start_bits": 8, "target_bits": 6},
                    "quantize_schedule": {"quantize_period": 1},
                    "quantize_groups": 4,
                }})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 128, size=(8, 12))}
    losses = [float(engine.train_batch(batch)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    assert engine.quantizer._bits.max() <= 7
    kernel = np.asarray(engine.state["params"]["blocks"]["q_proj"]["kernel"][0])
    # grouped 7-bit symmetric: far fewer distinct values than a dense fp kernel
    assert _unique_count(kernel) < kernel.size // 2


def test_moq_with_zeropp_secondary_aliasing(eight_devices):
    """Regression: MoQ donates the param buffers, and at hpz==1 the ZeRO++
    secondary ALIASES params — quantize must run before the secondary
    refresh or the next forward reads deleted arrays."""
    m = gpt2_model("gpt2-tiny", max_seq_len=16, vocab_size=128, remat=False,
                   dtype=jnp.float32)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_weights": True,
                                      "stage3_param_persistence_threshold": 0},
                "quantize_training": {
                    "enabled": True,
                    "quantize_bits": {"start_bits": 8, "target_bits": 7},
                    "quantize_schedule": {"quantize_period": 1},
                }})
    batch = {"input_ids": np.random.default_rng(2).integers(0, 128, size=(8, 12))}
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    assert all(np.isfinite(losses)), losses
    assert engine.quantizer.qsteps == 3


def test_moq_eigenvalue_through_engine(eight_devices):
    """eigenvalue-scheduled MoQ end to end (engine computes per-layer
    curvature at gas boundaries and stretches periods)."""
    m = gpt2_model("gpt2-tiny", max_seq_len=16, vocab_size=128, remat=False,
                   dtype=jnp.float32)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "quantize_training": {
                    "enabled": True,
                    "quantize_bits": {"start_bits": 8, "target_bits": 7},
                    "quantize_schedule": {"quantize_period": 1},
                    "eigenvalue": {"enabled": True, "max_iter": 3,
                                   "gas_boundary_resolution": 1},
                }})
    batch = {"input_ids": np.random.default_rng(1).integers(0, 128, size=(8, 12))}
    for _ in range(3):
        engine.train_batch(batch)
    assert engine.quantizer.qsteps == 3
    # periods were eigenvalue-stretched: after the first drop they are >= 2x
    assert (engine.quantizer._period >= 2).all()
    assert engine.quantizer._bits.tolist() == [7, 7]
