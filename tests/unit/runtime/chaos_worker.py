"""Chaos worker (NOT a pytest module): the training script the resilience
chaos tests run under ``DSElasticAgent``.

World shape: this environment's jaxlib cannot run cross-process CPU
collectives at all ("Multiprocess computations aren't implemented on the
CPU backend" — pre-existing; tests/unit/runtime/test_multiprocess.py hits
the same wall), so the 8-device CPU audit mesh is the repo's standard
single-process virtual form (tests/conftest.py): rank 0 hosts
``4 x world_size`` virtual devices and non-zero ranks exit immediately,
donating their slot to rank 0's mesh. The agent machinery stays fully
real — spawn, SIGKILL, reap, restart, shrink, DSTPU_ELASTIC threading —
and a shrink from 2 slots to 1 genuinely halves the dp width (8 -> 4),
which is the ZeRO re-bucket the resume path must survive.

Trains a tiny ZeRO-2 gpt2 to ``total_steps`` with a checkpoint committed
after every optimizer step and one loss logged per step through
``resilience.chaos.log_step``. Resume comes for free: the agent threads
``checkpoint_dir`` through ``DSTPU_ELASTIC`` and
``deepspeed_tpu.initialize`` reloads the last committed tag, so this
script has NO resume branch — the property under test is that a
restarted world continues mid-trajectory without one. The global batch
(8 sequences, seeded per optimizer step) is identical at every world
size, so loss trajectories are comparable across dp widths.
"""

import json
import os
import sys

if int(os.environ.get("JAX_PROCESS_ID", "0")) != 0:
    sys.exit(0)  # slot donated to rank 0's virtual mesh (see docstring)

_EL = json.loads(os.environ["DSTPU_ELASTIC"])
_DEVICES = 4 * int(_EL["world_size"])
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_DEVICES}").strip()
os.environ["DSTPU_ACCELERATOR"] = "cpu"
# single-process world: the coordinator rendezvous the agent exported
# must not be joined (the donated ranks are gone)
for _v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
           "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
    os.environ.pop(_v, None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import gpt2_model  # noqa: E402
from deepspeed_tpu.resilience import chaos  # noqa: E402

GLOBAL_BATCH = 8
SEQ_LEN = 8


def step_batch(step: int, skip_span: bool = False):
    """The global batch of optimizer step ``step`` — a pure function of
    the step index, so an uninterrupted run, a killed-and-resumed run,
    and a shrunk-world resume all consume identical data.
    ``skip_span`` is the guardian's skip-ahead hook: a step whose data
    span was marked poisoned (rolled back twice — data-deterministic
    anomaly) draws from a disjoint seed range instead of looping on the
    same poison forever."""
    rng = np.random.default_rng((10_000_000 if skip_span else 1000) + step)
    return {"input_ids": rng.integers(0, 128, size=(GLOBAL_BATCH, SEQ_LEN))}


def main(out_dir: str, total_steps: int = 4) -> int:
    assert jax.device_count() == _DEVICES, jax.device_count()
    assert GLOBAL_BATCH % _DEVICES == 0, (GLOBAL_BATCH, _DEVICES)

    model = gpt2_model("gpt2-tiny", max_seq_len=16, vocab_size=128,
                       remat=False)
    # initialize() resumes from DSTPU_ELASTIC's checkpoint_dir last
    # committed tag (fresh start when nothing committed yet); the
    # guardian (numerics chaos arm) arms via the DSTPU_GUARDIAN env.
    # DSTPU_CHAOS_OFFLOAD ("cpu" | "nvme:<dir>") adds an offloaded
    # optimizer — the ISSUE 15 sidecar-durability chaos arm.
    zero = {"stage": 2}
    offload = os.environ.get("DSTPU_CHAOS_OFFLOAD", "")
    if offload:
        dev, _, nvme = offload.partition(":")
        zero["offload_optimizer"] = {"device": dev,
                                     **({"nvme_path": nvme} if nvme else {})}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // _DEVICES,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": zero,
    }, seed=3)
    guardian = engine._guardian

    while engine.global_steps < total_steps:
        step = engine.global_steps + 1
        skip = guardian is not None and guardian.should_skip_data(step)
        loss = float(engine.train_batch(step_batch(step, skip_span=skip)))
        if engine.global_steps < step:
            # the guardian rolled this step back (in-process form) or an
            # anomalous step must not pollute the trajectory: re-run
            continue
        # with the guardian armed an anomalous-but-tolerated step may
        # carry a non-finite loss; without it that is a hard failure
        assert guardian is not None or np.isfinite(loss), (step, loss)
        # an injected crash at step k dies inside train_batch (step_end
        # seam) — before this step's loss is logged or its tag commits,
        # so the resumed attempt replays it from tag k-1
        chaos.log_step(out_dir, step, loss, rank=0,
                       world=_EL.get("world_size"))
        engine.save_checkpoint(_EL["checkpoint_dir"])
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1],
                  int(sys.argv[2]) if len(sys.argv) > 2 else 4))
