"""CLIP text encoder parity tests — exact logits vs
``transformers.CLIPTextModel`` (the SD prompt-encoder container)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.model_implementations.clip import load_clip_text_model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module", params=["quick_gelu", "gelu"])
def clip_ckpt(tmp_path_factory, request):
    path = tmp_path_factory.mktemp(f"hf_clip_{request.param}")
    cfg = transformers.CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act=request.param,
        eos_token_id=98, bos_token_id=97)
    torch.manual_seed(0)
    m = transformers.CLIPTextModel(cfg).eval()
    m.save_pretrained(path)
    return path, m


def test_hidden_state_and_pooled_parity(clip_ckpt):
    path, hf = clip_ckpt
    model, params = load_clip_text_model(str(path))
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 96, size=(2, 12))
    ids[0, 7] = 98   # EOS mid-sequence: pooled must read position 7
    ids[1, 11] = 98
    with torch.no_grad():
        ref = hf(torch.tensor(ids))
    hidden, pooled = jax.jit(model.apply)(
        jax.tree.map(jnp.asarray, params), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(hidden),
                               ref.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled),
                               ref.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_legacy_eos_token_id_2_pooling(tmp_path_factory):
    """SD-1.5 / openai CLIP configs say eos_token_id=2 while the real EOS
    id is the vocabulary's largest token — HF pools at argmax(input_ids)
    there, and so must we."""
    path = tmp_path_factory.mktemp("hf_clip_legacy")
    cfg = transformers.CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, eos_token_id=2, bos_token_id=0)
    torch.manual_seed(1)
    hf = transformers.CLIPTextModel(cfg).eval()
    hf.save_pretrained(path)
    model, params = load_clip_text_model(str(path))
    rng = np.random.default_rng(3)
    ids = rng.integers(3, 90, size=(2, 10))
    ids[0, 6] = 98  # "real" EOS = largest id, mid-sequence
    ids[1, 9] = 98
    with torch.no_grad():
        ref = hf(torch.tensor(ids))
    _, pooled = model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(pooled), ref.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_unsupported_activation_rejected():
    from deepspeed_tpu.model_implementations.clip import _act
    with pytest.raises(ValueError, match="unsupported CLIP hidden_act"):
        _act("gelu_new", jnp.ones((2, 2)))


def test_text_config_nested_form(tmp_path, clip_ckpt):
    """A full CLIPConfig (text_config + vision_config) directory must load
    the text tower."""
    import json
    path, hf = clip_ckpt
    cfg = json.loads((path / "config.json").read_text())
    nested = {"model_type": "clip", "text_config": cfg}
    (tmp_path / "config.json").write_text(json.dumps(nested))
    import shutil
    for f in path.iterdir():
        if f.name != "config.json":
            shutil.copy(f, tmp_path / f.name)
    model, params = load_clip_text_model(str(tmp_path))
    assert model.config.hidden_size == 32
    ids = np.full((1, 5), 98)
    hidden, _ = model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids))
    assert hidden.shape == (1, 5, 32)
