"""Diffusers UNet implementation tests (reference
``tests/unit/inference/test_inference.py`` stable-diffusion path +
``model_implementations/diffusers``): a checkpoint in diffusers' exact
on-disk format (config.json + diffusion_pytorch_model.safetensors with
the standard dotted names) must load and run."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.model_implementations import (UNet2DConditionModel,
                                                 UNetConfig,
                                                 load_diffusers_unet)
from deepspeed_tpu.model_implementations.diffusers.unet_2d_condition import (
    _nest, init_unet_params)

TINY = UNetConfig(
    in_channels=4, out_channels=4, sample_size=16,
    block_out_channels=(32, 64), layers_per_block=1,
    cross_attention_dim=24, attention_head_dim=4, norm_num_groups=8,
    down_block_types=("CrossAttnDownBlock2D", "DownBlock2D"),
    up_block_types=("UpBlock2D", "CrossAttnUpBlock2D"))

TINY_DIFFUSERS_CONFIG = {
    "in_channels": 4, "out_channels": 4, "sample_size": 16,
    "block_out_channels": [32, 64], "layers_per_block": 1,
    "cross_attention_dim": 24, "attention_head_dim": 4,
    "norm_num_groups": 8,
    "down_block_types": ["CrossAttnDownBlock2D", "DownBlock2D"],
    "up_block_types": ["UpBlock2D", "CrossAttnUpBlock2D"],
}


def _forward(model, params, seed=0):
    rng = np.random.default_rng(seed)
    sample = jnp.asarray(rng.standard_normal((2, 16, 16, 4)), jnp.float32)
    t = jnp.asarray([10, 500], jnp.int32)
    ctx = jnp.asarray(rng.standard_normal((2, 7, 24)), jnp.float32)
    return model.apply(params, sample, t, ctx)


def test_expected_diffusers_key_names():
    """The generated tree must use the REAL diffusers names — spot-check
    the load-bearing ones (these exact strings appear in every SD-1.x
    UNet safetensors index)."""
    flat = init_unet_params(TINY)
    for key in [
        "conv_in.weight",
        "time_embedding.linear_1.weight",
        "down_blocks.0.resnets.0.time_emb_proj.weight",
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q.weight",
        "down_blocks.0.attentions.0.transformer_blocks.0.attn2.to_k.weight",
        "down_blocks.0.attentions.0.transformer_blocks.0.ff.net.0.proj.weight",
        "down_blocks.0.downsamplers.0.conv.weight",
        "mid_block.resnets.1.conv2.weight",
        "up_blocks.0.resnets.0.conv_shortcut.weight",
        "up_blocks.0.upsamplers.0.conv.weight",
        "up_blocks.1.attentions.1.proj_out.weight",
        "conv_norm_out.weight",
        "conv_out.bias",
    ]:
        assert key in flat, key
    # cross-attn k/v read the text encoding width
    assert flat["down_blocks.0.attentions.0.transformer_blocks.0"
                ".attn2.to_k.weight"].shape == (32, 24)


def test_forward_shapes_and_finite():
    model = UNet2DConditionModel(TINY)
    params = _nest(init_unet_params(TINY, seed=1))
    out = _forward(model, params)
    assert out.shape == (2, 16, 16, 4)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_jit_matches_eager():
    model = UNet2DConditionModel(TINY)
    params = _nest(init_unet_params(TINY, seed=2))
    eager = _forward(model, params)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.standard_normal((2, 16, 16, 4)), jnp.float32)
    t = jnp.asarray([10, 500], jnp.int32)
    ctx = jnp.asarray(rng.standard_normal((2, 7, 24)), jnp.float32)
    jitted = jax.jit(model.apply)(params, sample, t, ctx)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                               rtol=1e-5, atol=1e-5)


def test_load_from_diffusers_directory(tmp_path):
    """End to end through the real on-disk format."""
    from safetensors.numpy import save_file

    flat = init_unet_params(TINY, seed=3)
    (tmp_path / "config.json").write_text(json.dumps(TINY_DIFFUSERS_CONFIG))
    save_file(flat, tmp_path / "diffusion_pytorch_model.safetensors")

    model, params = load_diffusers_unet(str(tmp_path))
    assert model.config.block_out_channels == (32, 64)
    out = _forward(model, params, seed=4)
    assert out.shape == (2, 16, 16, 4)
    # identical to using the in-memory tree directly
    direct = _forward(UNet2DConditionModel(TINY), _nest(flat), seed=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(direct),
                               rtol=1e-6, atol=1e-6)


def test_timesteps_change_output():
    model = UNet2DConditionModel(TINY)
    params = _nest(init_unet_params(TINY, seed=5))
    rng = np.random.default_rng(1)
    sample = jnp.asarray(rng.standard_normal((1, 16, 16, 4)), jnp.float32)
    ctx = jnp.asarray(rng.standard_normal((1, 7, 24)), jnp.float32)
    o1 = model.apply(params, sample, jnp.asarray([1]), ctx)
    o2 = model.apply(params, sample, jnp.asarray([900]), ctx)
    assert float(jnp.abs(o1 - o2).max()) > 1e-6


def test_cross_attention_sees_context():
    model = UNet2DConditionModel(TINY)
    params = _nest(init_unet_params(TINY, seed=6))
    rng = np.random.default_rng(2)
    sample = jnp.asarray(rng.standard_normal((1, 16, 16, 4)), jnp.float32)
    t = jnp.asarray([50])
    c1 = jnp.asarray(rng.standard_normal((1, 7, 24)), jnp.float32)
    c2 = jnp.asarray(rng.standard_normal((1, 7, 24)), jnp.float32)
    o1 = model.apply(params, sample, t, c1)
    o2 = model.apply(params, sample, t, c2)
    assert float(jnp.abs(o1 - o2).max()) > 1e-6


class TestVAEDecoder:

    CFG = None  # populated below

    def _tiny(self):
        from deepspeed_tpu.model_implementations.diffusers.vae import (
            VAEDecoder, VAEDecoderConfig, init_vae_decoder_params)
        cfg = VAEDecoderConfig(block_out_channels=(16, 32), layers_per_block=1,
                               norm_num_groups=8)
        return VAEDecoder(cfg), init_vae_decoder_params(cfg, seed=7), cfg

    def test_decode_shape_and_upsampling(self):
        from deepspeed_tpu.model_implementations.diffusers.unet_2d_condition import _nest
        dec, flat, cfg = self._tiny()
        lat = jnp.asarray(np.random.default_rng(0).standard_normal((1, 8, 8, 4)),
                          jnp.float32)
        img = dec.apply(_nest(flat), lat)
        # one 2x upsample per non-final up block
        assert img.shape == (1, 16, 16, 3)
        assert bool(jnp.all(jnp.isfinite(img)))

    def test_load_from_directory(self, tmp_path):
        from safetensors.numpy import save_file
        from deepspeed_tpu.model_implementations.diffusers.vae import (
            load_diffusers_vae_decoder)
        _, flat, _ = self._tiny()
        # a real AutoencoderKL file also contains encoder tensors: add a
        # decoy to prove the loader filters them
        flat = dict(flat)
        flat["encoder.conv_in.weight"] = np.zeros((16, 3, 3, 3), np.float32)
        (tmp_path / "config.json").write_text(json.dumps({
            "latent_channels": 4, "out_channels": 3,
            "block_out_channels": [16, 32], "layers_per_block": 1,
            "norm_num_groups": 8}))
        save_file(flat, tmp_path / "diffusion_pytorch_model.safetensors")
        dec, params = load_diffusers_vae_decoder(str(tmp_path))
        assert "encoder" not in params
        lat = jnp.asarray(np.random.default_rng(1).standard_normal((2, 4, 4, 4)),
                          jnp.float32)
        img = jax.jit(dec.apply)(params, lat)
        assert img.shape == (2, 8, 8, 3)

    def test_vae_key_names(self):
        _, flat, _ = self._tiny()
        for key in ["post_quant_conv.weight", "decoder.conv_in.weight",
                    "decoder.mid_block.attentions.0.to_q.weight",
                    "decoder.up_blocks.0.resnets.0.norm1.weight",
                    "decoder.up_blocks.0.upsamplers.0.conv.weight",
                    "decoder.conv_out.bias"]:
            assert key in flat, key


def test_sd2_style_linear_projection_and_head_dims(tmp_path):
    """SD-2.x convention: use_linear_projection=True and a per-level
    attention_head_dim list (head DIMS, not counts)."""
    from safetensors.numpy import save_file
    from deepspeed_tpu.model_implementations.diffusers.unet_2d_condition import (
        init_unet_params, unet_config_from_diffusers)
    cfg_json = dict(TINY_DIFFUSERS_CONFIG, use_linear_projection=True,
                    attention_head_dim=[8, 16])
    cfg = unet_config_from_diffusers(cfg_json)
    assert cfg.heads_for_level(0) == 32 // 8 == 4
    assert cfg.heads_for_level(1) == 64 // 16 == 4
    flat = init_unet_params(cfg, seed=8)
    # proj_in is a Linear [C, C], not a 1x1 conv
    assert flat["down_blocks.0.attentions.0.proj_in.weight"].shape == (32, 32)
    (tmp_path / "config.json").write_text(json.dumps(cfg_json))
    save_file(flat, tmp_path / "diffusion_pytorch_model.safetensors")
    model, params = load_diffusers_unet(str(tmp_path))
    out = _forward(model, params, seed=9)
    assert out.shape == (2, 16, 16, 4)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_unsupported_checkpoint_rejected_loudly(tmp_path):
    """Extra keys (e.g. SD-XL add_embedding / deeper transformer stacks)
    must fail the schema check, not silently skip layers."""
    from safetensors.numpy import save_file
    flat = init_unet_params(TINY, seed=10)
    flat = dict(flat)
    flat["add_embedding.linear_1.weight"] = np.zeros((8, 8), np.float32)
    (tmp_path / "config.json").write_text(json.dumps(TINY_DIFFUSERS_CONFIG))
    save_file(flat, tmp_path / "diffusion_pytorch_model.safetensors")
    with pytest.raises(ValueError, match="unsupported"):
        load_diffusers_unet(str(tmp_path))
