"""Compression tests (reference tests/unit/compression/test_compression.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression import (CompressionManager, CompressionScheduler,
                                       fake_quantize_ste, init_compression,
                                       magnitude_prune_mask, redundancy_clean,
                                       row_prune_mask, student_initialization)
from deepspeed_tpu.models import llama_model
from deepspeed_tpu.runtime.config import CompressionConfig


class TestQuantOps:

    def test_fake_quant_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 64)), jnp.float32)
        q = fake_quantize_ste(x, num_bits=8)
        step = float(jnp.max(jnp.abs(x))) / 127
        assert float(jnp.max(jnp.abs(q - x))) <= step

    def test_ste_gradient_is_identity(self):
        x = jnp.linspace(-1, 1, 32)
        g = jax.grad(lambda v: jnp.sum(fake_quantize_ste(v, 4) ** 2))(x)
        # d/dx sum(q(x)^2) with STE = 2*q(x) (identity through quantizer)
        np.testing.assert_allclose(np.asarray(g),
                                   2 * np.asarray(fake_quantize_ste(x, 4)),
                                   rtol=1e-5)

    def test_prune_masks_sparsity(self):
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
        m = magnitude_prune_mask(w, 0.75)
        assert abs(float(jnp.mean(m.astype(jnp.float32))) - 0.25) < 0.02
        r = row_prune_mask(w, 0.5, dim=-1)
        # whole columns zeroed
        col = np.asarray(r).all(axis=0) | (~np.asarray(r)).all(axis=0)
        assert col.all()


class TestManager:

    def _cfg(self, **kw):
        base = {
            "weight_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {
                    "wq": {"params": {"start_bits": 8}, "modules": ["*"]}}},
            "sparse_pruning": {
                "shared_parameters": {"enabled": True},
                "different_groups": {
                    "sp": {"params": {"dense_ratio": 0.5}, "modules": ["q_proj"]}}},
        }
        base.update(kw)
        return CompressionConfig(**base)

    def test_compress_params_quantizes_matmuls_only(self):
        model = llama_model("llama2-tiny", dtype=jnp.float32, remat=False)
        params = model.init(jax.random.PRNGKey(0), jnp.float32)
        cm = CompressionManager(self._cfg())
        out = cm.compress_params(params)
        # norm scales untouched; kernels changed
        np.testing.assert_array_equal(np.asarray(out["ln_f"]["scale"]),
                                      np.asarray(params["ln_f"]["scale"]))
        assert not np.allclose(np.asarray(out["blocks"]["q_proj"]["kernel"]),
                               np.asarray(params["blocks"]["q_proj"]["kernel"]))

    def test_masks_match_patterns(self):
        model = llama_model("llama2-tiny", dtype=jnp.float32, remat=False)
        params = model.init(jax.random.PRNGKey(0), jnp.float32)
        cm = CompressionManager(self._cfg())
        n = cm.update_masks(params)
        assert n == 1  # only q_proj matched
        out = cm.compress_params(params, quant_enabled=False)
        q = np.asarray(out["blocks"]["q_proj"]["kernel"])
        assert (q == 0).mean() > 0.4  # ~50% pruned
        k = np.asarray(out["blocks"]["k_proj"]["kernel"])
        assert (k == 0).mean() < 0.05

    def test_redundancy_clean_loss_still_finite(self):
        model = llama_model("llama2-tiny", dtype=jnp.float32, remat=False)
        params = model.init(jax.random.PRNGKey(0), jnp.float32)
        cm = init_compression(None, {
            "weight_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {"g": {"params": {"bits": 8},
                                           "modules": ["*"]}}}})
        cleaned = redundancy_clean(params, cm)
        batch = {"input_ids": np.random.default_rng(0).integers(0, 1024, size=(2, 16))}
        loss = model.loss(cleaned, batch)
        assert np.isfinite(float(loss))

    def test_scheduler_gates_offsets(self):
        cm = CompressionManager(self._cfg())
        sched = CompressionScheduler(cm, {"quantize_offset": 10, "prune_offset": 5})
        assert not sched.quant_enabled(9) and sched.quant_enabled(10)
        assert not sched.prune_enabled(4) and sched.prune_enabled(5)


class TestStudentInit:

    def test_layer_map_copies_teacher_layers(self):
        teacher = llama_model("llama2-tiny", dtype=jnp.float32, remat=False,
                              num_layers=4)
        student = llama_model("llama2-tiny", dtype=jnp.float32, remat=False,
                              num_layers=2)
        tp = teacher.init(jax.random.PRNGKey(0), jnp.float32)
        sp = student.init(jax.random.PRNGKey(1), jnp.float32)
        out = student_initialization(sp, tp, layer_map=[1, 3])
        np.testing.assert_array_equal(
            np.asarray(out["blocks"]["q_proj"]["kernel"][0]),
            np.asarray(tp["blocks"]["q_proj"]["kernel"][1]))
        np.testing.assert_array_equal(
            np.asarray(out["blocks"]["q_proj"]["kernel"][1]),
            np.asarray(tp["blocks"]["q_proj"]["kernel"][3]))
        # embeddings copied wholesale
        np.testing.assert_array_equal(np.asarray(out["wte"]["embedding"]),
                                      np.asarray(tp["wte"]["embedding"]))


class TestBitsAnnealing:
    """start_bits → target_bits on the reference doubling schedule
    (runtime/quantize.py:135-140): drops at p, 2p, 4p, ..."""

    def test_scheduled_bits_doubling_drops(self):
        from deepspeed_tpu.compression.compress import CompressionManager
        gp = {"start_bits": 8, "target_bits": 4, "quantization_period": 10}
        expect = {0: 8, 9: 8, 10: 7, 19: 7, 20: 6, 39: 6, 40: 5, 79: 5,
                  80: 4, 10_000: 4}
        for step, bits in expect.items():
            assert CompressionManager.scheduled_bits(gp, step) == bits, step

    def test_no_target_holds_start_bits(self):
        from deepspeed_tpu.compression.compress import CompressionManager
        assert CompressionManager.scheduled_bits({"start_bits": 8}, 999) == 8
        assert CompressionManager.scheduled_bits(
            {"start_bits": 8, "target_bits": 4}, 999) == 8  # no period
        assert CompressionManager.scheduled_bits(
            {"start_bits": 8, "target_bits": 4, "quantization_period": 10},
            None) == 8

    def test_annealing_changes_quantization_through_scheduler(self):
        """Late-step fake-quant must be coarser than early-step (target_bits
        actually honored, the r1 advisor finding)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from deepspeed_tpu.compression.compress import CompressionManager
        from deepspeed_tpu.compression.scheduler import CompressionScheduler
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        cfg = DeepSpeedConfig({
            "compression_training": {"weight_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {"wq1": {"params": {
                    "start_bits": 8, "target_bits": 2,
                    "quantization_period": 4},
                    "modules": ["*"]}}}}}).compression_config
        cm = CompressionManager(cfg)
        sched = CompressionScheduler(cm, {})
        params = {"fc_in": {"kernel": jnp.asarray(
            np.random.default_rng(0).normal(size=(16, 16)), jnp.float32)}}
        early = sched.compress(params, step=0)["fc_in"]["kernel"]
        late = sched.compress(params, step=10_000)["fc_in"]["kernel"]
        # 2-bit grid has at most 4 distinct levels per row group; 8-bit many
        assert len(np.unique(np.asarray(late))) < len(np.unique(np.asarray(early)))
        err_early = float(jnp.mean((early - params["fc_in"]["kernel"])**2))
        err_late = float(jnp.mean((late - params["fc_in"]["kernel"])**2))
        assert err_late > err_early
