"""Monitor sinks (csv round-trip, one-open-per-flush, MonitorMaster
fan-out + rank-0 guard) and the comms logger's overlapped/exposed split
feeding telemetry trace records."""

import builtins
import csv

import pytest

from deepspeed_tpu.monitor.monitor import Monitor, MonitorMaster, csvMonitor
from deepspeed_tpu.runtime.config import CSVConfig, MonitorConfig
from deepspeed_tpu.utils.comms_logging import CommsLogger


def _csv_cfg(tmp_path, enabled=True):
    return CSVConfig(enabled=enabled, output_path=str(tmp_path),
                     job_name="job")


# ---------------------------------------------------------------------------
# csvMonitor
# ---------------------------------------------------------------------------

def test_csv_round_trip(tmp_path):
    mon = csvMonitor(_csv_cfg(tmp_path))
    mon.write_events([("Train/loss", 2.5, 1), ("Train/lr", 0.1, 1)])
    mon.write_events([("Train/loss", 2.0, 2)])
    with open(tmp_path / "job" / "Train_loss.csv") as f:
        rows = list(csv.reader(f))
    assert rows == [["step", "Train/loss"], ["1", "2.5"], ["2", "2.0"]]
    with open(tmp_path / "job" / "Train_lr.csv") as f:
        assert list(csv.reader(f)) == [["step", "Train/lr"], ["1", "0.1"]]


def test_csv_opens_each_file_once_per_flush(tmp_path, monkeypatch):
    mon = csvMonitor(_csv_cfg(tmp_path))
    opens = []
    real_open = builtins.open

    def counting_open(path, *a, **k):
        opens.append(str(path))
        return real_open(path, *a, **k)

    monkeypatch.setattr(builtins, "open", counting_open)
    # 6 events over 2 tags: exactly 2 opens (was 6 — one per event)
    mon.write_events([("a", float(i), i) for i in range(3)]
                     + [("b", float(i), i) for i in range(3)])
    assert len(opens) == 2


def test_csv_disabled_writes_nothing(tmp_path):
    mon = csvMonitor(_csv_cfg(tmp_path / "off", enabled=False))
    assert not (tmp_path / "off").exists()


# ---------------------------------------------------------------------------
# MonitorMaster
# ---------------------------------------------------------------------------

def _master_cfg(tmp_path, enabled=True):
    return MonitorConfig(csv_monitor=_csv_cfg(tmp_path, enabled=enabled))


def test_master_fans_out_to_enabled_sinks(tmp_path):
    master = MonitorMaster(_master_cfg(tmp_path))
    assert master.enabled

    class Spy(Monitor):
        def __init__(self):
            super().__init__(None)
            self.enabled = True
            self.seen = []

        def write_events(self, events):
            self.seen.extend(events)

    spy = Spy()
    master.monitors.append(spy)
    master.write_events([("t", 1.0, 0)])
    assert spy.seen == [("t", 1.0, 0)]
    assert (tmp_path / "job" / "t.csv").exists()


def test_master_skips_disabled_sinks(tmp_path):
    master = MonitorMaster(_master_cfg(tmp_path))

    class Dead(Monitor):
        def __init__(self):
            super().__init__(None)
            self.enabled = False

        def write_events(self, events):
            raise AssertionError("disabled sink must not be called")

    master.monitors.append(Dead())
    master.write_events([("t", 1.0, 0)])


def test_master_rank0_guard(tmp_path, monkeypatch):
    import jax
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    master = MonitorMaster(_master_cfg(tmp_path))
    # non-zero ranks attach no sinks at all (the reference's rank-0 guard)
    assert master.monitors == [] and not master.enabled


# ---------------------------------------------------------------------------
# comms logger split -> telemetry
# ---------------------------------------------------------------------------

def test_log_summary_overlapped_exposed_split(monkeypatch):
    logger = CommsLogger()
    logger.append("all_gather", 1000, ("data",), overlapped=True, count=3)
    logger.append("reduce_scatter", 500, ("data",), overlapped=False)
    ov, ex = logger.sched_totals()
    assert (ov, ex) == (3000, 500)
    lines = []
    from deepspeed_tpu.utils import comms_logging as cl
    monkeypatch.setattr(cl.logger, "info", lambda msg: lines.append(msg))
    logger.log_all()
    text = "\n".join(lines)
    assert "overlapped" in text and "exposed" in text
    assert "0.86" in text  # 3000/3500


def test_comms_tail_formats_newest_records():
    logger = CommsLogger()
    for i in range(40):
        logger.append("all_gather", 100 + i, ("data",), overlapped=True)
    tail = logger.tail(5)
    assert "all_gather" in tail and "overlapped" in tail
    assert tail.count("\n") == 5  # header + 5 rows
    assert "139" in tail  # newest record present


def test_record_collective_feeds_telemetry_trace():
    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.telemetry import (TelemetryConfig, build_telemetry,
                                         reset_telemetry)
    tele = build_telemetry(TelemetryConfig(
        enabled=True, watchdog={"enabled": False}))
    try:
        dist.record_collective("all_gather", 2048, ("data",),
                               overlapped=True, count=2)
        dist.record_collective("reduce_scatter", 1024, ("data",),
                               overlapped=False)
        (g, s) = [e for e in tele.trace.events() if e["kind"] == "comm"]
        assert g["phase"] == "gather" and g["bytes"] == 2048
        assert s["phase"] == "scatter" and s["overlapped"] is False
        assert tele.metrics.overlap_efficiency() == pytest.approx(4096 / 5120)
    finally:
        reset_telemetry()


def test_comms_log_tail_helper_via_configured_logger():
    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.comm import comm as comm_mod
    logger = CommsLogger()
    old = comm_mod._COMMS_LOGGER
    try:
        dist.configure(comms_logger=logger)
        dist.record_collective("all_reduce", 64, ("data",), overlapped=False)
        assert "all_reduce" in dist.comms_log_tail()
    finally:
        comm_mod._COMMS_LOGGER = old
