"""MoE tests (reference tests/unit/moe/test_moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.moe.sharded_moe import capacity, top_k_gating
from deepspeed_tpu.models import mixtral_model


def test_capacity():
    assert capacity(64, 8, 1.0, 4) == 8
    assert capacity(8, 8, 1.0, 4) == 4  # min_capacity floor


def test_top_k_gating_shapes_and_combine():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (16, 4))
    combine, dispatch, aux, me = top_k_gating(logits, top_k=2, capacity_=8)
    assert combine.shape == (16, 4, 8)
    assert dispatch.shape == (16, 4, 8)
    # with ample capacity every token keeps both choices → weights sum to 1
    np.testing.assert_allclose(np.sum(combine, axis=(1, 2)), 1.0, rtol=1e-5)
    # each (expert, slot) holds at most one token
    assert int(np.max(np.sum(dispatch, axis=0))) <= 1
    assert float(aux) > 0


def test_top_k_gating_respects_capacity():
    # all tokens want expert 0; capacity 2 → only 2 dispatched
    logits = jnp.stack([jnp.array([10.0, 0, 0, 0])] * 8)
    combine, dispatch, _, _ = top_k_gating(logits, top_k=1, capacity_=2)
    assert int(np.sum(dispatch[:, 0, :])) == 2


def test_mixtral_trains_with_expert_parallelism(eight_devices):
    model = mixtral_model("mixtral-tiny", dtype=jnp.float32, remat=False,
                          max_seq_len=32, vocab_size=256)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "topology": {"expert": 4},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, size=(8, 16))}
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert losses[-1] < losses[0], losses
    # expert params sharded over the expert axis
    spec = engine.zero_plan.param_spec_tree()["blocks"]["moe"]["wo"]
    assert "expert" in str(spec)


def test_moe_ep_matches_no_ep(eight_devices):
    """Expert parallelism is a layout change, not an algorithm change."""
    batch = {"input_ids": np.random.default_rng(1).integers(0, 256, size=(8, 16))}
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    m1 = mixtral_model("mixtral-tiny", dtype=jnp.float32, remat=False,
                       max_seq_len=32, vocab_size=256)
    m2 = mixtral_model("mixtral-tiny", dtype=jnp.float32, remat=False,
                       max_seq_len=32, vocab_size=256)
    e1, _, _, _ = deepspeed_tpu.initialize(model=m1, config=dict(cfg), seed=5)
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=m2, config=dict(cfg, topology={"expert": 4}), seed=5)
    l1 = float(e1.forward(batch))
    l2 = float(e2.forward(batch))
    np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_gather_dispatch_matches_dense_einsum():
    """The index-based gather/scatter dispatch must be numerically identical
    to the dense one-hot einsum dispatch (the reference's MOELayer form,
    sharded_moe.py:425) while spending far fewer FLOPs."""
    from deepspeed_tpu.moe.layer import MoE
    moe = MoE(hidden_size=32, intermediate_size=64, num_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32), jnp.float32)
    out, aux = moe(params, x)

    tokens = x.reshape(-1, 32)
    cap = capacity(32, 4, moe.capacity_factor, moe.min_capacity)
    combine, dispatch, aux_ref, _ = top_k_gating(tokens @ params["gate"], 2, cap)
    ein = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), tokens)
    gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", ein, params["wi_gate"]))
    up = jnp.einsum("ech,ehf->ecf", ein, params["wi_up"])
    ref = jnp.einsum("tec,ech->th",
                     combine, jnp.einsum("ecf,efh->ech", gate * up, params["wo"]))
    np.testing.assert_allclose(np.asarray(out.reshape(-1, 32)), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_gather_dispatch_flops_beat_dense():
    """Dispatch is O(t*k*h), not the dense O(t*e*cap*h) — at 4k tokens the
    whole layer must cost several times fewer FLOPs than the one-hot form."""
    from deepspeed_tpu.moe.layer import MoE
    moe = MoE(hidden_size=256, intermediate_size=512, num_experts=8, top_k=2)
    p = moe.init(jax.random.PRNGKey(0), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 512, 256), jnp.float32)
    def flops(compiled):
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one dict per device
            cost = cost[0]
        return cost["flops"]

    new = flops(jax.jit(lambda p, v: moe(p, v)[0]).lower(p, x).compile())

    def dense(p, v):
        t = v.reshape(-1, 256)
        cp = capacity(t.shape[0], 8, moe.capacity_factor, moe.min_capacity)
        cb, dp, _, _ = top_k_gating(t @ p["gate"], 2, cp)
        ein = jnp.einsum("tec,th->ech", dp.astype(v.dtype), t)
        g = jax.nn.silu(jnp.einsum("ech,ehf->ecf", ein, p["wi_gate"]))
        u = jnp.einsum("ech,ehf->ecf", ein, p["wi_up"])
        o = jnp.einsum("tec,ech->th",
                       cb, jnp.einsum("ecf,efh->ech", g * u, p["wo"]))
        return o.reshape(v.shape)

    old = flops(jax.jit(dense).lower(p, x).compile())
    assert new * 3 < old, (new, old)


def test_split_shared_and_expert_params(eight_devices):
    """Expert-sharded leaves split out by spec (reference moe/utils.py:29
    split_params_into_shared_and_expert_params)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.moe.layer import MoE
    from deepspeed_tpu.moe.utils import (expert_param_mask, is_moe_spec,
                                         split_params_into_shared_and_expert_params)

    moe = MoE(hidden_size=16, intermediate_size=32, num_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0), jnp.float32)
    specs = moe.specs()
    assert not is_moe_spec(specs["gate"])
    assert is_moe_spec(specs["wo"])
    shared, expert = split_params_into_shared_and_expert_params(params, specs)
    assert shared["gate"] is not None and expert["gate"] is None
    assert shared["wo"] is None and expert["wo"] is not None
    mask = expert_param_mask(specs)
    assert mask["wo"] is True and mask["gate"] is False
    # the masks drive optax.masked: a transform scoped to expert leaves
    import optax
    tx = optax.masked(optax.scale(0.0), mask)
    grads = jax.tree.map(jnp.ones_like, params)
    state = tx.init(params)
    out, _ = tx.update(grads, state, params)
    assert float(jnp.sum(jnp.abs(out["wo"]))) == 0.0      # scaled to zero
    assert float(jnp.sum(jnp.abs(out["gate"]))) > 0.0     # untouched


def test_moe_split_handles_replicated_none_specs(eight_devices):
    """Replicated leaves carry spec None (add_axes_to_spec convention) —
    they must split as shared, not crash the tree map."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.moe.utils import (expert_param_mask,
                                         split_params_into_shared_and_expert_params)
    params = {"a": np.ones(2), "b": np.ones(2)}
    specs = {"a": None, "b": P("expert", None)}
    assert expert_param_mask(specs) == {"a": False, "b": True}
    shared, expert = split_params_into_shared_and_expert_params(params, specs)
    assert shared["a"] is not None and expert["a"] is None
    assert shared["b"] is None and expert["b"] is not None


class TestChunkedDispatch:
    """ISSUE 9: the overlap planner's scan-carry placement chunks the MoE
    dispatch over the capacity dim (chunk c+1's gather+exchange prefetched
    while chunk c's expert FFN computes). The restructuring must be
    EXACT on the forward (same gather rows, same per-slot contractions)
    and tolerance-tight through the backward scan."""

    def _setup(self):
        from deepspeed_tpu.moe.layer import MoE
        from deepspeed_tpu.runtime import topology as topo_mod
        from deepspeed_tpu.runtime.topology import TopologyConfig

        topo_mod.reset()
        topo = topo_mod.initialize(TopologyConfig(expert=2, data=-1),
                                   force=True)
        moe = MoE(hidden_size=16, intermediate_size=32, num_experts=4,
                  top_k=2)
        params = moe.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16),
                              jnp.float32)
        return topo, moe, params, x

    def test_plan_chunks_forward_exactly(self, eight_devices, monkeypatch):
        from deepspeed_tpu.runtime import overlap_planner as op
        topo, moe, params, x = self._setup()
        assert op.plan_for("moe-dispatch").n_chunks > 1, \
            "committed map should drive a chunked plan"
        with topo.mesh:
            on, aux_on = jax.jit(lambda p, t: moe(p, t))(params, x)
        monkeypatch.setenv("DSTPU_OVERLAP_PLAN", "0")
        with topo.mesh:
            off, aux_off = jax.jit(lambda p, t: moe(p, t))(params, x)
        np.testing.assert_array_equal(np.asarray(on), np.asarray(off))
        np.testing.assert_array_equal(np.asarray(aux_on),
                                      np.asarray(aux_off))

    def test_plan_chunks_grads_match(self, eight_devices, monkeypatch):
        topo, moe, params, x = self._setup()

        def loss(p, t):
            out, aux = moe(p, t)
            return jnp.sum(out * out) + aux

        with topo.mesh:
            g_on = jax.jit(jax.grad(loss))(params, x)
        monkeypatch.setenv("DSTPU_OVERLAP_PLAN", "0")
        with topo.mesh:
            g_off = jax.jit(jax.grad(loss))(params, x)
        for a, b in zip(jax.tree.leaves(g_on), jax.tree.leaves(g_off)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-5)

    def test_top_k_beyond_two_pins_unchunked(self, eight_devices,
                                             monkeypatch):
        """The masked per-chunk combine reassociates a token's k weighted
        terms into chunk order — exact only for k <= 2. top_k=3 must pin
        nc=1 so plan-on stays BITWISE against the unchunked program."""
        from deepspeed_tpu.moe.layer import MoE
        from deepspeed_tpu.runtime import topology as topo_mod
        from deepspeed_tpu.runtime.topology import TopologyConfig

        topo_mod.reset()
        topo = topo_mod.initialize(TopologyConfig(expert=2, data=-1),
                                   force=True)
        moe = MoE(hidden_size=16, intermediate_size=32, num_experts=4,
                  top_k=3)
        params = moe.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16),
                              jnp.float32)
        with topo.mesh:
            on, aux_on = jax.jit(lambda p, t: moe(p, t))(params, x)
        monkeypatch.setenv("DSTPU_OVERLAP_PLAN", "0")
        with topo.mesh:
            off, aux_off = jax.jit(lambda p, t: moe(p, t))(params, x)
        np.testing.assert_array_equal(np.asarray(on), np.asarray(off))
        np.testing.assert_array_equal(np.asarray(aux_on),
                                      np.asarray(aux_off))

    def test_chunk_count_clamps_to_capacity_divisor(self, eight_devices,
                                                    monkeypatch):
        """A capacity the plan's chunk count does not divide must clamp,
        not crash: top_k=1 with a prime-ish capacity."""
        from deepspeed_tpu.moe.layer import MoE
        from deepspeed_tpu.runtime import topology as topo_mod
        from deepspeed_tpu.runtime.topology import TopologyConfig

        topo_mod.reset()
        topo = topo_mod.initialize(TopologyConfig(expert=2, data=-1),
                                   force=True)
        # tokens=20, e=4, k=1, cf=1.0 -> capacity 5 (odd)
        moe = MoE(hidden_size=16, intermediate_size=32, num_experts=4,
                  top_k=1, capacity_factor=1.0, min_capacity=5)
        params = moe.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 5, 16),
                              jnp.float32)
        with topo.mesh:
            out, _ = jax.jit(lambda p, t: moe(p, t))(params, x)
        assert np.all(np.isfinite(np.asarray(out)))
