"""Accelerator abstraction.

Counterpart of the reference ``accelerator/abstract_accelerator.py:12-276``
(~60-method ``DeepSpeedAccelerator`` interface). The reference abstracts over
torch device runtimes (cuda/xpu/npu/...); here the abstraction is over JAX
backends (tpu/cpu/gpu), and several CUDA-specific concepts collapse:

- *streams/events*: XLA schedules async execution itself; stream APIs are
  no-ops kept for interface parity, events map to ``block_until_ready``.
- *memory stats*: ``jax.Device.memory_stats()``.
- *communication backend*: always XLA collectives ("xla") — the reference's
  per-device backend names (nccl/ccl/hccl, ``abstract_accelerator.py:189``)
  choose a wire protocol; XLA picks ICI/DCN itself.
- *op builder dir*: selects the native-kernel implementation directory, the
  hook the reference uses to plug per-device kernels (``op_builder/all_ops.py``).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # -- device APIs --------------------------------------------------------
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    def set_device(self, device_index: int) -> None:  # XLA manages placement
        ...

    def current_device(self) -> int:
        return 0

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        ...

    # -- RNG APIs -----------------------------------------------------------
    def random(self):
        import jax
        return jax.random

    def manual_seed(self, seed: int):
        import jax
        return jax.random.PRNGKey(seed)

    def initial_seed(self) -> int:
        return 0

    def default_generator(self, device_index: int):
        return None

    # -- streams/events (no-op parity layer) --------------------------------
    class _NoopStream:

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def synchronize(self):
            ...

        def wait_stream(self, other):
            ...

    def Stream(self, *args, **kwargs):
        return self._NoopStream()

    def stream(self, stream):
        return self._NoopStream()

    def current_stream(self, device_index: Optional[int] = None):
        return self._NoopStream()

    def default_stream(self, device_index: Optional[int] = None):
        return self._NoopStream()

    def Event(self, **kwargs):
        return None

    # -- memory -------------------------------------------------------------
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        ...

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("peak_bytes_in_use", 0))

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        ...

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        stats = self.memory_stats(device_index)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    def empty_cache(self) -> None:
        ...

    # -- dtype support ------------------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp
        dtypes = [jnp.float32]
        if self.is_bf16_supported():
            dtypes.append(jnp.bfloat16)
        if self.is_fp16_supported():
            dtypes.append(jnp.float16)
        return dtypes

    # -- misc ---------------------------------------------------------------
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    def range_push(self, msg: str):
        """Profiler annotation push (reference accelerator range_push →
        nvtx; here jax.profiler trace annotations via utils.nvtx)."""
        ...

    def range_pop(self):
        ...

    def lazy_call(self, callback):
        callback()

    def communication_backend_version(self) -> str:
        import jax
        return jax.__version__

    # -- op builder hooks (reference abstract_accelerator.py:258) -----------
    @abc.abstractmethod
    def op_builder_dir(self) -> str:
        ...

    def on_accelerator(self, tensor) -> bool:
        return True
