"""Accelerator auto-detection.

Counterpart of ``accelerator/real_accelerator.py:51-186`` (``get_accelerator``
with env override ``DS_ACCELERATOR``). Detection order: tpu → cpu. The env
override here is ``DSTPU_ACCELERATOR``.
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None

ACCELERATOR_ENV = "DSTPU_ACCELERATOR"


def _make(name: str) -> DeepSpeedAccelerator:
    if name == "tpu":
        from .tpu_accelerator import TPU_Accelerator
        return TPU_Accelerator()
    if name == "cpu":
        from .cpu_accelerator import CPU_Accelerator
        return CPU_Accelerator()
    raise ValueError(f"Unknown accelerator '{name}' (expected 'tpu' or 'cpu')")


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is not None:
        return _ACCELERATOR

    override = os.environ.get(ACCELERATOR_ENV)
    if override:
        _ACCELERATOR = _make(override)
        return _ACCELERATOR

    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        platform = "cpu"
    # Any non-cpu jax backend (tpu, or the experimental tunneled 'axon'
    # platform exposing a TPU) is treated as the TPU accelerator.
    _ACCELERATOR = _make("tpu" if platform != "cpu" else "cpu")
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator().is_available()
