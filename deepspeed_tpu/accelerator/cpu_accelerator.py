"""CPU accelerator — used for tests (virtual multi-device CPU meshes) and as
the fallback when no TPU is attached. Mirrors the slot of the reference's
``accelerator/cpu_accelerator.py`` (295 LoC)."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .abstract_accelerator import DeepSpeedAccelerator


class CPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def device(self, device_index: Optional[int] = None):
        import jax
        return jax.local_devices(backend="cpu")[device_index or 0]

    def device_count(self) -> int:
        import jax
        return len(jax.local_devices(backend="cpu"))

    def synchronize(self, device_index: Optional[int] = None) -> None:
        import jax
        jax.effects_barrier()

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        try:
            import psutil  # pragma: no cover - optional
            vm = psutil.virtual_memory()
            return {"bytes_in_use": vm.used, "bytes_limit": vm.total}
        except ImportError:
            if hasattr(os, "sysconf"):
                total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
                return {"bytes_in_use": 0, "bytes_limit": total}
            return {}

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return False

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def is_available(self) -> bool:
        return True

    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.cpu"
