"""TPU accelerator (the primary backend).

Fills the slot the reference fills with ``accelerator/cuda_accelerator.py``
(338 LoC): device discovery, memory stats, dtype capability, comm-backend
name, op-builder directory.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"

    def _devices(self):
        import jax
        return jax.local_devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = self._devices()
        return devs[device_index or 0]

    def device_count(self) -> int:
        return len(self._devices())

    def synchronize(self, device_index: Optional[int] = None) -> None:
        import jax
        jax.effects_barrier()

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        dev = self.device(device_index)
        stats = dev.memory_stats()
        return dict(stats) if stats else {}

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # TPUs compute in bf16; fp16 storage is supported but bf16 preferred.
        return True

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def is_available(self) -> bool:
        try:
            import jax
            return any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            return False

    def range_push(self, msg: str):
        import jax
        self._trace = jax.profiler.TraceAnnotation(msg)
        self._trace.__enter__()

    def range_pop(self):
        if getattr(self, "_trace", None) is not None:
            self._trace.__exit__(None, None, None)
            self._trace = None

    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.tpu"
