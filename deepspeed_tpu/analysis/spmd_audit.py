"""Layer C: post-SPMD sharding & memory audit of compiled entry points.

Layers A and B stop at the source: the AST and the jaxpr. But the failures
that actually cap scale — GSPMD quietly materializing an all-gather around
a mis-sharded matmul, a logits tensor replicated across a sharded mesh, a
remat schedule stacking gathered params into residuals, a donation XLA
silently dropped, a step whose temp bytes crept past the HBM ceiling — only
exist in the *partitioned, optimized* artifact. This layer lowers each
registered :class:`~.entry_points.EntrySpec` with its real mesh/shardings
(via the shared :mod:`.lowering` path telemetry also uses) and audits the
compiled program:

- ``implicit-reshard`` — diff the collective *kinds* between the source
  jaxpr and the partitioned HLO. Kinds implied by the source's own
  collective primitives (psum -> all-reduce, ppermute ->
  collective-permute, ...) are expected, as are the kinds each spec
  *declares* GSPMD may insert (``expected_spmd`` — e.g. the engine step's
  data-parallel grad all-reduce). Anything else is the partitioner fixing
  up a sharding mismatch behind your back, reported with estimated bytes.
- ``replicated-large-intermediate`` — a non-parameter instruction in the
  partitioned program whose (dtype, shape) still equals a large *logical*
  value's full shape means every device materializes the whole tensor:
  replication (or a full re-gather) on a sharded mesh.
- ``remat-residual-full-param`` — the ZeRO schedule invariant "residuals
  must never contain full params" (docs/ZERO_OVERLAP.md), previously
  prose: scan residuals (stacked ``ys``) whose per-iteration slice matches
  a full parameter shape re-materialize the gathered weights once per
  layer. The pipelined prefetch CARRY legitimately holds one gathered
  layer; stacked residuals never may.
- ``dead-donation`` — the module-level ``input_output_alias`` table is
  what XLA *actually* aliased. A donated input absent from it wastes its
  bytes: the caller gave the buffer up and got nothing back. (Layer B's
  ``donation-unusable`` is the aval-matching prediction; this is the
  ground truth.)
- ``memory-budget-regression`` — ``memory_analysis()`` + collective bytes
  checked against the committed shrink-only ``tools/memory_budgets.json``
  (:mod:`.budgets`). Exceeding a budget is a hard finding; so is a
  registered entry point with no budget at all.

Findings carry the ``<spmd:NAME>`` path marker so the baseline machinery
(:mod:`.baseline`) treats the layer independently, exactly like Layer B's
``<trace:NAME>``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

import numpy as np

from .budgets import KIND_PREFIX, TRACKED_FIELDS, tracks_field
from .findings import Finding, SEVERITY_ERROR, SEVERITY_WARNING, sort_findings
from .registry import LAYER_SPMD, Rule, register

SPMD_PREFIX = "<spmd:"

IMPLICIT_RESHARD = register(Rule(
    rule_id="implicit-reshard", layer=LAYER_SPMD, severity=SEVERITY_ERROR,
    description="Partitioner-inserted collective of a kind neither the "
                "source jaxpr nor the entry point's declared contract "
                "expects — GSPMD is resharding behind your back",
    fix_hint="fix the producer/consumer shardings so the operands agree "
             "(with_sharding_constraint or shard_map specs); if the "
             "collective is intended, declare the kind in the spec's "
             "expected_spmd contract"))

REPLICATED_LARGE = register(Rule(
    rule_id="replicated-large-intermediate", layer=LAYER_SPMD,
    severity=SEVERITY_WARNING,
    description="Compiled intermediate materializes a large logical value "
                "at FULL size on every device of a sharded mesh",
    fix_hint="shard the value (with_sharding_constraint over the batch/seq "
             "axes) or compute it blockwise; a fully-replicated tensor "
             "multiplies its HBM cost by the mesh size"))

REMAT_RESIDUAL_PARAM = register(Rule(
    rule_id="remat-residual-full-param", layer=LAYER_SPMD,
    severity=SEVERITY_ERROR,
    description="Scan residuals (stacked ys) hold full-parameter-shaped "
                "tensors — the backward saves gathered weights per layer "
                "instead of re-gathering",
    fix_hint="residuals must hold activations only: recompute the block "
             "from its saved input and re-gather params in the backward "
             "scan (docs/ZERO_OVERLAP.md, layer-granular remat)"))

DEAD_DONATION = register(Rule(
    rule_id="dead-donation", layer=LAYER_SPMD, severity=SEVERITY_WARNING,
    description="Donated input missing from the compiled module's "
                "input_output_alias table — XLA dropped the donation and "
                "the bytes are wasted",
    fix_hint="make the donated buffer flow to a same-shape/dtype/sharding "
             "output, or remove it from donate_argnums; Layer B's "
             "donation-unusable hint shows the aval mismatch"))

MEMORY_BUDGET_REGRESSION = register(Rule(
    rule_id="memory-budget-regression", layer=LAYER_SPMD,
    severity=SEVERITY_ERROR,
    description="Compiled memory/collective bytes exceed the committed "
                "shrink-only budget (tools/memory_budgets.json), or the "
                "entry point has no committed budget",
    fix_hint="shrink the program back under budget; if the growth is "
             "justified, raise the budget BY HAND in "
             "tools/memory_budgets.json and defend it in review"))

SPMD_LOWER_FAILED = register(Rule(
    rule_id="spmd-lower-failed", layer=LAYER_SPMD, severity=SEVERITY_ERROR,
    description="Entry point failed to lower/compile on the audit mesh — "
                "a broken hot path must not pass silently",
    fix_hint="run under JAX_PLATFORMS=cpu with "
             "xla_force_host_platform_device_count>=8 and fix the compile "
             "error"))

#: default thresholds (bytes). Overridable per call; the tiny audit models
#: sit far below both, so HEAD is clean by construction and the rules are
#: exercised by fixtures with lowered thresholds.
REPLICATED_BYTES_DEFAULT = 1 << 26        # 64 MiB full-size intermediate
RESIDUAL_BYTES_DEFAULT = 1 << 14          # 16 KiB per-layer residual slice

# source jaxpr collective primitive -> HLO collective kind(s) it may
# legitimately lower to (reduce_scatter may legalize as all-reduce+slice).
_SRC_PRIM_KINDS: Dict[str, Tuple[str, ...]] = {
    "psum": ("all-reduce",), "psum2": ("all-reduce",),
    "pmin": ("all-reduce",), "pmax": ("all-reduce",),
    "all_gather": ("all-gather",), "pgather": ("all-gather",),
    "reduce_scatter": ("reduce-scatter", "all-reduce"),
    "ppermute": ("collective-permute",),
    "pshuffle": ("collective-permute",),
    "all_to_all": ("all-to-all",),
}

_HLO_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                         "collective-permute", "all-to-all")

# HLO shape element type -> numpy dtype string (for byte math and for
# matching logical avals against compiled instruction shapes)
_HLO_DTYPES = {
    "pred": "bool", "s8": "int8", "s16": "int16", "s32": "int32",
    "s64": "int64", "u8": "uint8", "u16": "uint16", "u32": "uint32",
    "u64": "uint64", "f16": "float16", "bf16": "bfloat16", "f32": "float32",
    "f64": "float64", "f8e4m3fn": "float8_e4m3fn", "f8e5m2": "float8_e5m2",
    "c64": "complex64", "c128": "complex128",
}
_NP_TO_HLO = {v: k for k, v in _HLO_DTYPES.items()}

# one HLO instruction: `%name = <shape> opcode(...)` where <shape> is a
# typed array `f32[8,16]{1,0}` or a tuple of them.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\([^)]*\)|[a-z][\w]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"([a-z][a-z0-9\-]*)\(", re.MULTILINE)
_ARRAY_SHAPE_RE = re.compile(r"([a-z][\w]*)\[([0-9,]*)\]")


def _dtype_itemsize(hlo_dtype: str) -> int:
    np_name = _HLO_DTYPES.get(hlo_dtype)
    if np_name is None:
        return 0
    if np_name.startswith("float8"):
        return 1
    if np_name == "bfloat16":
        return 2
    try:
        return np.dtype(np_name).itemsize
    except TypeError:
        return 0


def _parse_shapes(shape_text: str) -> List[Tuple[str, Tuple[int, ...], int]]:
    """'(f32[8,16]{1,0}, s32[4])' -> [(dtype, dims, bytes), ...]."""
    out = []
    for m in _ARRAY_SHAPE_RE.finditer(shape_text):
        dtype, dims_text = m.group(1), m.group(2)
        if dtype not in _HLO_DTYPES:
            continue  # token/opaque types
        dims = tuple(int(d) for d in dims_text.split(",")) if dims_text else ()
        n = int(np.prod(dims, dtype=np.int64)) if dims else 1
        out.append((dtype, dims, n * _dtype_itemsize(dtype)))
    return out


def iter_hlo_instructions(hlo_text: str) -> Iterable[
        Tuple[str, List[Tuple[str, Tuple[int, ...], int]]]]:
    """Yield ``(opcode, [(dtype, shape, bytes), ...])`` for every
    instruction in the optimized module (fused computations included —
    their bodies are listed like any other computation)."""
    for m in _INSTR_RE.finditer(hlo_text):
        yield m.group(2), _parse_shapes(m.group(1))


# a collective instruction with its operand list: opcode + everything up
# to (at least) the operand parenthesis; the blob is cut at the matching
# close paren by _operand_blob so trailing attributes never leak shapes in
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(?:\([^)]*\)|[a-z][\w]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"([a-z][a-z0-9\-]*)\((.*)$", re.MULTILINE)


def _operand_blob(rest: str) -> str:
    """``rest`` starts just past the opcode's '('; return the operand text
    up to the MATCHING ')' (tuple-shaped operands nest parens)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def collective_summary(hlo_text: str) -> Dict[str, Tuple[int, int]]:
    """-> {kind: (count, total_operand_bytes)} over the partitioned program.

    Bytes are OPERAND-side (each launch's input payload) — the same
    convention as Layer D's per-launch ``moved_bytes`` and the runtime
    ledger's ``record_collective``, and the honest wire estimate under
    quantized transport: a reduce-scatter's input is what travels the
    links (its result is the 1/n shard), and an int8 all-to-all's input
    is the 1-byte payload + scale sideband. (Before ISSUE 8 this charged
    RESULT bytes, which inverted the reduce-scatter vs all-to-all
    comparison and hid the quantization win.) Async pairs count once
    (``-start`` carries the operands, ``-done`` is skipped)."""
    out: Dict[str, Tuple[int, int]] = {}
    for m in _COLL_RE.finditer(hlo_text):
        opcode = m.group(1)
        kind = opcode[:-6] if opcode.endswith("-start") else opcode
        if opcode.endswith("-done") or kind not in _HLO_COLLECTIVE_KINDS:
            continue
        shapes = _parse_shapes(_operand_blob(m.group(2)))
        count, total = out.get(kind, (0, 0))
        out[kind] = (count + 1, total + sum(b for _, _, b in shapes))
    return out


def parse_alias_params(hlo_text: str) -> Optional[Set[int]]:
    """Parameter numbers in the module's ``input_output_alias`` table —
    the donations XLA actually honored. None when the module declares no
    alias table at all (nothing was donated / backend elided it)."""
    marker = "input_output_alias={"
    start = hlo_text.find(marker)
    if start < 0:
        return None
    # the table nests braces ({0}: (0, {}, may-alias)) — scan for balance
    depth, i = 1, start + len(marker)
    while i < len(hlo_text) and depth:
        depth += {"{": 1, "}": -1}.get(hlo_text[i], 0)
        i += 1
    table = hlo_text[start + len(marker):i - 1]
    return {int(p) for p in re.findall(r":\s*\((\d+)\s*,", table)}


# ---------------------------------------------------------------------------
# jaxpr-side helpers
# ---------------------------------------------------------------------------

def _walk_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            items = v if isinstance(v, (tuple, list)) else (v,)
            for item in items:
                core = getattr(item, "jaxpr", None)
                if core is not None and hasattr(core, "eqns"):
                    yield from _walk_jaxprs(core)
                elif hasattr(item, "eqns") and hasattr(item, "invars"):
                    yield from _walk_jaxprs(item)


def source_collective_kinds(closed_jaxpr) -> Set[str]:
    """HLO collective kinds the source jaxpr's own primitives lower to."""
    kinds: Set[str] = set()
    for jaxpr in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            for k in _SRC_PRIM_KINDS.get(eqn.primitive.name, ()):
                kinds.add(k)
    return kinds


def _aval_nbytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 2 if "bfloat16" in str(dtype) else 0
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return n * itemsize


def _hlo_key(aval) -> Optional[Tuple[str, Tuple[int, ...]]]:
    hlo_dtype = _NP_TO_HLO.get(str(getattr(aval, "dtype", "")))
    if hlo_dtype is None:
        return None
    return (hlo_dtype, tuple(getattr(aval, "shape", ())))


def large_logical_avals(closed_jaxpr, threshold: int
                        ) -> Dict[Tuple[str, Tuple[int, ...]], int]:
    """Full (logical) shapes of source values >= threshold bytes, keyed the
    way compiled HLO spells shapes."""
    out: Dict[Tuple[str, Tuple[int, ...]], int] = {}
    for jaxpr in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is None:
                    continue
                nbytes = _aval_nbytes(aval)
                if nbytes < threshold:
                    continue
                key = _hlo_key(aval)
                if key is not None:
                    out[key] = nbytes
    return out


def scan_param_residuals(closed_jaxpr,
                         param_shapes: FrozenSet[Tuple[Tuple[int, ...], str]],
                         min_bytes: int) -> List[Tuple[Tuple[int, ...], str, int]]:
    """Stacked scan outputs (ys) whose per-iteration slice matches a full
    parameter shape: ``[(stacked_shape, dtype, stacked_bytes), ...]``.
    Carries are exempt — the pipelined schedule's prefetch carry holds one
    gathered layer by design; residuals are what persists per layer."""
    hits = []
    for jaxpr in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "scan":
                continue
            num_carry = eqn.params.get("num_carry", 0)
            for var in eqn.outvars[num_carry:]:
                aval = getattr(var, "aval", None)
                shape = tuple(getattr(aval, "shape", ()))
                if len(shape) < 1:
                    continue
                slice_key = (shape[1:], str(getattr(aval, "dtype", "")))
                if slice_key in param_shapes:
                    nbytes = _aval_nbytes(aval)
                    if nbytes >= min_bytes:
                        hits.append((shape, slice_key[1], nbytes))
    return hits


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpmdReport:
    """Per-entry numbers the budget flow and ``--json`` consume."""
    name: str
    memory: Dict[str, float]
    collective_counts: Dict[str, int]
    collective_bytes: int
    collective_bytes_by_kind: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    def budget_fields(self) -> Dict[str, int]:
        out = {f: int(self.memory[f]) for f in TRACKED_FIELDS
               if f in self.memory}
        out["collective_bytes"] = int(self.collective_bytes)
        # per-kind shrink-only budgets (ISSUE 8): the static pin of the
        # quantized-transport byte reduction, one key per HLO kind
        for kind, nbytes in sorted(self.collective_bytes_by_kind.items()):
            out[KIND_PREFIX + kind] = int(nbytes)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "memory": self.memory,
                "collective_counts": self.collective_counts,
                "collective_bytes": self.collective_bytes,
                "collective_bytes_by_kind": dict(
                    sorted(self.collective_bytes_by_kind.items()))}


def _finding(rule: Rule, name: str, message: str) -> Finding:
    return Finding(rule_id=rule.rule_id, path=f"{SPMD_PREFIX}{name}>",
                   line=0, severity=rule.severity, message=message,
                   fix_hint=rule.fix_hint)


def audit_artifact(spec, artifact, *,
                   replicated_bytes: int = REPLICATED_BYTES_DEFAULT,
                   residual_bytes: int = RESIDUAL_BYTES_DEFAULT,
                   ) -> Tuple[List[Finding], SpmdReport]:
    """All compiled-layer rules except the budget check (which needs the
    committed file — :func:`check_budgets`)."""
    import jax

    name = spec.name
    findings: List[Finding] = []
    hlo = artifact.hlo_text

    # --- implicit-reshard -------------------------------------------------
    expected = source_collective_kinds(artifact.closed_jaxpr) | set(
        spec.expected_spmd)
    summary = collective_summary(hlo)
    for kind in sorted(set(summary) - expected):
        count, nbytes = summary[kind]
        findings.append(_finding(
            IMPLICIT_RESHARD, name,
            f"partitioner inserted {count} {kind} instruction(s) "
            f"(~{nbytes} B/device result bytes); source jaxpr implies "
            f"{sorted(expected) or 'no collectives'}"))

    # --- replicated-large-intermediate ------------------------------------
    if jax.device_count() > 1:
        large = large_logical_avals(artifact.closed_jaxpr, replicated_bytes)
        if large:
            seen: Dict[Tuple[str, Tuple[int, ...]], int] = {}
            for opcode, shapes in iter_hlo_instructions(hlo):
                if opcode in ("parameter", "constant"):
                    continue
                for dtype, dims, _ in shapes:
                    key = (dtype, dims)
                    if key in large:
                        seen[key] = seen.get(key, 0) + 1
            for (dtype, dims), count in sorted(seen.items()):
                findings.append(_finding(
                    REPLICATED_LARGE, name,
                    f"{dtype}{list(dims)} ({large[(dtype, dims)]} B) appears "
                    f"at FULL logical size in {count} compiled "
                    f"instruction(s) on a {jax.device_count()}-device mesh "
                    f"— replicated, not sharded"))

    # --- remat-residual-full-param ----------------------------------------
    if spec.param_shapes:
        for shape, dtype, nbytes in scan_param_residuals(
                artifact.closed_jaxpr, spec.param_shapes, residual_bytes):
            findings.append(_finding(
                REMAT_RESIDUAL_PARAM, name,
                f"scan residual stacks full-parameter slices: "
                f"{dtype}{list(shape)} ({nbytes} B) — gathered weights "
                f"saved once per layer"))

    # --- dead-donation ----------------------------------------------------
    offsets = np.cumsum([0] + list(artifact.arg_leaf_counts))
    donated: List[int] = []
    for argnum in artifact.donate_argnums:
        donated.extend(range(offsets[argnum], offsets[argnum + 1]))
    if donated:
        aliased = parse_alias_params(hlo)
        kept = _kept_param_numbers(artifact)
        invars = artifact.closed_jaxpr.jaxpr.invars
        for i in donated:
            param_no = kept.get(i) if kept is not None else i
            if param_no is None:
                # the executable pruned the arg entirely: donated AND unused
                ok = False
            else:
                ok = aliased is not None and param_no in aliased
            if not ok:
                nbytes = _aval_nbytes(invars[i].aval) if i < len(invars) else 0
                findings.append(_finding(
                    DEAD_DONATION, name,
                    f"donated input leaf #{i} was not aliased by XLA "
                    f"({nbytes} B wasted — buffer surrendered for "
                    "nothing)"))

    report = SpmdReport(
        name=name, memory=artifact.memory() or {},
        collective_counts={k: c for k, (c, _) in summary.items()},
        collective_bytes=sum(b for _, b in summary.values()),
        collective_bytes_by_kind={k: b for k, (_, b) in summary.items()})
    return findings, report


def _kept_param_numbers(artifact) -> Optional[Dict[int, Optional[int]]]:
    """flat invar index -> compiled parameter number, accounting for XLA
    dropping unused args (kept_var_idx). None = mapping unavailable
    (assume identity)."""
    kept = None
    for path in ("_executable", "runtime_executable"):
        ex = getattr(artifact.compiled, path, None)
        if ex is not None and hasattr(ex, "_kept_var_idx"):
            kept = sorted(ex._kept_var_idx)
            break
    if kept is None:
        return None
    mapping: Dict[int, Optional[int]] = {}
    pos = {idx: n for n, idx in enumerate(kept)}
    n_invars = len(artifact.closed_jaxpr.jaxpr.invars)
    for i in range(n_invars):
        mapping[i] = pos.get(i)
    return mapping


def check_budgets(name: str, report: SpmdReport,
                  budgets: Optional[Dict]) -> List[Finding]:
    """Diff one entry's report against the committed budgets (already
    loaded + env-matched by the caller; pass None to skip)."""
    if budgets is None:
        return []
    entry = budgets.get("budgets", {}).get(name)
    if entry is None:
        return [_finding(
            MEMORY_BUDGET_REGRESSION, name,
            "no committed budget in tools/memory_budgets.json — run "
            "`dstpu lint --update-budgets` and commit the file")]
    findings = []
    current = report.budget_fields()
    for field in sorted(current):
        if not tracks_field(field, TRACKED_FIELDS):
            continue
        if field not in entry:
            if field.startswith(KIND_PREFIX) and current[field] > 0:
                # a collective KIND with no committed budget appeared —
                # the per-kind analogue of a new-entry missing budget
                findings.append(_finding(
                    MEMORY_BUDGET_REGRESSION, name,
                    f"{field} {current[field]} B has no committed per-kind "
                    f"budget — a new collective kind entered the compiled "
                    f"program (hand-add it with review, or fix the "
                    f"sharding)"))
            continue
        if current[field] > entry[field]:
            findings.append(_finding(
                MEMORY_BUDGET_REGRESSION, name,
                f"{field} {current[field]} B exceeds committed budget "
                f"{entry[field]} B (+{current[field] - entry[field]} B)"))
    return findings


def audit_spec_spmd(spec, budgets: Optional[Dict] = None, **thresholds
                    ) -> Tuple[List[Finding], Optional[SpmdReport]]:
    """Lower+compile one spec and run every Layer-C rule. A spec that
    cannot compile is itself a hard finding."""
    from .lowering import lower_entry

    try:
        with spec.mesh_ctx():
            artifact = lower_entry(spec.fn, spec.args,
                                   donate_argnums=spec.donate_argnums,
                                   jit_kwargs=spec.jit_kwargs,
                                   name=spec.name)
    except Exception as e:  # noqa: BLE001 — any compile failure is a finding
        return [_finding(SPMD_LOWER_FAILED, spec.name,
                         f"failed to lower/compile: "
                         f"{type(e).__name__}: {e}")], None
    findings, report = audit_artifact(spec, artifact, **thresholds)
    findings += check_budgets(spec.name, report, budgets)
    return findings, report


def iter_compiled_entries(names=None):
    """Build + lower/compile each registered entry point ONCE, yielding
    ``(name, spec, artifact, error)`` — ``error`` is a message string when
    the spec could not even build or compile (spec/artifact None as
    appropriate). Layers C and D both consume this, so a combined run
    pays one compile per entry, not one per layer."""
    from deepspeed_tpu.runtime import topology as topo_mod

    from .entry_points import SPEC_BUILDERS, build_spec
    from .lowering import lower_entry

    if names:
        unknown = sorted(set(names) - set(SPEC_BUILDERS))
        if unknown:
            raise ValueError(
                f"unknown entry point(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(SPEC_BUILDERS))})")
    try:
        for name in SPEC_BUILDERS:
            if names and name not in names:
                continue
            try:
                spec = build_spec(name)  # resets the global topology first
            except Exception as e:  # noqa: BLE001
                yield (name, None, None,
                       f"entry point failed to build: "
                       f"{type(e).__name__}: {e}")
                continue
            try:
                with spec.mesh_ctx():
                    artifact = lower_entry(
                        spec.fn, spec.args,
                        donate_argnums=spec.donate_argnums,
                        jit_kwargs=spec.jit_kwargs, name=spec.name)
            except Exception as e:  # noqa: BLE001
                yield (name, spec, None,
                       f"failed to lower/compile: {type(e).__name__}: {e}")
                continue
            yield name, spec, artifact, None
    finally:
        topo_mod.reset()


def audit_spmd_entry_points(names=None, budgets: Optional[Dict] = None,
                            entries=None,
                            ) -> Tuple[List[Finding], Dict[str, SpmdReport]]:
    """Run Layer C over the registered entry points (default: all).

    ``budgets`` is the loaded+env-matched budgets dict (None skips budget
    checks — the CLI and gate pass it when the environment matches the
    committed mesh). ``entries`` is an optional pre-materialized
    :func:`iter_compiled_entries` result — a combined ``--spmd
    --schedule`` run compiles once and feeds both layers. Returns
    findings plus per-entry reports for ``--update-budgets`` /
    ``--json``."""
    findings: List[Finding] = []
    reports: Dict[str, SpmdReport] = {}
    for name, spec, artifact, error in (
            entries if entries is not None else iter_compiled_entries(names)):
        if error is not None:
            findings.append(_finding(SPMD_LOWER_FAILED, name, error))
            continue
        f, report = audit_artifact(spec, artifact)
        f += check_budgets(name, report, budgets)
        findings.extend(f)
        reports[name] = report
    return sort_findings(findings), reports
