"""``dstpu lint`` — CLI driver for the static analysis suite.

Exit codes: 0 = clean against the baseline, 1 = new findings (or stale
baseline entries), 2 = usage error. The fast AST layer runs on every
invocation; the jaxpr layer (``--jaxpr``) traces the real engine/ZeRO/MoE/
sequence/serving entry points and needs a working JAX (use
``JAX_PLATFORMS=cpu`` off-accelerator); the compiled layer (``--spmd``)
additionally lowers+compiles every entry point with its real
mesh/shardings and audits the post-SPMD artifact against
``tools/memory_budgets.json`` (run it with
``--xla_force_host_platform_device_count=8`` so the budgets' audit mesh
matches). ``--update-budgets`` re-pins the budgets file — downward only.
The schedule layer (``--schedule``) walks each compiled entry point's
instruction schedule, classifies every collective overlapped/exposed/
serialized against ``tools/exposure_budgets.json`` and refreshes the
per-entry placement maps in ``tools/collective_maps/``.
``--json`` emits the findings, the baseline diff, and (when ``--spmd`` /
``--schedule`` ran) the per-entry memory/collective/schedule reports as
machine-readable JSON.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
from typing import List

from . import ast_rules
from .baseline import (by_layer, default_baseline_path, diff_against_baseline,
                       load_baseline, prune_unknown_entries, write_baseline)
from .findings import Finding, SEVERITY_ERROR, sort_findings
from .registry import all_rules, is_known


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
    return sorted(set(out))


def _relpath(path: str) -> str:
    try:
        rel = os.path.relpath(path, os.path.dirname(_package_root()))
        return rel if not rel.startswith("..") else path
    except ValueError:
        return path


def run_ast_layer(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in collect_py_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(ast_rules.lint_source(_relpath(path), source))
    return sort_findings(findings)


def run_jaxpr_layer(entry_names=None) -> List[Finding]:
    from .entry_points import audit_entry_points
    return audit_entry_points(entry_names)


def _budget_gate_note(budgets, path, what, update_flag):
    """-> env_matches(budgets), with a visible note when the gate is
    skipped — a silently-skipped budget check looks like a pass."""
    from .budgets import env_matches

    checked = env_matches(budgets)
    if budgets is None:
        print(f"dstpu lint: no {what} file at {path} — {what} checks "
              f"skipped (run {update_flag} to create it)", file=sys.stderr)
    elif not checked:
        import jax
        print(f"dstpu lint: skipping {what} checks — {jax.device_count()} "
              f"live device(s) vs committed audit mesh of "
              f"{budgets['mesh_devices']}", file=sys.stderr)
    return checked


def run_spmd_layer(entry_names=None, budgets_path=None, entries=None):
    """-> (findings, reports, budgets_checked: bool). Budget comparison is
    skipped (with a visible note) when the live device count differs from
    the committed audit mesh — bytes from a different partitioning are not
    comparable. ``entries`` is an optional shared compile pass (a combined
    ``--spmd --schedule`` run lowers each entry once for both layers)."""
    from .budgets import default_budgets_path, load_budgets
    from .spmd_audit import audit_spmd_entry_points

    path = budgets_path or default_budgets_path()
    budgets = load_budgets(path)
    checked = _budget_gate_note(budgets, path, "budget", "--update-budgets")
    findings, reports = audit_spmd_entry_points(
        entry_names, budgets=budgets if checked else None, entries=entries)
    return findings, reports, checked


def run_schedule_layer(entry_names=None, exposure_path=None, entries=None):
    """Layer D (``--schedule``): compile each entry point and walk its
    schedule. -> (findings, reports, exposure_checked: bool). Same
    mesh-match semantics (and shared-``entries`` contract) as the
    Layer-C budgets."""
    from .schedule_audit import (audit_schedule_entry_points,
                                 default_exposure_path,
                                 load_exposure_budgets)

    path = exposure_path or default_exposure_path()
    exposure = load_exposure_budgets(path)
    checked = _budget_gate_note(exposure, path, "exposure budget",
                                "--schedule --update-budgets")
    findings, reports = audit_schedule_entry_points(
        entry_names, exposure=exposure if checked else None, entries=entries)
    return findings, reports, checked


def run_feasibility_layer(entry_names=None, exposure_path=None, entries=None):
    """Layer E (``--feasibility``): the static config-feasibility oracle
    over the HEAD default configs -> (findings, verdicts). Exposure
    rejections use the committed budgets under the same mesh-match
    semantics as Layer D; ``entries`` is the shared compile pass."""
    from .budgets import env_matches
    from .feasibility import evaluate_entries
    from .schedule_audit import default_exposure_path, load_exposure_budgets

    path = exposure_path or default_exposure_path()
    exposure = load_exposure_budgets(path)
    if exposure is not None and not env_matches(exposure):
        exposure = None
    return evaluate_entries(entry_names, entries=entries, exposure=exposure)


def render(findings: List[Finding], fix_hints: bool) -> str:
    lines = []
    for f in findings:
        lines.append(f"{f.location}: [{f.rule_id}] {f.severity}: {f.message}")
        if fix_hints and f.fix_hint:
            lines.append(f"    hint: {f.fix_hint}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dstpu lint",
        description="TPU-graph invariant linter (AST layer + jaxpr audit)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the "
                             "deepspeed_tpu package)")
    parser.add_argument("--jaxpr", action="store_true",
                        help="also run the jaxpr entry-point audits "
                             "(traces engine/ZeRO/MoE/sequence/serving "
                             "paths)")
    parser.add_argument("--spmd", action="store_true",
                        help="also run the Layer-C compiled-artifact audits "
                             "(lowers+compiles every entry point with its "
                             "real mesh/shardings; checks "
                             "tools/memory_budgets.json)")
    parser.add_argument("--schedule", action="store_true",
                        help="also run the Layer-D HLO-schedule overlap "
                             "audits (classifies every compiled collective "
                             "overlapped/exposed/serialized, checks "
                             "tools/exposure_budgets.json, and refreshes "
                             "tools/collective_maps/<entry>.json)")
    parser.add_argument("--feasibility", action="store_true",
                        help="also run the Layer-E config-feasibility "
                             "audits (the `dstpu plan` oracle over the "
                             "HEAD default configs: HBM fit, compile, "
                             "exposure, donation)")
    parser.add_argument("--hosts", action="store_true",
                        help="also run the Layer-F cross-host divergence "
                             "and host-seam concurrency audits (static "
                             "thread/lock graph + rank-conditional "
                             "collective scan; pure AST, no jax)")
    parser.add_argument("--all", action="store_true", dest="all_layers",
                        help="run every layer (A-F: AST + --jaxpr + --spmd "
                             "+ --schedule + --feasibility + --hosts) off "
                             "one shared compile per entry")
    parser.add_argument("--maps-dir", default=None,
                        help="directory for the per-entry collective maps "
                             "a --schedule run emits (default: "
                             "tools/collective_maps)")
    parser.add_argument("--entry", action="append", default=None,
                        help="restrict --jaxpr/--spmd to the named entry "
                             "points")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: tools/lint_baseline.json)")
    parser.add_argument("--budgets", default=None,
                        help="budgets JSON (default: "
                             "tools/memory_budgets.json)")
    parser.add_argument("--exposure-budgets", default=None,
                        dest="exposure_budgets",
                        help="exposure budgets JSON for --schedule "
                             "(default: tools/exposure_budgets.json)")
    parser.add_argument("--update-budgets", action="store_true",
                        help="run --spmd and re-pin the budgets file — "
                             "DOWNWARD only; exceeded budgets stay put and "
                             "keep failing until fixed or hand-raised. "
                             "With --schedule, additionally re-pins "
                             "tools/exposure_budgets.json (same contract)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding; ignore the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current findings")
    parser.add_argument("--fix-hints", action="store_true",
                        help="print a fix hint under every finding, plus the "
                             "rule reference")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.as_json:
        # stdout must be pure JSON: the audits boot real engines whose
        # framework logger writes INFO to stdout — reroute it for the run
        with _framework_logs_to_stderr():
            return _main(args)
    return _main(args)


@contextlib.contextmanager
def _framework_logs_to_stderr():
    import logging

    from ..utils.logging import logger as fw_logger

    # the handler may hold a stale reference to a replaced sys.stdout
    # (test capture, IDE shells) — anything not already on stderr moves
    moved = [(h, h.stream) for h in fw_logger.handlers
             if isinstance(h, logging.StreamHandler)
             and getattr(h, "stream", None) is not sys.stderr]
    for h, _ in moved:
        h.setStream(sys.stderr)
    try:
        yield
    finally:
        for h, old in moved:
            h.setStream(old)


def _main(args) -> int:

    if args.list_rules:
        from . import trace_harness  # noqa: F401 — registers Layer-B rules
        from . import spmd_audit  # noqa: F401 — registers Layer-C rules
        from . import schedule_audit  # noqa: F401 — registers Layer-D rules
        from . import feasibility  # noqa: F401 — registers Layer-E rules
        from . import host_audit  # noqa: F401 — registers Layer-F rules
        for rule in all_rules():
            print(f"{rule.rule_id:26} [{rule.layer}/{rule.severity}] "
                  f"{rule.description}")
        return 0

    paths = args.paths or [_package_root()]
    for p in paths:
        if not os.path.exists(p):
            print(f"dstpu lint: no such path: {p}", file=sys.stderr)
            return 2

    if args.all_layers:
        args.jaxpr = True
        args.spmd = True
        args.schedule = True
        args.feasibility = True
        args.hosts = True
    run_spmd = args.spmd or args.update_budgets
    run_sched = args.schedule
    run_feas = args.feasibility
    if run_spmd or run_sched:
        # fail fast on budget-file problems BEFORE the ~40s compile audit:
        # a typo'd explicit --budgets path must not silently disable the
        # gate, and --update-budgets on the wrong mesh must not waste the
        # whole run only to refuse at the end
        from .budgets import default_budgets_path, load_budgets
        from .schedule_audit import (default_exposure_path,
                                     load_exposure_budgets)
        budgets_path = args.budgets or default_budgets_path()
        exposure_path = args.exposure_budgets or default_exposure_path()
        for given, what in ((args.budgets if run_spmd else None, "budgets"),
                            (args.exposure_budgets if run_sched else None,
                             "exposure budgets")):
            if given and not args.update_budgets and not os.path.exists(given):
                print(f"dstpu lint: no such {what} file: {given}",
                      file=sys.stderr)
                return 2
        if args.update_budgets:
            import jax
            pinned = [(budgets_path, load_budgets(budgets_path))]
            if run_sched:
                pinned.append((exposure_path,
                               load_exposure_budgets(exposure_path)))
            for path, old in pinned:
                if old is not None \
                        and old["mesh_devices"] != jax.device_count():
                    # numbers from a different partitioning are not
                    # comparable — refusing beats silently replacing the
                    # committed audit mesh
                    print(f"dstpu lint: refusing --update-budgets: "
                          f"{path} was taken on {old['mesh_devices']} "
                          f"devices, this environment has "
                          f"{jax.device_count()}", file=sys.stderr)
                    return 2

    findings = run_ast_layer(paths)
    if args.hosts:
        from .host_audit import run_host_layer
        findings += run_host_layer(paths if args.paths else None)
    spmd_reports = {}
    sched_reports = {}
    feas_verdicts = {}
    budgets_checked = False
    exposure_checked = False
    try:
        if args.jaxpr:
            findings += run_jaxpr_layer(args.entry)
        shared_entries = None
        if sum((run_spmd, run_sched, run_feas)) >= 2:
            # one lower+compile pass feeds every compiled layer (C, D, E)
            from .spmd_audit import iter_compiled_entries
            shared_entries = list(iter_compiled_entries(args.entry))
        if run_spmd:
            spmd_findings, spmd_reports, budgets_checked = run_spmd_layer(
                args.entry, args.budgets, entries=shared_entries)
            findings += spmd_findings
        if run_sched:
            sched_findings, sched_reports, exposure_checked = \
                run_schedule_layer(args.entry, args.exposure_budgets,
                                   entries=shared_entries)
            findings += sched_findings
        if run_feas:
            feas_findings, feas_verdicts = run_feasibility_layer(
                args.entry, args.exposure_budgets, entries=shared_entries)
            findings += feas_findings
    except ValueError as e:
        print(f"dstpu lint: {e}", file=sys.stderr)
        return 2
    findings = sort_findings(findings)

    collective_maps = {}
    if run_sched:
        # every --schedule run refreshes the committed placement maps —
        # the declarative artifact the auto-overlap planner consumes.
        # Same mesh discipline as the budgets: placement from a different
        # partitioning must not overwrite the committed audit-mesh maps
        # (a missing exposure file means bootstrap — write freely).
        from .budgets import env_matches
        from .schedule_audit import (default_maps_dir, load_exposure_budgets,
                                     write_collective_map)
        import jax
        exposure_on_disk = load_exposure_budgets(exposure_path)
        maps_ok = exposure_on_disk is None or env_matches(exposure_on_disk)
        maps_dir = args.maps_dir or default_maps_dir()
        for name, report in sched_reports.items():
            if maps_ok:
                write_collective_map(maps_dir, report, jax.device_count())
            collective_maps[name] = report.to_map(jax.device_count())
        if sched_reports and maps_ok:
            print(f"refreshed {len(sched_reports)} collective map(s) in "
                  f"{maps_dir}", file=sys.stderr)
        elif sched_reports:
            print(f"dstpu lint: NOT refreshing collective maps — "
                  f"{jax.device_count()} live device(s) vs committed audit "
                  f"mesh of {exposure_on_disk['mesh_devices']}",
                  file=sys.stderr)

    if args.update_budgets:
        from .budgets import shrink_budgets, write_budgets
        import jax
        old = load_budgets(budgets_path)
        reports = {k: r.budget_fields() for k, r in spmd_reports.items()}
        merged, exceeded = shrink_budgets(old, reports, jax.device_count())
        write_budgets(budgets_path, merged)
        print(f"wrote {len(merged['budgets'])} budget entr"
              f"{'y' if len(merged['budgets']) == 1 else 'ies'} to "
              f"{budgets_path} (downward only)",
              # --json keeps stdout pure JSON
              file=sys.stderr if args.as_json else sys.stdout)
        for key in exceeded:
            print(f"  NOT raised (exceeds committed budget): {key}",
                  file=sys.stderr)
        if run_sched:
            from .schedule_audit import (shrink_exposure_budgets,
                                         write_exposure_budgets)
            old_exp = load_exposure_budgets(exposure_path)
            exp_reports = {k: r.budget_fields()
                           for k, r in sched_reports.items()}
            merged_exp, exceeded_exp = shrink_exposure_budgets(
                old_exp, exp_reports, jax.device_count())
            write_exposure_budgets(exposure_path, merged_exp)
            print(f"wrote {len(merged_exp['budgets'])} exposure budget "
                  f"entr{'y' if len(merged_exp['budgets']) == 1 else 'ies'} "
                  f"to {exposure_path} (downward only)",
                  file=sys.stderr if args.as_json else sys.stdout)
            for key in exceeded_exp:
                print(f"  NOT raised (exceeds committed exposure budget): "
                      f"{key}", file=sys.stderr)

    ran_layers = {"ast"} | ({"jaxpr"} if args.jaxpr else set()) \
        | ({"spmd"} if run_spmd else set()) \
        | ({"schedule"} if run_sched else set()) \
        | ({"feasibility"} if run_feas else set()) \
        | ({"hosts"} if args.hosts else set())
    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        # A partial run must not erase grandfathered entries for the
        # layers that did not run: carry their baseline slices over —
        # except entries naming specs that no longer exist in the
        # registry, which are pruned with a warning (they could otherwise
        # never fire and never go stale: grandfathered forever).
        from .baseline import entry_name
        kept_layers = by_layer(load_baseline(baseline_path))
        kept = [f for layer, fs in kept_layers.items()
                if layer not in ran_layers for f in fs]
        if args.entry:
            # an --entry-restricted run only re-audited THOSE entries:
            # the ran layers' baseline slices for every other entry point
            # carry over too, or a partial regenerate would erase them
            audited = set(args.entry)
            kept += [f for layer, fs in kept_layers.items()
                     if layer in ran_layers and layer != "ast"
                     for f in fs if entry_name(f.path) not in audited]
        pruned = []
        if any(entry_name(f.path) is not None for f in kept):
            # lazy: only an entry-marker carryover needs the registry —
            # a pure AST regenerate must stay jax-import-free
            from .entry_points import SPEC_BUILDERS
            kept, pruned = prune_unknown_entries(kept, SPEC_BUILDERS)
        for f in pruned:
            print(f"dstpu lint: pruning stale baseline entry for unknown "
                  f"entry point: {f.path} [{f.rule_id}]", file=sys.stderr)
        write_baseline(baseline_path, findings + kept)
        print(f"wrote {len(findings) + len(kept)} finding(s) to "
              f"{baseline_path}"
              + (f" ({len(kept)} entr"
                 f"{'y' if len(kept) == 1 else 'ies'} from layers that did "
                 "not run carried over)" if kept else ""))
        return 0

    baseline = [] if args.no_baseline else load_baseline(baseline_path)
    # a layer that did not run has baseline entries that are neither
    # matchable nor stale here
    baseline = [f for layer, fs in by_layer(baseline).items()
                if layer in ran_layers for f in fs]
    new, stale = diff_against_baseline(findings, baseline)

    if args.as_json:
        import json
        payload = {"findings": [f.to_dict() for f in findings],
                   "new": [f.to_dict() for f in new],
                   "stale_baseline": [f.to_dict() for f in stale]}
        if run_spmd:
            payload["spmd_reports"] = {k: r.to_dict()
                                       for k, r in spmd_reports.items()}
            payload["budgets_checked"] = budgets_checked
        if run_sched:
            payload["schedule_reports"] = {k: r.summary()
                                           for k, r in sched_reports.items()}
            payload["collective_maps"] = collective_maps
            payload["exposure_checked"] = exposure_checked
        if run_feas:
            payload["feasibility_verdicts"] = {
                k: v.to_dict() for k, v in feas_verdicts.items()}
        print(json.dumps(payload, indent=2))
    else:
        report = new if not args.no_baseline else findings
        if report:
            print(render(report, args.fix_hints))
        if stale:
            print(f"\n{len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (finding no longer "
                  "fires) — regenerate with --write-baseline:")
            for f in stale:
                print(f"  {f.path}: [{f.rule_id}] {f.message}")
        grandfathered = len(findings) - len(new)
        print(f"\ndstpu lint: {len(findings)} finding(s), "
              f"{grandfathered} grandfathered, {len(new)} new, "
              f"{len(stale)} stale baseline")
        if args.fix_hints and new:
            seen = sorted({f.rule_id for f in new if is_known(f.rule_id)})
            if seen:
                print("\nrule reference:")
                for rid in seen:
                    from .registry import get
                    rule = get(rid)
                    print(f"  {rid}: {rule.description}")

    has_blocking = bool(new) or bool(stale)
    return 1 if has_blocking else 0


if __name__ == "__main__":
    sys.exit(main())
