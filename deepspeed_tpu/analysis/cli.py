"""``dstpu lint`` — CLI driver for the static analysis suite.

Exit codes: 0 = clean against the baseline, 1 = new findings (or stale
baseline entries), 2 = usage error. The fast AST layer runs on every
invocation; the jaxpr layer (``--jaxpr``) traces the real engine/ZeRO/MoE/
sequence entry points and needs a working JAX (use ``JAX_PLATFORMS=cpu``
off-accelerator).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from . import ast_rules
from .baseline import (default_baseline_path, diff_against_baseline,
                       load_baseline, split_layers, write_baseline)
from .findings import Finding, SEVERITY_ERROR, sort_findings
from .registry import all_rules, is_known


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
    return sorted(set(out))


def _relpath(path: str) -> str:
    try:
        rel = os.path.relpath(path, os.path.dirname(_package_root()))
        return rel if not rel.startswith("..") else path
    except ValueError:
        return path


def run_ast_layer(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in collect_py_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(ast_rules.lint_source(_relpath(path), source))
    return sort_findings(findings)


def run_jaxpr_layer(entry_names=None) -> List[Finding]:
    from .entry_points import audit_entry_points
    return audit_entry_points(entry_names)


def render(findings: List[Finding], fix_hints: bool) -> str:
    lines = []
    for f in findings:
        lines.append(f"{f.location}: [{f.rule_id}] {f.severity}: {f.message}")
        if fix_hints and f.fix_hint:
            lines.append(f"    hint: {f.fix_hint}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dstpu lint",
        description="TPU-graph invariant linter (AST layer + jaxpr audit)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the "
                             "deepspeed_tpu package)")
    parser.add_argument("--jaxpr", action="store_true",
                        help="also run the jaxpr entry-point audits "
                             "(traces engine/ZeRO/MoE/sequence paths)")
    parser.add_argument("--entry", action="append", default=None,
                        help="restrict --jaxpr to the named entry points")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: tools/lint_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding; ignore the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current findings")
    parser.add_argument("--fix-hints", action="store_true",
                        help="print a fix hint under every finding, plus the "
                             "rule reference")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from . import trace_harness  # noqa: F401 — registers Layer-B rules
        for rule in all_rules():
            print(f"{rule.rule_id:26} [{rule.layer}/{rule.severity}] "
                  f"{rule.description}")
        return 0

    paths = args.paths or [_package_root()]
    for p in paths:
        if not os.path.exists(p):
            print(f"dstpu lint: no such path: {p}", file=sys.stderr)
            return 2

    findings = run_ast_layer(paths)
    if args.jaxpr:
        try:
            findings += run_jaxpr_layer(args.entry)
        except ValueError as e:
            print(f"dstpu lint: {e}", file=sys.stderr)
            return 2
    findings = sort_findings(findings)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        # An AST-only run must not erase grandfathered jaxpr entries: keep
        # the baseline slice for the layer that did not run.
        kept = ([] if args.jaxpr
                else split_layers(load_baseline(baseline_path))[1])
        write_baseline(baseline_path, findings + kept)
        print(f"wrote {len(findings) + len(kept)} finding(s) to "
              f"{baseline_path}"
              + (f" ({len(kept)} jaxpr entr"
                 f"{'y' if len(kept) == 1 else 'ies'} carried over)"
                 if kept else ""))
        return 0

    baseline = [] if args.no_baseline else load_baseline(baseline_path)
    if not args.jaxpr:
        # Layer B did not run; its baseline entries are neither matchable
        # nor stale here.
        baseline = split_layers(baseline)[0]
    new, stale = diff_against_baseline(findings, baseline)

    if args.as_json:
        import json
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "new": [f.to_dict() for f in new],
                          "stale_baseline": [f.to_dict() for f in stale]},
                         indent=2))
    else:
        report = new if not args.no_baseline else findings
        if report:
            print(render(report, args.fix_hints))
        if stale:
            print(f"\n{len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (finding no longer "
                  "fires) — regenerate with --write-baseline:")
            for f in stale:
                print(f"  {f.path}: [{f.rule_id}] {f.message}")
        grandfathered = len(findings) - len(new)
        print(f"\ndstpu lint: {len(findings)} finding(s), "
              f"{grandfathered} grandfathered, {len(new)} new, "
              f"{len(stale)} stale baseline")
        if args.fix_hints and new:
            seen = sorted({f.rule_id for f in new if is_known(f.rule_id)})
            if seen:
                print("\nrule reference:")
                for rid in seen:
                    from .registry import get
                    rule = get(rid)
                    print(f"  {rid}: {rule.description}")

    has_blocking = bool(new) or bool(stale)
    return 1 if has_blocking else 0


if __name__ == "__main__":
    sys.exit(main())
