"""Layer E — the static config-feasibility oracle (``dstpu plan``).

The missing piece under ROADMAP item 3 (the autotuner "brain"): take a
*candidate* config — mesh/bucket/remat/moment-dtype/transport/batch knobs
layered over a base engine config — and decide **feasible / infeasible**
plus a predicted static cost WITHOUT running a step. The reference
DeepSpeed's ``autotuning/`` layer answers the same question dynamically
with trial runs; here everything the trial would reveal is already in the
compiled artifact the other lint layers audit:

- **HBM fit** — XLA's ``memory_analysis`` of the partitioned program
  (the Layer-C budget quantity) against the per-device HBM of the
  accelerator table below (``DSTPU_HBM_BYTES`` overrides).
- **Partitionability** — the compile itself: a candidate whose shapes
  don't partition on the declared mesh dies in ``lower().compile()``,
  which is the ``spmd-lower-failed`` rejection.
- **Exposure** — the Layer-D schedule walk's exposed collective bytes
  against the committed shrink-only budget: a candidate that un-hides
  communication the repo already proved hideable is rejected statically.
- **Donation** — the Layer-C ``dead-donation`` alias check: a candidate
  that makes XLA drop a donated buffer pays double-residency at peak,
  which on a full-size model IS an OOM the memory analysis of the tiny
  audit program can't see.

One compile serves Layers C, D and E (``iter_compiled_entries`` /
``analysis/lowering.py``); candidate synthesis re-parameterizes the
EXISTING registry builders via
:func:`~.entry_points.candidate_overrides`, and candidate validation is
the SAME :class:`~deepspeed_tpu.runtime.config.DeepSpeedConfig` pass the
engine build runs (``validate_candidate_config``), so `plan` can never
accept a config the engine would reject (or vice versa).

Cost-model semantics (and their audit-mesh limits): ``cost`` is
*flop-equivalents* — ``predicted_step_flops`` (the Layer-D
:class:`~.schedule_audit.FlopModel` over the entry computation, the same
dot/conv costing MFU keys on) **plus** ``exposed_bytes /
bytes_per_flop`` (the Layer-D roofline ratio converting exposed
communication into the compute a device could have done while moving
those bytes). It ranks candidates; it is NOT a wall-clock claim —
numbers taken on the 8-device CPU audit mesh rank *schedule structure*,
and transfer to a real pod only insofar as the partitioning transfers
(the same caveat the committed budgets carry; docs/STATIC_ANALYSIS.md).

Artifacts: ``tools/feasibility/<entry>.json`` — the HEAD default
config's verdict per entry, deterministic (no wall times, no
trace-cache-dependent transport summary), refreshed by
``dstpu plan --update-artifacts`` and drift-checked by the tier-1
artifact-freshness gate. The future autotuner controller consumes these
as its warm-start priors.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .findings import Finding, SEVERITY_ERROR
from .registry import LAYER_FEASIBILITY, Rule, register

PLAN_PREFIX = "<plan:"

CONFIG_INFEASIBLE = register(Rule(
    rule_id="config-infeasible", layer=LAYER_FEASIBILITY,
    severity=SEVERITY_ERROR,
    description="The entry point's config is statically infeasible: HBM "
                "overflow vs the device budget, unpartitionable shapes "
                "(compile failure), exposed collective bytes over the "
                "committed budget, or a dead donation on a donated buffer",
    fix_hint="run `dstpu plan --entry <name>` for the full verdict; shrink "
             "the candidate (batch/remat/moment dtypes), fix the sharding, "
             "or re-overlap the exposed collective"))

FEASIBILITY_AUDIT_FAILED = register(Rule(
    rule_id="feasibility-audit-failed", layer=LAYER_FEASIBILITY,
    severity=SEVERITY_ERROR,
    description="The feasibility oracle itself could not produce a verdict "
                "for the entry point (spec build crashed before lowering)",
    fix_hint="run the audit under JAX_PLATFORMS=cpu with "
             "xla_force_host_platform_device_count>=8 and fix the build "
             "error"))

#: per-device HBM by accelerator (marketing capacities, same stated-
#: convention contract as telemetry's ``PEAK_FLOPS_BY_KIND`` and Layer D's
#: ``BYTES_PER_FLOP_BY_KIND``). Keyed by substrings of
#: ``jax.devices()[0].device_kind`` lowercased. The "cpu" row is the
#: audit-mesh stand-in: generous enough that HEAD's tiny audit programs
#: always fit — real rejections on the audit mesh come from
#: ``DSTPU_HBM_BYTES`` pinning a deliberate ceiling.
HBM_BYTES_BY_KIND = (
    ("v6e", int(32e9)),
    ("v5p", int(95e9)),
    ("v5e", int(16e9)),
    ("v5 lite", int(16e9)),
    ("v4", int(32e9)),
    ("v3", int(16e9)),
    ("v2", int(8e9)),
    ("cpu", int(16e9)),
)


def hbm_bytes_per_device(device_kind: Optional[str] = None) -> int:
    """Per-device HBM budget from the accelerator table;
    ``DSTPU_HBM_BYTES`` (per-device, in bytes) overrides."""
    env = os.environ.get("DSTPU_HBM_BYTES")
    if env:
        return int(float(env))
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - no backend
            return int(16e9)
    kind = (device_kind or "").lower()
    for key, nbytes in HBM_BYTES_BY_KIND:
        if key in kind:
            return nbytes
    return int(16e9)


# ---------------------------------------------------------------------------
# candidates
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space: overrides layered on a registry
    builder's HEAD defaults. ``config`` deep-merges into the engine
    config (nested dict form), ``model`` overrides tiny-model kwargs
    (e.g. ``remat``), ``batch`` the representative batch shape
    (``size``/``seq``). ``label`` is display-only."""
    label: str = "candidate"
    config: Tuple[Tuple[str, Any], ...] = ()     # frozen as sorted items
    model: Tuple[Tuple[str, Any], ...] = ()
    batch: Tuple[Tuple[str, Any], ...] = ()

    @staticmethod
    def from_overrides(overrides: Dict[str, Any],
                       label: Optional[str] = None) -> "Candidate":
        """Build from FLAT dotted overrides: ``model.*`` keys go to the
        model namespace, ``batch.*`` to the batch shape, everything else
        is a (dotted) engine-config path."""
        from deepspeed_tpu.runtime.config import expand_dotted

        config: Dict[str, Any] = {}
        model: Dict[str, Any] = {}
        batch: Dict[str, Any] = {}
        for key, value in overrides.items():
            if key.startswith("model."):
                model[key[len("model."):]] = value
            elif key.startswith("batch."):
                batch[key[len("batch."):]] = value
            else:
                config[key] = value
        lbl = label if label is not None else ",".join(
            f"{k}={json.dumps(v)}" for k, v in sorted(overrides.items()))
        return Candidate(
            label=lbl or "candidate",
            config=_freeze(expand_dotted(config)),
            model=_freeze(model), batch=_freeze(batch))

    def namespaces(self) -> Tuple[Dict, Dict, Dict]:
        return _thaw(self.config), _thaw(self.model), _thaw(self.batch)

    def to_dict(self) -> Dict[str, Any]:
        config, model, batch = self.namespaces()
        return {"label": self.label, "config": config, "model": model,
                "batch": batch}


def _freeze(d: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    return tuple(sorted(
        (k, _freeze(v) if isinstance(v, dict) else v) for k, v in d.items()))


def _thaw(items) -> Dict[str, Any]:
    return {k: _thaw(v) if isinstance(v, tuple) else v for k, v in items}


# ---------------------------------------------------------------------------
# the verdict
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FeasibilityVerdict:
    """What `dstpu plan` answers for one (entry, candidate): go / no-go
    with every rejection named, plus the static numbers the cost model
    and the autotuner controller rank on."""
    entry: str
    feasible: bool
    reasons: List[str]                     # empty iff feasible
    mesh_devices: int
    device_kind: str
    candidate: Optional[Dict[str, Any]]    # None = HEAD defaults
    hbm_bytes: int                         # peak per-device program bytes
    hbm_budget_bytes: int
    memory: Dict[str, int]                 # raw memory_analysis fields
    collective_bytes: int
    collective_bytes_by_kind: Dict[str, int]
    exposed_bytes: int
    overlapped_bytes: int
    exposure_budget_bytes: Optional[int]   # None = no committed budget
    predicted_step_flops: int
    bytes_per_flop: float
    cost: float                            # flop-equivalents (see module doc)
    tokens_per_step: Optional[int]
    cost_per_token: Optional[float]
    transport_plan_summary: Optional[Dict[str, int]]
    compile_wall: Optional[float]          # seconds; NOT in the artifact

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_artifact(self) -> Dict[str, Any]:
        """The deterministic committed form: everything except wall
        times (compile_wall varies run to run) and the transport
        summary (the ledger records NOTHING on a trace-cache hit, so
        its numbers depend on process history — see
        ``trace_runtime_ledger``). The artifact must diff clean when
        nothing changed."""
        out = self.to_dict()
        out.pop("compile_wall")
        out.pop("transport_plan_summary")
        return out


def _infeasible(entry: str, reasons: List[str], *, mesh_devices: int,
                device_kind: str, candidate: Optional[Candidate],
                compile_wall: Optional[float] = None) -> FeasibilityVerdict:
    """A verdict for a candidate that never produced an artifact (compile
    failure, invalid config, or statically pruned)."""
    return FeasibilityVerdict(
        entry=entry, feasible=False, reasons=list(reasons),
        mesh_devices=mesh_devices, device_kind=device_kind,
        candidate=candidate.to_dict() if candidate else None,
        hbm_bytes=0, hbm_budget_bytes=hbm_bytes_per_device(device_kind),
        memory={}, collective_bytes=0, collective_bytes_by_kind={},
        exposed_bytes=0, overlapped_bytes=0, exposure_budget_bytes=None,
        predicted_step_flops=0, bytes_per_flop=0.0, cost=float("inf"),
        tokens_per_step=None, cost_per_token=None,
        transport_plan_summary=None, compile_wall=compile_wall)


def _device_env() -> Tuple[int, str]:
    import jax
    return jax.device_count(), jax.devices()[0].device_kind


def transport_summary(spec) -> Optional[Dict[str, int]]:
    """Trace the transport-planner ledger for ``spec`` and summarize it
    (overlapped/exposed split plus logical-vs-wire bytes). MUST run
    BEFORE the spec is lowered — jax caches traces, so tracing after a
    compile records nothing; for the same reason the summary depends on
    process history (an entry whose fn was already traced records
    empty), which is why it is advisory display output and excluded
    from the committed artifact. None when the trace itself fails."""
    from .schedule_audit import trace_runtime_ledger

    try:
        ledger = trace_runtime_ledger(spec)
        transport = dict(ledger.split(wire=True))
        transport["logical_bytes"] = sum(
            r["bytes"] * r["count"] for r in ledger.records)
        transport["wire_bytes"] = sum(
            r["wire_bytes"] * r["count"] for r in ledger.records)
        transport["records"] = len(ledger.records)
        return transport
    except Exception:  # noqa: BLE001 — advisory
        return None


def evaluate_compiled(spec, artifact, *, exposure: Optional[Dict] = None,
                      candidate: Optional[Candidate] = None,
                      compile_wall: Optional[float] = None,
                      transport: Optional[Dict[str, int]] = None,
                      tokens_per_step: Optional[int] = None,
                      ) -> FeasibilityVerdict:
    """The Layer-E verdict over an already-compiled artifact — the shared
    half ``dstpu lint --feasibility`` reuses off the one compile pass
    Layers C and D consume."""
    from .schedule_audit import (ScheduleReport, bytes_per_flop,
                                 entry_computation, FlopModel,
                                 parse_hlo_computations, walk_schedule)
    from .spmd_audit import audit_artifact

    mesh_devices, device_kind = _device_env()
    reasons: List[str] = []

    # Layer C's machinery: collectives by kind + the dead-donation check
    spmd_findings, spmd_report = audit_artifact(spec, artifact)
    dead = [f for f in spmd_findings if f.rule_id == "dead-donation"]
    if dead:
        reasons.append(
            f"dead-donation: {len(dead)} donated buffer(s) not aliased by "
            "XLA — double residency at peak on the full-size model")

    # HBM fit: peak per-device program bytes vs the accelerator budget.
    # arguments + outputs + temps, minus the donated bytes XLA aliased
    # (an aliased output shares its argument's buffer).
    mem = {k: int(v) for k, v in (spmd_report.memory or {}).items()}
    hbm_bytes = (mem.get("argument_size_in_bytes", 0)
                 + mem.get("output_size_in_bytes", 0)
                 + mem.get("temp_size_in_bytes", 0)
                 - mem.get("alias_size_in_bytes", 0))
    hbm_budget = hbm_bytes_per_device(device_kind)
    if hbm_bytes > hbm_budget:
        reasons.append(
            f"hbm-overflow: {hbm_bytes} B/device > {hbm_budget} B "
            f"({device_kind} budget)")

    # Layer D's machinery: schedule walk -> exposed split + the FLOP model
    ratio = bytes_per_flop(device_kind)
    comps = parse_hlo_computations(artifact.hlo_text)
    records, _ = walk_schedule(comps, ratio)
    sched = ScheduleReport(name=spec.name, records=records,
                           bytes_per_flop=ratio)
    exposed = int(sched.exposed_bytes)
    exposure_budget: Optional[int] = None
    if exposure is not None:
        entry_budget = exposure.get("budgets", {}).get(spec.name)
        if entry_budget is not None:
            exposure_budget = int(entry_budget.get("exposed_bytes", 0))
            if exposed > exposure_budget:
                reasons.append(
                    f"exposure-over-budget: {exposed} B exposed > committed "
                    f"{exposure_budget} B — the candidate un-hides "
                    "communication the committed schedule overlaps")

    entry_comp = entry_computation(comps)
    flops = (FlopModel(comps).computation_flops(entry_comp.name)
             if entry_comp is not None else 0)
    cost = float(flops) + (exposed / ratio if ratio > 0 else 0.0)

    return FeasibilityVerdict(
        entry=spec.name, feasible=not reasons, reasons=reasons,
        mesh_devices=mesh_devices, device_kind=device_kind,
        candidate=candidate.to_dict() if candidate else None,
        hbm_bytes=int(hbm_bytes), hbm_budget_bytes=int(hbm_budget),
        memory=mem, collective_bytes=int(spmd_report.collective_bytes),
        collective_bytes_by_kind=dict(
            sorted(spmd_report.collective_bytes_by_kind.items())),
        exposed_bytes=exposed,
        overlapped_bytes=int(sched.overlapped_bytes),
        exposure_budget_bytes=exposure_budget,
        predicted_step_flops=int(flops), bytes_per_flop=ratio, cost=cost,
        tokens_per_step=tokens_per_step,
        cost_per_token=(cost / tokens_per_step
                        if tokens_per_step else None),
        transport_plan_summary=transport, compile_wall=compile_wall)


def _candidate_tokens(name: str, candidate: Optional[Candidate]
                      ) -> Optional[int]:
    """tokens/step for the entries whose representative batch the
    candidate controls (the ``_batch`` defaults otherwise); None for the
    fixed toy programs where tokens/step is not a meaningful unit."""
    from .entry_points import CANDIDATE_ENTRY_POINTS

    if name not in CANDIDATE_ENTRY_POINTS:
        return None
    batch = dict(candidate.namespaces()[2]) if candidate else {}
    return int(batch.get("size", 8)) * int(batch.get("seq", 16))


def evaluate_entry(name: str, candidate: Optional[Candidate] = None,
                   exposure: Optional[Dict] = None) -> FeasibilityVerdict:
    """Build, lower and compile one entry (optionally re-parameterized by
    ``candidate``) and return its verdict. This is the standalone
    `dstpu plan` path: it additionally traces the transport-planner
    ledger (BEFORE compiling — jax caches traces, so tracing after the
    compile would record nothing) for the wire-vs-logical byte summary."""
    from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                              validate_candidate_config)

    from .entry_points import (CANDIDATE_ENTRY_POINTS, build_spec,
                               candidate_overrides)
    from .lowering import lower_entry

    mesh_devices, device_kind = _device_env()
    config, model, batch = (candidate.namespaces() if candidate
                            else ({}, {}, {}))
    if candidate and name not in CANDIDATE_ENTRY_POINTS:
        return _infeasible(
            name, [f"candidate-unsupported: {name!r} builds a fixed toy "
                   f"program; candidates re-parameterize "
                   f"{', '.join(CANDIDATE_ENTRY_POINTS)}"],
            mesh_devices=mesh_devices, device_kind=device_kind,
            candidate=candidate)
    if config:
        # the engine-build validation pass, paid BEFORE any compile
        try:
            validate_candidate_config({}, config)
        except DeepSpeedConfigError as e:
            return _infeasible(
                name, [f"config-invalid: {e}"], mesh_devices=mesh_devices,
                device_kind=device_kind, candidate=candidate)

    tokens = _candidate_tokens(name, candidate)
    start = time.monotonic()
    with candidate_overrides(config=config, model=model, batch=batch):
        try:
            spec = build_spec(name)
        except DeepSpeedConfigError as e:
            # the engine-build validation (mesh-aware batch math etc.)
            # rejecting the merged config — a config error, not a
            # partitioning one
            return _infeasible(
                name, [f"config-invalid: {e}"], mesh_devices=mesh_devices,
                device_kind=device_kind, candidate=candidate,
                compile_wall=time.monotonic() - start)
        except Exception as e:  # noqa: BLE001 — any build failure rejects
            return _infeasible(
                name, [f"spmd-lower-failed: entry point failed to build: "
                       f"{type(e).__name__}: {e}"],
                mesh_devices=mesh_devices, device_kind=device_kind,
                candidate=candidate,
                compile_wall=time.monotonic() - start)
        transport = transport_summary(spec)
        try:
            with spec.mesh_ctx():
                artifact = lower_entry(
                    spec.fn, spec.args, donate_argnums=spec.donate_argnums,
                    jit_kwargs=spec.jit_kwargs, name=spec.name)
        except Exception as e:  # noqa: BLE001 — unpartitionable = rejected
            return _infeasible(
                name, [f"spmd-lower-failed: {type(e).__name__}: {e}"],
                mesh_devices=mesh_devices, device_kind=device_kind,
                candidate=candidate,
                compile_wall=time.monotonic() - start)
    wall = time.monotonic() - start
    return evaluate_compiled(spec, artifact, exposure=exposure,
                             candidate=candidate, compile_wall=wall,
                             transport=transport, tokens_per_step=tokens)


def evaluate_entries(names=None, entries=None, exposure: Optional[Dict] = None
                     ) -> Tuple[List[Finding], Dict[str, FeasibilityVerdict]]:
    """Layer E over the registered entry points at HEAD defaults — the
    ``dstpu lint --feasibility`` integration. ``entries`` is an optional
    pre-materialized :func:`~.spmd_audit.iter_compiled_entries` result
    (the shared compile pass); verdicts taken this way omit the
    transport summary (the specs were already traced, so a ledger trace
    would record nothing — `dstpu plan` owns the full artifact)."""
    from .spmd_audit import iter_compiled_entries

    findings: List[Finding] = []
    verdicts: Dict[str, FeasibilityVerdict] = {}
    mesh_devices, device_kind = _device_env()
    for name, spec, artifact, error in (
            entries if entries is not None else iter_compiled_entries(names)):
        if error is not None:
            verdict = _infeasible(
                name, [f"spmd-lower-failed: {error}"],
                mesh_devices=mesh_devices, device_kind=device_kind,
                candidate=None)
        else:
            verdict = evaluate_compiled(
                spec, artifact, exposure=exposure,
                tokens_per_step=_candidate_tokens(name, None))
        verdicts[name] = verdict
        if not verdict.feasible:
            findings.append(Finding(
                rule_id=CONFIG_INFEASIBLE.rule_id,
                path=f"{PLAN_PREFIX}{name}>", line=0,
                severity=CONFIG_INFEASIBLE.severity,
                message="HEAD config statically infeasible: "
                        + "; ".join(verdict.reasons),
                fix_hint=CONFIG_INFEASIBLE.fix_hint))
    return findings, verdicts


# ---------------------------------------------------------------------------
# committed artifacts
# ---------------------------------------------------------------------------

def default_plans_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "feasibility")


def write_verdict_artifact(plans_dir: str, verdict: FeasibilityVerdict
                           ) -> str:
    os.makedirs(plans_dir, exist_ok=True)
    path = os.path.join(plans_dir, f"{verdict.entry}.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(verdict.to_artifact(), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def load_verdict_artifact(plans_dir: str, name: str) -> Optional[Dict]:
    path = os.path.join(plans_dir, f"{name}.json")
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# grid sweeps
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SweepResult:
    """One grid point's outcome. ``compiled`` False = statically pruned
    (the verdict's infeasibility is implied by a dominated axis value, no
    compile paid)."""
    candidate: Candidate
    verdict: FeasibilityVerdict
    compiled: bool

    def to_dict(self) -> Dict[str, Any]:
        return {"candidate": self.candidate.to_dict(),
                "verdict": self.verdict.to_dict(),
                "compiled": self.compiled}


def load_grid(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        grid = json.load(fh)
    if "axes" not in grid or not isinstance(grid["axes"], dict):
        raise ValueError(f"grid file {path} has no 'axes' object")
    for axis in grid.get("monotone", []):
        if axis not in grid["axes"]:
            raise ValueError(f"monotone axis {axis!r} not in 'axes'")
    return grid


def expand_grid(grid: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The cartesian product of ``axes`` (flat dotted-override keys ->
    value lists), merged over the optional flat ``base`` overrides.
    Deterministic order: axes sorted by name, values in listed order."""
    axes = grid["axes"]
    names = sorted(axes)
    base = grid.get("base", {})
    points = []
    for combo in itertools.product(*(range(len(axes[n])) for n in names)):
        overrides = dict(base)
        overrides.update({n: axes[n][i] for n, i in zip(names, combo)})
        points.append(overrides)
    return points


def sweep(grid: Dict[str, Any], exposure: Optional[Dict] = None,
          log=None) -> List[SweepResult]:
    """Evaluate every grid point, pruning statically: when a point is
    rejected for **hbm-overflow**, every point identical on the other
    axes with a LATER value on a declared ``monotone`` axis (value lists
    are ordered by increasing memory) is infeasible by domination and is
    never compiled. Only the overflow rejection prunes — a compile
    failure or exposure regression at one point says nothing about its
    neighbors."""
    entry = grid.get("entry", "engine-train-step")
    axes = grid["axes"]
    names = sorted(axes)
    monotone = [a for a in grid.get("monotone", []) if a in axes]
    # per monotone axis: {values-of-the-other-axes -> smallest index that
    # overflowed}
    dominated: Dict[str, Dict[Tuple, int]] = {a: {} for a in monotone}
    results: List[SweepResult] = []
    for overrides in expand_grid(grid):
        candidate = Candidate.from_overrides(overrides)
        pruned_by = None
        for axis in monotone:
            rest = tuple((n, json.dumps(overrides[n], sort_keys=True))
                         for n in names if n != axis)
            floor = dominated[axis].get(rest)
            if floor is not None and axes[axis].index(overrides[axis]) >= floor:
                pruned_by = (axis, axes[axis][floor])
                break
        if pruned_by is not None:
            axis, value = pruned_by
            verdict = _infeasible(
                entry, [f"hbm-overflow: pruned without compiling — "
                        f"dominated by {axis}={json.dumps(value)}, which "
                        f"already overflowed with the same remaining axes"],
                mesh_devices=_device_env()[0], device_kind=_device_env()[1],
                candidate=candidate)
            results.append(SweepResult(candidate, verdict, compiled=False))
            continue
        verdict = evaluate_entry(entry, candidate, exposure=exposure)
        results.append(SweepResult(candidate, verdict, compiled=True))
        if any(r.startswith("hbm-overflow") for r in verdict.reasons):
            for axis in monotone:
                rest = tuple((n, json.dumps(overrides[n], sort_keys=True))
                             for n in names if n != axis)
                idx = axes[axis].index(overrides[axis])
                prev = dominated[axis].get(rest)
                if prev is None or idx < prev:
                    dominated[axis][rest] = idx
    compiled = sum(1 for r in results if r.compiled)
    if log is not None:
        log(f"dstpu plan: compiled {compiled} of {len(results)} grid "
            f"point(s) ({len(results) - compiled} pruned statically)")
    return results


def rank_survivors(results: List[SweepResult]) -> List[SweepResult]:
    """Feasible points, cheapest first (cost-per-token when defined, raw
    flop-equivalent cost otherwise; candidate label breaks ties so the
    order is total and deterministic)."""
    survivors = [r for r in results if r.verdict.feasible]
    key = lambda r: (r.verdict.cost_per_token
                     if r.verdict.cost_per_token is not None
                     else r.verdict.cost, r.candidate.label)
    return sorted(survivors, key=key)


# ---------------------------------------------------------------------------
# model mode — static prediction off committed artifacts (dstpu tune)
# ---------------------------------------------------------------------------

#: the representative tokens/step of the HEAD-default audit batch
#: (``entry_points._batch``: size 8 x seq 16) — the denominator the
#: static model scales candidate geometry against.
_HEAD_TOKENS_PER_STEP = 8 * 16


def predict_from_artifact(artifact: Dict[str, Any], candidate: Candidate,
                          entry: Optional[str] = None) -> FeasibilityVerdict:
    """A verdict WITHOUT a compile: scale the committed HEAD verdict
    artifact (``tools/feasibility/<entry>.json``) by the candidate's
    token geometry. The model is deliberately coarse — FLOPs, exposed
    and collective bytes scale linearly with tokens/step; HBM splits
    into a constant resident part (arguments: params + optimizer state)
    and a token-proportional part (outputs + temps, net of aliasing) —
    and is blind to every non-batch knob. That is exactly the fidelity
    the tune pipeline needs from its zero-cost stage: rank and prune
    before paying compiles, then let measured trials (and the
    calibration record) correct it. Deterministic given (artifact,
    candidate, DSTPU_HBM_BYTES)."""
    name = entry or str(artifact.get("entry", "engine-train-step"))
    batch = dict(candidate.namespaces()[2])
    tokens = int(batch.get("size", 8)) * int(batch.get("seq", 16))
    base_tokens = int(artifact.get("tokens_per_step")
                      or _HEAD_TOKENS_PER_STEP)
    r = tokens / float(base_tokens)

    mem = {k: int(v) for k, v in (artifact.get("memory") or {}).items()}
    resident = mem.get("argument_size_in_bytes", 0)
    activ = (mem.get("output_size_in_bytes", 0)
             + mem.get("temp_size_in_bytes", 0)
             - mem.get("alias_size_in_bytes", 0))
    hbm = int(resident + activ * r)
    budget = hbm_bytes_per_device(artifact.get("device_kind"))

    flops = int(int(artifact.get("predicted_step_flops") or 0) * r)
    exposed = int(int(artifact.get("exposed_bytes") or 0) * r)
    overlapped = int(int(artifact.get("overlapped_bytes") or 0) * r)
    coll = int(int(artifact.get("collective_bytes") or 0) * r)
    ratio = float(artifact.get("bytes_per_flop") or 0.0)
    cost = float(flops) + (exposed / ratio if ratio > 0 else 0.0)

    reasons: List[str] = []
    if hbm > budget:
        reasons.append(
            f"hbm-overflow: predicted {hbm} B/device > {budget} B "
            f"(static model over the committed {name} artifact)")
    return FeasibilityVerdict(
        entry=name, feasible=not reasons, reasons=reasons,
        mesh_devices=int(artifact.get("mesh_devices") or 0),
        device_kind=str(artifact.get("device_kind") or ""),
        candidate=candidate.to_dict(),
        hbm_bytes=hbm, hbm_budget_bytes=int(budget),
        memory={}, collective_bytes=coll, collective_bytes_by_kind={},
        exposed_bytes=exposed, overlapped_bytes=overlapped,
        exposure_budget_bytes=None, predicted_step_flops=flops,
        bytes_per_flop=ratio, cost=cost, tokens_per_step=tokens,
        cost_per_token=(cost / tokens if tokens else None),
        transport_plan_summary=None, compile_wall=None)


def static_sweep(grid: Dict[str, Any], artifact: Optional[Dict] = None,
                 log=None) -> List[SweepResult]:
    """:func:`sweep`'s zero-compile sibling: every grid point scored by
    :func:`predict_from_artifact` over the entry's committed artifact.
    All results carry ``compiled=False``; infeasibility comes from the
    static model alone. Raises when no artifact is committed for the
    entry — model mode has nothing to extrapolate from."""
    entry = grid.get("entry", "engine-train-step")
    if artifact is None:
        artifact = load_verdict_artifact(default_plans_dir(), entry)
    if artifact is None:
        raise ValueError(
            f"no committed verdict artifact for entry {entry!r} "
            f"(run `dstpu plan --entry {entry} --update-artifacts`)")
    results = [SweepResult(c, predict_from_artifact(artifact, c, entry),
                           compiled=False)
               for c in (Candidate.from_overrides(o)
                         for o in expand_grid(grid))]
    if log is not None:
        pruned = sum(1 for r in results if not r.verdict.feasible)
        log(f"dstpu plan: statically predicted {len(results)} grid "
            f"point(s), {pruned} infeasible (model mode, 0 compiled)")
    return results


def export_survivors(results: List[SweepResult]) -> List[Dict[str, Any]]:
    """The ranked-survivor export the trial ledger commits: candidate (in
    re-runnable namespace form) + deterministic verdict artifact +
    whether the verdict came from a compile audit or the static model."""
    return [{"candidate": r.candidate.to_dict(),
             "verdict": r.verdict.to_artifact(),
             "compiled": r.compiled}
            for r in rank_survivors(results)]


# ---------------------------------------------------------------------------
# calibration — measured trials sharpening the static model
# ---------------------------------------------------------------------------

def default_calibration_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "autotune", "calibration.json")


def load_calibration(path: Optional[str] = None) -> Dict[str, Any]:
    """The per-entry calibration records ({entry: {seconds_per_cost,
    flops_ratio, samples}}); {} when none accumulated yet (or torn —
    calibration is advisory, a bad file must never fail a plan)."""
    p = path or default_calibration_path()
    if not os.path.exists(p):
        return {}
    try:
        with open(p, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    return doc if isinstance(doc, dict) else {}


def update_calibration(entry: str, *, measured_step_s: float, cost: float,
                       flops_ratio: Optional[float] = None,
                       path: Optional[str] = None,
                       alpha: float = 0.5) -> Dict[str, Any]:
    """Fold one full-budget trial's measurement into the entry's record:
    EWMA of ``seconds_per_cost`` (wall seconds per flop-equivalent — the
    factor turning the oracle's unitless cost into a predicted step
    time) and of the measured/predicted FLOPs ratio from
    ``feasibility_cross_check``. Crash-consistent via the checkpoint
    store's atomic-write discipline. Returns the updated record."""
    from deepspeed_tpu.checkpoint.store import _atomic_json

    p = path or default_calibration_path()
    if measured_step_s <= 0 or cost <= 0:
        return load_calibration(p).get(entry, {})
    doc = load_calibration(p)
    rec = dict(doc.get(entry) or {})
    spc = measured_step_s / cost
    prev = rec.get("seconds_per_cost")
    rec["seconds_per_cost"] = (spc if prev is None
                               else alpha * spc + (1 - alpha) * float(prev))
    if flops_ratio is not None and flops_ratio > 0:
        prev_fr = rec.get("flops_ratio")
        rec["flops_ratio"] = (flops_ratio if prev_fr is None
                              else alpha * flops_ratio
                              + (1 - alpha) * float(prev_fr))
    rec["samples"] = int(rec.get("samples") or 0) + 1
    doc[entry] = rec
    os.makedirs(os.path.dirname(p), exist_ok=True)
    _atomic_json(p, doc)
    return rec


def predicted_step_seconds(verdict: FeasibilityVerdict,
                           calibration: Optional[Dict[str, Any]] = None
                           ) -> Optional[float]:
    """Wall-clock prediction for a verdict: ``cost x seconds_per_cost``
    from the entry's calibration record; None before any full trial has
    calibrated the entry (the oracle alone ranks, it does not clock)."""
    cal = calibration if calibration is not None else load_calibration()
    rec = cal.get(verdict.entry) or {}
    spc = rec.get("seconds_per_cost")
    if not spc or verdict.cost in (None, float("inf")):
        return None
    return float(verdict.cost) * float(spc)


# ---------------------------------------------------------------------------
# CLI — `dstpu plan`
# ---------------------------------------------------------------------------

def build_parser():
    import argparse

    parser = argparse.ArgumentParser(
        prog="dstpu plan",
        description="Layer E: static config-feasibility oracle — compile "
                    "and audit candidate configs without running a step "
                    "(docs/STATIC_ANALYSIS.md)")
    parser.add_argument("--entry", action="append", default=None,
                        help="entry point(s) to evaluate (default: all "
                             "registered; candidate/grid mode defaults to "
                             "engine-train-step)")
    parser.add_argument("--set", action="append", default=[],
                        metavar="KEY=VALUE", dest="overrides",
                        help="candidate override (dotted config path, or "
                             "model.*/batch.* — JSON-parsed value), e.g. "
                             "--set batch.size=64 "
                             "--set model.remat=false --set "
                             "data_types.optimizer_moment_dtype='\"float32\"'")
    parser.add_argument("--candidate", default=None,
                        help="candidate JSON file (flat dotted overrides, "
                             "or {config/model/batch} namespaces)")
    parser.add_argument("--grid", default=None,
                        help="grid JSON file: {entry, base?, axes: {key: "
                             "[values...]}, monotone?: [keys...]} — sweeps "
                             "the cartesian product with static pruning")
    parser.add_argument("--plans-dir", default=None,
                        help="artifact directory (default: "
                             "tools/feasibility)")
    parser.add_argument("--update-artifacts", action="store_true",
                        help="write tools/feasibility/<entry>.json for "
                             "HEAD-default verdicts (deterministic; the "
                             "tier-1 freshness gate diffs them)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit verdicts as JSON")
    parser.add_argument("--list-entries", action="store_true",
                        help="print the registered entry points and exit")
    return parser


def _parse_set(items: List[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for item in items:
        if "=" not in item:
            raise ValueError(f"--set expects KEY=VALUE, got {item!r}")
        key, _, raw = item.partition("=")
        try:
            out[key] = json.loads(raw)
        except json.JSONDecodeError:
            out[key] = raw
    return out


def _load_candidate_file(path: str) -> Candidate:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if set(data) <= {"label", "config", "model", "batch"}:
        from deepspeed_tpu.runtime.config import expand_dotted
        return Candidate(
            label=data.get("label", os.path.basename(path)),
            config=_freeze(expand_dotted(data.get("config", {}))),
            model=_freeze(data.get("model", {})),
            batch=_freeze(data.get("batch", {})))
    return Candidate.from_overrides(data, label=os.path.basename(path))


def _render_verdict(v: FeasibilityVerdict) -> str:
    head = "FEASIBLE" if v.feasible else "INFEASIBLE"
    lines = [f"{v.entry}: {head}"
             + (f" [{v.candidate['label']}]" if v.candidate else "")]
    for reason in v.reasons:
        lines.append(f"    reject: {reason}")
    if v.memory:
        lines.append(
            f"    hbm {v.hbm_bytes} / {v.hbm_budget_bytes} B/device, "
            f"collectives {v.collective_bytes} B, exposed "
            f"{v.exposed_bytes} B"
            + (f" (budget {v.exposure_budget_bytes} B)"
               if v.exposure_budget_bytes is not None else "")
            + f", flops {v.predicted_step_flops}, cost {v.cost:.3e}")
    pred_s = predicted_step_seconds(v)
    if pred_s is not None:
        lines.append(f"    predicted step {pred_s:.4f}s (calibrated by "
                     "measured trials — tools/autotune/calibration.json)")
    if v.compile_wall is not None:
        lines.append(f"    compile {v.compile_wall:.2f}s")
    return "\n".join(lines)


def main(argv=None) -> int:
    import sys

    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    from .entry_points import SPEC_BUILDERS

    if args.list_entries:
        from .entry_points import CANDIDATE_ENTRY_POINTS
        for name in sorted(SPEC_BUILDERS):
            tag = " [candidate-capable]" if name in CANDIDATE_ENTRY_POINTS \
                else ""
            print(f"{name}{tag}")
        return 0

    try:
        overrides = _parse_set(args.overrides)
    except ValueError as e:
        print(f"dstpu plan: {e}", file=sys.stderr)
        return 2
    if args.grid and (overrides or args.candidate):
        print("dstpu plan: --grid is exclusive with --set/--candidate",
              file=sys.stderr)
        return 2

    from .budgets import env_matches
    from .schedule_audit import default_exposure_path, load_exposure_budgets
    exposure = load_exposure_budgets(default_exposure_path())
    if exposure is not None and not env_matches(exposure):
        print("dstpu plan: exposure budgets committed for "
              f"{exposure['mesh_devices']} devices — exposure rejections "
              "skipped on this mesh", file=sys.stderr)
        exposure = None

    if args.grid:
        try:
            grid = load_grid(args.grid)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"dstpu plan: bad grid file: {e}", file=sys.stderr)
            return 2
        if args.entry:
            grid["entry"] = args.entry[0]
        results = sweep(grid, exposure=exposure,
                        log=lambda m: print(m, file=sys.stderr))
        ranked = rank_survivors(results)
        if args.as_json:
            print(json.dumps({
                "entry": grid.get("entry", "engine-train-step"),
                "grid_points": len(results),
                "compiled": sum(1 for r in results if r.compiled),
                "pruned": sum(1 for r in results if not r.compiled),
                "results": [r.to_dict() for r in results],
                "ranked": [r.candidate.label for r in ranked],
            }, indent=2))
        else:
            for r in results:
                print(_render_verdict(r.verdict)
                      + ("" if r.compiled else "    (pruned, not compiled)"))
            print(f"\n{len(ranked)} feasible of {len(results)} point(s); "
                  "ranked cheapest first:")
            for i, r in enumerate(ranked):
                v = r.verdict
                per_tok = (f", {v.cost_per_token:.3e}/token"
                           if v.cost_per_token is not None else "")
                print(f"  {i + 1}. {r.candidate.label} "
                      f"(cost {v.cost:.3e}{per_tok})")
        return 0 if ranked else 1

    candidate: Optional[Candidate] = None
    if args.candidate and overrides:
        print("dstpu plan: --candidate is exclusive with --set",
              file=sys.stderr)
        return 2
    if args.candidate:
        try:
            candidate = _load_candidate_file(args.candidate)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"dstpu plan: bad candidate file: {e}", file=sys.stderr)
            return 2
    if overrides:
        candidate = Candidate.from_overrides(overrides)

    if candidate is not None:
        names = args.entry or ["engine-train-step"]
    else:
        names = args.entry or sorted(SPEC_BUILDERS)
    unknown = sorted(set(names) - set(SPEC_BUILDERS))
    if unknown:
        print(f"dstpu plan: unknown entry point(s): {', '.join(unknown)}",
              file=sys.stderr)
        return 2

    verdicts = []
    for name in names:
        verdict = evaluate_entry(name, candidate, exposure=exposure)
        verdicts.append(verdict)
        if not args.as_json:
            print(_render_verdict(verdict))
        if candidate is None and args.update_artifacts:
            path = write_verdict_artifact(
                args.plans_dir or default_plans_dir(), verdict)
            print(f"wrote {path}", file=sys.stderr)
    if args.as_json:
        print(json.dumps({"verdicts": [v.to_dict() for v in verdicts]},
                         indent=2))
    return 0 if all(v.feasible for v in verdicts) else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
