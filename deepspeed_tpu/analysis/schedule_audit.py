"""Layer D: HLO-schedule overlap auditor + collective placement maps.

Layer C (:mod:`.spmd_audit`) answers *which* collectives the partitioned
program contains; this layer answers *where they land in the schedule* and
whether the surrounding compute can hide them. T3 (arXiv:2401.16677)
argues that is the question that decides comm/compute overlap, and *The
Big Send-off* (arXiv:2504.18658) needs the same placement data to pick a
per-bucket algorithm — ROADMAP item 2's auto-overlap planner consumes the
maps this layer emits.

For every registered :class:`~.entry_points.EntrySpec` the auditor walks
the compiled module's instruction sequence (the optimized HLO is emitted
``is_scheduled=true``, so text order IS the schedule), pairs async
``-start``/``-done`` collectives, costs the dot/conv FLOPs of the
surrounding compute — recursing into ``while`` bodies scaled by the
compiler's ``known_trip_count``, the static analogue of
``TreeComm.trace_executions`` — and classifies each collective:

- **overlapped** — enough *independent* compute sits in the collective's
  slack window to hide its bytes under the per-platform bytes/flop ratio
  (:func:`bytes_per_flop`). For an async pair the window is the
  instructions between ``-start`` and ``-done`` (the schedule's declared
  overlap); for a sync collective (the CPU audit mesh emits only these)
  it is the compute scheduled *after* the launch that does not depend on
  its result — what an async-capable backend could run concurrently.
- **exposed** — the window's independent compute cannot hide the bytes:
  the program stalls on the wire.
- **serialized** — the collective's first reader is itself another
  collective with zero costed compute between them: a dependent
  back-to-back chain that no scheduler can overlap.

Rules:

- ``exposed-collective`` — entries declaring ``overlap_contract`` in
  their spec (the pipelined ZeRO micro, the ragged serving wave) must
  have zero exposed bytes beyond their committed exposure budget.
- ``serialized-collective-chain`` — a dependent back-to-back collective
  chain (above a noise floor) anywhere in the schedule.
- ``exposure-budget-regression`` — per-entry exposed bytes checked
  against the committed shrink-only ``tools/exposure_budgets.json``
  (same contract as the memory budgets: ``--update-budgets`` only ever
  writes downward).
- ``schedule-audit-failed`` — the entry could not be compiled/walked.

Each audit also produces the entry's **collective map**
(``tools/collective_maps/<entry>.json``): kind, bytes, start/done
schedule positions, hideable FLOPs, classification and loop context per
collective — the declarative artifact the item-2 planner (and
``tools/overlap_report.py``) consume.

Findings carry the ``<sched:NAME>`` path marker so the baseline machinery
treats the layer independently, exactly like Layer C's ``<spmd:NAME>``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

from .budgets import (load_budgets, shrink_budgets as _shrink,
                      write_budgets as _write)
from .findings import Finding, SEVERITY_ERROR, SEVERITY_WARNING, sort_findings
from .registry import LAYER_SCHEDULE, Rule, register
from .spmd_audit import _HLO_COLLECTIVE_KINDS, _dtype_itemsize

SCHED_PREFIX = "<sched:"

EXPOSED_COLLECTIVE = register(Rule(
    rule_id="exposed-collective", layer=LAYER_SCHEDULE,
    severity=SEVERITY_ERROR,
    description="Entry point declares an overlap contract but its "
                "schedule carries exposed collective bytes beyond the "
                "committed exposure budget — the pipelining the entry "
                "exists for has regressed",
    fix_hint="restructure the schedule so the collective overlaps "
             "independent compute (prefetch it a step earlier, move the "
             "consumer later); if the exposure is a deliberate pipeline "
             "edge, raise tools/exposure_budgets.json BY HAND and defend "
             "it in review"))

SERIALIZED_CHAIN = register(Rule(
    rule_id="serialized-collective-chain", layer=LAYER_SCHEDULE,
    severity=SEVERITY_WARNING,
    description="Dependent back-to-back collectives with no compute "
                "between them — the chain's latency is the sum of its "
                "links and no scheduler can hide it",
    fix_hint="break the dependence (fuse the collectives, reassociate "
             "the reduction, or interleave independent compute between "
             "the links); hierarchical/multi-algorithm selection "
             "(ROADMAP item 1) is the systematic fix"))

EXPOSURE_BUDGET_REGRESSION = register(Rule(
    rule_id="exposure-budget-regression", layer=LAYER_SCHEDULE,
    severity=SEVERITY_ERROR,
    description="Exposed collective bytes exceed the committed "
                "shrink-only budget (tools/exposure_budgets.json), or "
                "the entry point has no committed exposure budget",
    fix_hint="overlap the newly exposed collective back under compute; "
             "if the exposure is justified, raise the budget BY HAND in "
             "tools/exposure_budgets.json and defend it in review"))

SCHEDULE_AUDIT_FAILED = register(Rule(
    rule_id="schedule-audit-failed", layer=LAYER_SCHEDULE,
    severity=SEVERITY_ERROR,
    description="Entry point failed to compile or its schedule could not "
                "be walked — a broken hot path must not pass silently",
    fix_hint="run under JAX_PLATFORMS=cpu with "
             "xla_force_host_platform_device_count>=8 and fix the "
             "compile error"))

#: serialized chains whose TOTAL moved bytes (summed over all links,
#: execution-scaled) stay below this floor are noise — a scalar loss
#: psum feeding a grad-norm psum is not worth a finding.
SERIALIZED_MIN_BYTES = 4096

#: classification: a collective is *overlapped* when
#: ``hideable_flops * bytes_per_flop >= operand_bytes``. The ratio is the
#: interconnect bytes a device can move per FLOP it computes — peak ICI
#: bandwidth over peak dense FLOPs, same marketing-peak convention as
#: telemetry's ``PEAK_FLOPS_BY_KIND`` (the number just has to be stated;
#: classification is a roofline ratio, not a wall-clock claim). Keyed by
#: substrings of ``jax.devices()[0].device_kind`` lowercased.
BYTES_PER_FLOP_BY_KIND = (
    ("v6e", 3.9e-4),         # ~360 GB/s ICI / 918 Tflops
    ("v5p", 1.0e-3),         # ~459 GB/s ICI / 459 Tflops
    ("v5e", 8.1e-4),         # ~160 GB/s ICI / 197 Tflops
    ("v5 lite", 8.1e-4),
    ("v4", 8.7e-4),          # ~240 GB/s ICI / 275 Tflops
    ("v3", 5.3e-4),
    ("v2", 1.1e-3),
    ("cpu", 5e-2),           # host audit mesh: generous, so schedule
                             # STRUCTURE (not host memcpy speed) decides
)


def bytes_per_flop(device_kind: Optional[str] = None) -> float:
    """Per-platform hideable-bytes-per-flop ratio;
    ``DSTPU_BYTES_PER_FLOP`` overrides."""
    env = os.environ.get("DSTPU_BYTES_PER_FLOP")
    if env:
        return float(env)
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - no backend
            return 5e-2
    kind = (device_kind or "").lower()
    for key, ratio in BYTES_PER_FLOP_BY_KIND:
        if key in kind:
            return ratio
    return 5e-2


# ---------------------------------------------------------------------------
# structured HLO parsing (instruction order, operands, called computations)
# ---------------------------------------------------------------------------

_ARRAY_SHAPE_RE = re.compile(r"([a-z][\w]*)\[([0-9,]*)\]")
_INSTR_HEAD_RE = re.compile(r"^\s+(?:ROOT\s+)?%([\w.\-]+)\s*=\s*")
_COMP_HEAD_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s+\(.*\{\s*$")
_OPCODE_RE = re.compile(r"\s*([a-z][a-z0-9\-]*)")
_TRIP_COUNT_RE = re.compile(r'known_trip_count[^0-9]*"n"[^0-9]*(\d+)')
_CALLED_RE = re.compile(r"(?:calls|body|to_apply)=%([\w.\-]+)")
# conditional instructions name their branches with these attrs, not
# `calls=` — missing them would silently drop branch collectives
_BRANCH_KEYS_RE = re.compile(
    r"(?:true_computation|false_computation|branch_computations)="
    r"(%[\w.\-]+|\{[^}]*\})")
_CONDITION_RE = re.compile(r"condition=%([\w.\-]+)")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')
_SOURCE_RE = re.compile(r'source_file="([^"]*)"[^"]*source_line=(\d+)')
_DIMS_SET_RE = {side: re.compile(side + r"_contracting_dims=\{([0-9,]*)\}")
                for side in ("lhs", "rhs")}


def _array_bytes(text: str) -> int:
    """Total bytes of every typed array shape in ``text``."""
    total = 0
    for m in _ARRAY_SHAPE_RE.finditer(text):
        dims = m.group(2)
        n = int(np.prod([int(d) for d in dims.split(",")],
                        dtype=np.int64)) if dims else 1
        total += n * _dtype_itemsize(m.group(1))
    return total


def _balanced(text: str) -> Tuple[str, str]:
    """Split ``(....)rest`` at the matching close paren -> (inner, rest)."""
    depth = 0
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return text[1:i], text[i + 1:]
    return text[1:], ""


def _top_level_split(text: str) -> List[str]:
    out, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(text[start:i])
            start = i + 1
    if text[start:].strip():
        out.append(text[start:])
    return out


@dataclasses.dataclass
class HloInstruction:
    """One scheduled instruction of one computation."""
    name: str
    opcode: str
    shape_text: str                    # result shape (array or tuple)
    operands: List[Tuple[str, str]]    # (operand name, operand text)
    attrs: str                         # everything after the operand list
    index: int                         # schedule position in its computation

    @property
    def result_bytes(self) -> int:
        return _array_bytes(self.shape_text)

    @property
    def operand_bytes(self) -> int:
        return sum(_array_bytes(text) for _, text in self.operands)

    @property
    def operand_names(self) -> List[str]:
        return [n for n, _ in self.operands]

    @property
    def called(self) -> List[str]:
        return _CALLED_RE.findall(self.attrs)

    @property
    def branches(self) -> List[str]:
        """Branch computations of a ``conditional`` (true/false or the
        indexed ``branch_computations={...}`` form)."""
        out: List[str] = []
        for group in _BRANCH_KEYS_RE.findall(self.attrs):
            out.extend(re.findall(r"%([\w.\-]+)", group))
        return out

    @property
    def trip_count(self) -> Optional[int]:
        m = _TRIP_COUNT_RE.search(self.attrs)
        return int(m.group(1)) if m else None

    @property
    def op_name(self) -> str:
        m = _METADATA_RE.search(self.attrs)
        return m.group(1) if m else ""

    @property
    def source(self) -> str:
        m = _SOURCE_RE.search(self.attrs)
        if m is None:
            return ""
        # repo-relative: the committed maps must not bake in a machine
        path = m.group(1)
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))) + os.sep
        if path.startswith(root):
            path = path[len(root):]
        return f"{path}:{m.group(2)}"

    @property
    def collective_kind(self) -> Optional[str]:
        """'all-gather' for both sync ops and ``-start`` halves; None for
        non-collectives and for ``-done`` halves (paired, never counted
        twice)."""
        op = self.opcode
        if op.endswith("-done"):
            return None
        kind = op[:-6] if op.endswith("-start") else op
        return kind if kind in _HLO_COLLECTIVE_KINDS else None

    @property
    def is_async_start(self) -> bool:
        return self.opcode.endswith("-start")


def _parse_instruction(line: str, index: int) -> Optional[HloInstruction]:
    head = _INSTR_HEAD_RE.match(line)
    if head is None:
        return None
    name, rest = head.group(1), line[head.end():]
    if rest.startswith("("):
        shape_text, rest = _balanced(rest)
        shape_text = f"({shape_text})"
    else:
        m = re.match(r"[\w]+\[[0-9,]*\](?:\{[^}]*\})?", rest)
        if m is None:
            return None
        shape_text, rest = m.group(0), rest[m.end():]
    op = _OPCODE_RE.match(rest)
    if op is None:
        return None
    rest = rest[op.end():]
    if not rest.startswith("("):
        return None
    operand_text, attrs = _balanced(rest)
    operands = []
    for seg in _top_level_split(operand_text):
        names = re.findall(r"%([\w.\-]+)", seg)
        if names:
            operands.append((names[-1], seg))
    return HloInstruction(name=name, opcode=op.group(1),
                          shape_text=shape_text, operands=operands,
                          attrs=attrs, index=index)


@dataclasses.dataclass
class HloComputation:
    name: str
    is_entry: bool
    instructions: List[HloInstruction]

    def __post_init__(self):
        self.by_name = {i.name: i for i in self.instructions}


def parse_hlo_computations(hlo_text: str) -> Dict[str, HloComputation]:
    """The optimized module as ordered computations. The dump is emitted
    with ``is_scheduled=true``, so each computation's instruction order is
    the actual schedule."""
    comps: Dict[str, HloComputation] = {}
    current: Optional[HloComputation] = None
    for line in hlo_text.splitlines():
        head = _COMP_HEAD_RE.match(line)
        if head is not None:
            current = HloComputation(name=head.group(2),
                                     is_entry=bool(head.group(1)),
                                     instructions=[])
            comps[current.name] = current
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is not None:
            instr = _parse_instruction(line, len(current.instructions))
            if instr is not None:
                current.instructions.append(instr)
                current.by_name[instr.name] = instr
    return comps


def entry_computation(comps: Dict[str, HloComputation]
                      ) -> Optional[HloComputation]:
    for comp in comps.values():
        if comp.is_entry:
            return comp
    return None


# ---------------------------------------------------------------------------
# FLOP costing (dot/conv — the same cost model XLA's cost_analysis keys
# MFU on; everything element-wise is treated as free)
# ---------------------------------------------------------------------------

def _shape_elems(shape_text: str) -> int:
    total = 0
    for m in _ARRAY_SHAPE_RE.finditer(shape_text):
        dims = m.group(2)
        total += int(np.prod([int(d) for d in dims.split(",")],
                             dtype=np.int64)) if dims else 1
    return total


def _dot_flops(instr: HloInstruction) -> int:
    """2 * result_elems * contracted_extent, dims from the dot's own
    attrs and the lhs operand's printed shape."""
    out_elems = _shape_elems(instr.shape_text)
    if not instr.operands:
        return 2 * out_elems
    lhs_text = instr.operands[0][1]
    m = _ARRAY_SHAPE_RE.search(lhs_text)
    if m is None:
        return 2 * out_elems
    lhs_dims = [int(d) for d in m.group(2).split(",")] if m.group(2) else []
    cm = _DIMS_SET_RE["lhs"].search(instr.attrs)
    contracted = 1
    if cm and cm.group(1):
        for i in cm.group(1).split(","):
            if int(i) < len(lhs_dims):
                contracted *= lhs_dims[int(i)]
    elif lhs_dims:
        contracted = lhs_dims[-1]   # default dot: last lhs dim contracts
    return 2 * out_elems * contracted


def _conv_flops(instr: HloInstruction) -> int:
    """2 * output_elems * (kernel elems / output features) — the rhs is
    the kernel; its output-feature dim ('o' in dim_labels) produces, the
    rest contract."""
    out_elems = _shape_elems(instr.shape_text)
    if len(instr.operands) < 2:
        return 2 * out_elems
    m = _ARRAY_SHAPE_RE.search(instr.operands[1][1])
    if m is None:
        return 2 * out_elems
    rhs_dims = [int(d) for d in m.group(2).split(",")] if m.group(2) else []
    kernel = int(np.prod(rhs_dims, dtype=np.int64)) if rhs_dims else 1
    lm = re.search(r"dim_labels=[^_,]*_([\w?]+)->", instr.attrs)
    if lm and "o" in lm.group(1) and lm.group(1).index("o") < len(rhs_dims):
        kernel //= max(1, rhs_dims[lm.group(1).index("o")])
    return 2 * out_elems * kernel


class FlopModel:
    """Per-instruction and per-computation dot/conv FLOPs, with
    ``fusion``/``call``/``while`` instructions charged their callee's cost
    (``while`` scaled by the compiler's known trip count)."""

    def __init__(self, comps: Dict[str, HloComputation]):
        self.comps = comps
        self._comp_cache: Dict[str, int] = {}

    def instruction_flops(self, instr: HloInstruction) -> int:
        op = instr.opcode
        if op == "dot":
            return _dot_flops(instr)
        if op == "convolution":
            return _conv_flops(instr)
        if instr.collective_kind is not None or op.endswith("-done"):
            return 0    # a collective's reduction lambda is not compute
        if op == "conditional":
            # one branch runs: charge the cheapest (conservative for the
            # hideable-compute estimate)
            branch_costs = [self.computation_flops(b)
                            for b in instr.branches]
            return min(branch_costs) if branch_costs else 0
        called = instr.called
        if not called:
            return 0
        total = sum(self.computation_flops(c) for c in called)
        if op == "while":
            total *= max(1, instr.trip_count or 1)
        return total

    def computation_flops(self, name: str) -> int:
        if name in self._comp_cache:
            return self._comp_cache[name]
        self._comp_cache[name] = 0   # cycle guard
        comp = self.comps.get(name)
        if comp is not None:
            self._comp_cache[name] = sum(self.instruction_flops(i)
                                         for i in comp.instructions)
        return self._comp_cache[name]


# ---------------------------------------------------------------------------
# the schedule walk
# ---------------------------------------------------------------------------

CLASS_OVERLAPPED = "overlapped"
CLASS_EXPOSED = "exposed"
CLASS_SERIALIZED = "serialized"


@dataclasses.dataclass
class CollectiveRecord:
    """One collective's placement in the compiled schedule — a row of the
    entry's collective map."""
    kind: str
    name: str
    computation: str
    start_index: int
    done_index: Optional[int]          # async pairs only
    operand_bytes: int                 # input-side bytes, per launch
    result_bytes: int
    hideable_flops: int
    classification: str
    executions: int                    # loop-context trip-count product
    loop: Optional[Dict[str, Any]]     # {"while": ..., "trip_count": ...}
    op_name: str
    source: str

    @property
    def moved_bytes(self) -> int:
        """Execution-scaled input-side bytes — the convention matches the
        runtime's ``record_collective`` (which charges each launch's
        input bytes), so the static and runtime splits compare."""
        return self.operand_bytes * self.executions

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _async_done_index(comp: HloComputation, start: HloInstruction
                      ) -> Optional[int]:
    for instr in comp.instructions[start.index + 1:]:
        if instr.opcode == start.opcode[:-6] + "-done" \
                and start.name in instr.operand_names:
            return instr.index
    return None


def _dependents(comp: HloComputation, roots: Set[str]) -> Set[str]:
    """Names of instructions transitively reading any of ``roots`` within
    the computation (schedule order makes one forward pass sufficient)."""
    out = set(roots)
    for instr in comp.instructions:
        if instr.name in out:
            continue
        if any(n in out for n in instr.operand_names):
            out.add(instr.name)
    return out - roots


def _start_result_bytes(instr: HloInstruction) -> int:
    """An async ``-start`` returns ``(operand aliases..., results...,
    context scratch...)`` — charge only the result slice: skip as many
    leading shapes as the instruction has operands, and drop trailing
    integer-scalar scratch (the u32[] context pair a
    collective-permute-start carries)."""
    if not instr.is_async_start:
        return instr.result_bytes
    shapes = [(m.group(1), m.group(2), _array_bytes(m.group(0)))
              for m in _ARRAY_SHAPE_RE.finditer(instr.shape_text)]
    if len(shapes) <= 1:
        return sum(b for _, _, b in shapes)
    n_ops = len(instr.operands)
    cand = shapes[n_ops:] if 0 < n_ops < len(shapes) else (
        shapes[len(shapes) // 2:] if len(shapes) % 2 == 0 else shapes[-1:])
    while len(cand) > 1 and cand[-1][0] in ("u32", "s32", "u64", "s64") \
            and cand[-1][1] == "":
        cand = cand[:-1]
    return sum(b for _, _, b in cand)


def walk_schedule(comps: Dict[str, HloComputation],
                  ratio: float) -> Tuple[List[CollectiveRecord], List[str]]:
    """Classify every collective reachable from the entry computation ->
    (records, serialized chain descriptions)."""
    flops = FlopModel(comps)
    records: List[CollectiveRecord] = []
    chains: List[str] = []
    entry = entry_computation(comps)
    if entry is None:
        return records, chains

    def visit(comp: HloComputation, mult: int,
              loop: Optional[Dict[str, Any]], seen: Set[str]) -> None:
        if comp.name in seen:
            return
        seen = seen | {comp.name}
        comp_records: List[CollectiveRecord] = []
        for instr in comp.instructions:
            if instr.opcode == "while":
                # body AND condition: a psum inside cond_fun (a global
                # convergence check) is a per-iteration collective too
                trip = max(1, instr.trip_count or 1)
                for b in instr.called + _CONDITION_RE.findall(instr.attrs):
                    visit_comp = comps.get(b)
                    if visit_comp is not None:
                        visit(visit_comp, mult * trip,
                              {"while": instr.name, "trip_count": trip},
                              seen)
                continue
            if instr.opcode in ("call", "conditional"):
                for c in instr.called + instr.branches:
                    sub = comps.get(c)
                    if sub is not None:
                        visit(sub, mult, loop, seen)
            kind = instr.collective_kind
            if kind is None:
                continue
            done_idx = (_async_done_index(comp, instr)
                        if instr.is_async_start else None)
            result_name = instr.name
            if done_idx is not None:
                result_name = comp.instructions[done_idx].name
            deps = _dependents(comp, {instr.name, result_name})
            if done_idx is not None:
                # async pair: the schedule DECLARED its overlap window
                window = comp.instructions[instr.index + 1:done_idx]
            elif loop is not None:
                # sync collective in a loop body: the schedule is circular
                # across iterations (a launch at the body's tail overlaps
                # the next iteration's head — the software-pipelining the
                # prefetch carry exists for), so every non-dependent
                # instruction of the body is window
                window = comp.instructions
            else:
                # sync straight-line: what a launch-early/consume-late
                # backend could run concurrently is the compute scheduled
                # after the launch
                window = comp.instructions[instr.index + 1:]
            hideable = sum(flops.instruction_flops(w) for w in window
                           if w.name not in deps
                           and w.name != instr.name
                           and w.collective_kind is None)
            rec = CollectiveRecord(
                kind=kind, name=instr.name, computation=comp.name,
                start_index=instr.index, done_index=done_idx,
                operand_bytes=instr.operand_bytes,
                result_bytes=_start_result_bytes(instr),
                hideable_flops=int(hideable),
                classification=(CLASS_OVERLAPPED
                                if hideable * ratio >= instr.operand_bytes
                                else CLASS_EXPOSED),
                executions=mult, loop=loop, op_name=instr.op_name,
                source=instr.source)
            comp_records.append(rec)
            records.append(rec)

        # serialized chains: a collective whose FIRST reader is itself a
        # collective, with zero costed compute between the two launches
        by_name = {r.name: r for r in comp_records}
        link_to: Dict[str, str] = {}
        for rec in comp_records:
            anchor = rec.done_index if rec.done_index is not None \
                else rec.start_index
            result = comp.instructions[anchor].name
            for instr in comp.instructions[anchor + 1:]:
                if result in instr.operand_names:
                    gap = comp.instructions[anchor + 1:instr.index]
                    gap_flops = sum(flops.instruction_flops(g) for g in gap)
                    if instr.collective_kind is not None and gap_flops == 0 \
                            and instr.name in by_name:
                        link_to[rec.name] = instr.name
                    break
        heads = set(link_to) - set(link_to.values())
        for head in sorted(heads):
            chain = [head]
            while chain[-1] in link_to:
                chain.append(link_to[chain[-1]])
            chain_bytes = sum(by_name[n].moved_bytes for n in chain)
            if chain_bytes < SERIALIZED_MIN_BYTES:
                continue
            for n in chain:
                by_name[n].classification = CLASS_SERIALIZED
            kinds = " -> ".join(by_name[n].kind for n in chain)
            chains.append(
                f"{len(chain)} dependent back-to-back collective(s) in "
                f"{comp.name}: {kinds} ({chain_bytes} B, no compute "
                f"between launches)")

    visit(entry, 1, None, set())
    return records, chains


# ---------------------------------------------------------------------------
# reports, exposure budgets, collective maps
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ScheduleReport:
    """Per-entry schedule numbers: the collective map rows plus the
    overlapped/exposed byte split the exposure budgets and the telemetry
    parity test consume."""
    name: str
    records: List[CollectiveRecord]
    bytes_per_flop: float

    def split(self) -> Dict[str, int]:
        out = {CLASS_OVERLAPPED: 0, CLASS_EXPOSED: 0, CLASS_SERIALIZED: 0}
        for r in self.records:
            out[r.classification] += r.moved_bytes
        return out

    @property
    def overlapped_bytes(self) -> int:
        return self.split()[CLASS_OVERLAPPED]

    @property
    def exposed_bytes(self) -> int:
        """Exposed + serialized — serialized links are exposed bytes the
        schedule additionally chains."""
        s = self.split()
        return s[CLASS_EXPOSED] + s[CLASS_SERIALIZED]

    def budget_fields(self) -> Dict[str, int]:
        return {"exposed_bytes": int(self.exposed_bytes)}

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "bytes_per_flop": self.bytes_per_flop,
                "summary": self.summary(),
                "collectives": [r.to_dict() for r in self.records]}

    def summary(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for r in self.records:
            counts[r.classification] = counts.get(r.classification, 0) + 1
        split = self.split()
        # "exposed_bytes" here is THE budgeted quantity (exposed +
        # serialized, == self.exposed_bytes) so the map summary, the
        # --json payload and tools/exposure_budgets.json all agree;
        # "serialized_bytes" calls out the chained subset
        return {"collectives": len(self.records), "counts": counts,
                "overlapped_bytes": split[CLASS_OVERLAPPED],
                "exposed_bytes": (split[CLASS_EXPOSED]
                                  + split[CLASS_SERIALIZED]),
                "serialized_bytes": split[CLASS_SERIALIZED],
                "total_bytes": sum(split.values())}

    def to_map(self, mesh_devices: int) -> Dict[str, Any]:
        """The committed ``tools/collective_maps/<entry>.json`` artifact
        (deterministic: no timestamps, stable ordering)."""
        return {"entry": self.name, "mesh_devices": mesh_devices,
                "bytes_per_flop": self.bytes_per_flop,
                "summary": self.summary(),
                "collectives": [r.to_dict() for r in self.records]}


EXPOSURE_FIELDS: Tuple[str, ...] = ("exposed_bytes",)

EXPOSURE_COMMENT = ("Per-entry-point exposed collective byte budgets "
                    "(dstpu lint --schedule). Shrink, never grow: "
                    "`dstpu lint --schedule --update-budgets` only "
                    "lowers; raising a budget is a hand edit that must "
                    "survive review.")


def default_exposure_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "exposure_budgets.json")


def load_exposure_budgets(path: str) -> Optional[Dict]:
    return load_budgets(path, fields=EXPOSURE_FIELDS)


def write_exposure_budgets(path: str, budgets: Dict) -> None:
    _write(path, budgets, comment=EXPOSURE_COMMENT)


def shrink_exposure_budgets(old, reports: Dict[str, Dict[str, int]],
                            mesh_devices: int):
    return _shrink(old, reports, mesh_devices, fields=EXPOSURE_FIELDS)


def default_maps_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "collective_maps")


def write_collective_map(maps_dir: str, report: ScheduleReport,
                         mesh_devices: int) -> str:
    os.makedirs(maps_dir, exist_ok=True)
    path = os.path.join(maps_dir, f"{report.name}.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report.to_map(mesh_devices), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def load_collective_map(maps_dir: str, name: str) -> Optional[Dict]:
    path = os.path.join(maps_dir, f"{name}.json")
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def _finding(rule: Rule, name: str, message: str) -> Finding:
    return Finding(rule_id=rule.rule_id, path=f"{SCHED_PREFIX}{name}>",
                   line=0, severity=rule.severity, message=message,
                   fix_hint=rule.fix_hint)


def audit_artifact_schedule(spec, artifact, *,
                            ratio: Optional[float] = None,
                            ) -> Tuple[List[Finding], ScheduleReport]:
    """Walk one compiled artifact's schedule: classification + the
    serialized-chain rule. Budget/contract checks need the committed file
    (:func:`check_exposure`)."""
    ratio = bytes_per_flop() if ratio is None else ratio
    comps = parse_hlo_computations(artifact.hlo_text)
    records, chains = walk_schedule(comps, ratio)
    findings = [_finding(SERIALIZED_CHAIN, spec.name, chain)
                for chain in chains]
    report = ScheduleReport(name=spec.name, records=records,
                            bytes_per_flop=ratio)
    return findings, report


def check_exposure(name: str, report: ScheduleReport,
                   exposure: Optional[Dict],
                   overlap_contract: bool = False) -> List[Finding]:
    """Diff one entry's exposed bytes against the committed shrink-only
    exposure budgets (already loaded + env-matched; None skips). Contract
    entries escalate a breach to ``exposed-collective``: their whole
    design is that nothing unbudgeted is ever exposed."""
    if exposure is None:
        return []
    entry = exposure.get("budgets", {}).get(name)
    if entry is None or "exposed_bytes" not in entry:
        return [_finding(
            EXPOSURE_BUDGET_REGRESSION, name,
            "no committed exposure budget in tools/exposure_budgets.json "
            "— run `dstpu lint --schedule --update-budgets` and commit "
            "the file")]
    exposed = int(report.exposed_bytes)
    budget = int(entry["exposed_bytes"])
    if exposed <= budget:
        return []
    offenders = sorted(
        {f"{r.kind}@{r.source or r.computation}" for r in report.records
         if r.classification in (CLASS_EXPOSED, CLASS_SERIALIZED)})
    detail = (f"exposed collective bytes {exposed} B exceed the committed "
              f"budget {budget} B (+{exposed - budget} B); exposed: "
              f"{', '.join(offenders) or 'none'}")
    if overlap_contract:
        return [_finding(
            EXPOSED_COLLECTIVE, name,
            f"entry declares an overlap contract but carries unbudgeted "
            f"exposed collectives — {detail}")]
    return [_finding(EXPOSURE_BUDGET_REGRESSION, name, detail)]


def audit_spec_schedule(spec, exposure: Optional[Dict] = None,
                        artifact=None, **kw
                        ) -> Tuple[List[Finding], Optional[ScheduleReport]]:
    """Compile (unless ``artifact`` is supplied — the gate compiles once
    and feeds Layers C and D) and run every Layer-D rule on one spec."""
    from .lowering import lower_entry

    if artifact is None:
        try:
            with spec.mesh_ctx():
                artifact = lower_entry(spec.fn, spec.args,
                                       donate_argnums=spec.donate_argnums,
                                       jit_kwargs=spec.jit_kwargs,
                                       name=spec.name)
        except Exception as e:  # noqa: BLE001 — any failure is a finding
            return [_finding(SCHEDULE_AUDIT_FAILED, spec.name,
                             f"failed to lower/compile: "
                             f"{type(e).__name__}: {e}")], None
    findings, report = audit_artifact_schedule(spec, artifact, **kw)
    findings += check_exposure(spec.name, report, exposure,
                               getattr(spec, "overlap_contract", False))
    return findings, report


def trace_runtime_ledger(spec):
    """Trace ``spec.fn`` ONCE under a recording ledger
    (``dist.record_collective`` fires at trace time — nothing executes)
    and return the :class:`~deepspeed_tpu.comm.CollectiveLedger`. One
    trace only: jax caches traces per (fn, avals), so a second
    ``eval_shape`` of the same spec records NOTHING — callers wanting
    both the split and the raw records must share this ledger."""
    import jax

    from deepspeed_tpu import comm as dist

    ledger = dist.CollectiveLedger()
    with dist.record_into(ledger):
        with spec.mesh_ctx():
            jax.eval_shape(spec.fn, *spec.args)
    return ledger


def trace_runtime_split(spec) -> Dict[str, int]:
    """The RUNTIME side of the overlap parity ->
    ``{"overlapped_bytes", "exposed_bytes"}`` (WIRE bytes — the
    convention that matches the static side's HLO operand bytes).
    The parity test and ``tools/overlap_report.py`` hold this against the
    static :class:`ScheduleReport` split: same taxonomy, two estimators
    (design-intent tags vs compiled placement)."""
    return trace_runtime_ledger(spec).split()


def audit_schedule_entry_points(names=None, exposure: Optional[Dict] = None,
                                entries=None,
                                ) -> Tuple[List[Finding],
                                           Dict[str, ScheduleReport]]:
    """Run Layer D over the registered entry points (default: all).

    ``exposure`` is the loaded+env-matched exposure budgets dict (None
    skips budget checks); ``entries`` an optional pre-materialized
    :func:`~.spmd_audit.iter_compiled_entries` result so a combined run
    compiles once. Returns findings plus per-entry reports for
    ``--update-budgets`` / ``--json`` / the collective maps."""
    from .spmd_audit import iter_compiled_entries

    findings: List[Finding] = []
    reports: Dict[str, ScheduleReport] = {}
    for name, spec, artifact, error in (
            entries if entries is not None else iter_compiled_entries(names)):
        if error is not None:
            findings.append(_finding(SCHEDULE_AUDIT_FAILED, name, error))
            continue
        f, report = audit_spec_schedule(spec, exposure=exposure,
                                        artifact=artifact)
        findings.extend(f)
        if report is not None:
            reports[name] = report
    return sort_findings(findings), reports
