"""Layer B: jaxpr-level audit of traced entry points.

``trace_and_check(fn, *args)`` traces ``fn`` with ``jax.make_jaxpr`` and
walks the jaxpr (recursing through pjit / shard_map / scan / cond
sub-jaxprs) enforcing:

- **collective axes** — every collective primitive (``psum``,
  ``all_gather``, ``reduce_scatter``, ``all_to_all``, ``ppermute``,
  ``axis_index``, ...) names only axes bound by the surrounding
  ``shard_map`` mesh, and every bound axis is one of the canonical names
  from :mod:`deepspeed_tpu.utils.groups`. When the global
  :class:`MeshTopology` is initialized, shard_map meshes must agree with
  its axis sizes — a mis-sized private mesh silently changes the collective
  group.
- **donation** — donated buffers must be aliasable to an output
  (shape+dtype match; XLA otherwise drops the donation and the "saving" is
  imaginary), and large state buffers that flow through unchanged-shape to
  an output but are NOT donated get flagged: that is the classic
  doubled-peak-HBM accumulator.
- **retrace hazards** — ``check_retrace`` counts distinct trace signatures
  over representative input sets; more signatures than expected means every
  step pays a recompile.

All checks emit the same structured :class:`Finding` records as Layer A, so
baselines, suppression accounting, and the CLI treat both layers uniformly.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .findings import Finding, SEVERITY_ERROR, SEVERITY_WARNING, sort_findings
from .registry import LAYER_JAXPR, Rule, register

UNBOUND_AXIS = register(Rule(
    rule_id="unbound-collective-axis", layer=LAYER_JAXPR, severity=SEVERITY_ERROR,
    description="Collective names an axis not bound by the surrounding "
                "shard_map mesh",
    fix_hint="run the collective inside a shard_map whose mesh declares the "
             "axis, or fix the axis argument"))

NON_CANONICAL_AXIS = register(Rule(
    rule_id="non-canonical-axis", layer=LAYER_JAXPR, severity=SEVERITY_ERROR,
    description="Collective/mesh/sharding uses an axis name outside the "
                "canonical topology (utils/groups.MESH_AXES)",
    fix_hint="name mesh axes from deepspeed_tpu.utils.groups constants; "
             "private ad-hoc axis names fragment the collective groups"))

TOPOLOGY_MISMATCH = register(Rule(
    rule_id="topology-mismatch", layer=LAYER_JAXPR, severity=SEVERITY_ERROR,
    description="shard_map mesh axis size disagrees with the global "
                "MeshTopology — the collective group is not the configured one",
    fix_hint="build shard_maps over topology.mesh (runtime/topology.py), "
             "never over a locally constructed mesh"))

DONATION_UNUSABLE = register(Rule(
    rule_id="donation-unusable", layer=LAYER_JAXPR, severity=SEVERITY_WARNING,
    description="Donated buffer has no shape/dtype-matching output to alias; "
                "XLA drops the donation silently",
    fix_hint="donate only buffers that are replaced by a same-shaped output "
             "(state trees); drop the donate_argnums entry otherwise"))

UNDONATED_ACCUMULATOR = register(Rule(
    rule_id="undonated-accumulator", layer=LAYER_JAXPR, severity=SEVERITY_WARNING,
    description="Large input buffer with a matching output is not donated — "
                "input and output copies coexist at peak",
    fix_hint="add the argument to donate_argnums so XLA aliases the buffers "
             "in place"))

RETRACE_HAZARD = register(Rule(
    rule_id="retrace-hazard", layer=LAYER_JAXPR, severity=SEVERITY_WARNING,
    description="Representative inputs produce more distinct trace "
                "signatures than expected — each one is a full recompile",
    fix_hint="pad/bucket shapes to a fixed set and keep non-array arguments "
             "static and hashable"))

HOST_CALLBACK_IN_GRAPH = register(Rule(
    rule_id="host-callback-in-graph", layer=LAYER_JAXPR,
    severity=SEVERITY_ERROR,
    description="Host-callback primitive (pure_callback/io_callback/debug "
                "callback) inside an audited step graph — stalls the XLA "
                "pipeline per invocation and breaks the telemetry "
                "zero-overhead contract",
    fix_hint="keep observability host-side (telemetry span hooks around the "
             "dispatch); remove the callback from traced code"))

TELEMETRY_GRAPH_DRIFT = register(Rule(
    rule_id="telemetry-graph-drift", layer=LAYER_JAXPR,
    severity=SEVERITY_ERROR,
    description="Enabling telemetry changed a step entry point's jaxpr — "
                "the disabled/enabled paths must compile the identical "
                "program (telemetry is host-side by contract)",
    fix_hint="move the instrumentation outside the jit boundary; spans wrap "
             "dispatches, they never enter traced code"))

GUARDIAN_GRAPH_DRIFT = register(Rule(
    rule_id="guardian-graph-drift", layer=LAYER_JAXPR,
    severity=SEVERITY_ERROR,
    description="A guardian-OFF engine's step jaxpr differs from the "
                "pre-guardian program — the zero-overhead contract "
                "(docs/RESILIENCE.md): with the guardian disabled the "
                "sentinels must leave no trace in the step; armed, the "
                "anomaly word may only ride reductions the step already "
                "computes",
    fix_hint="keep the sentinel pack behind the spike_thresh=None gate in "
             "_apply_from_grads; policy/rollback logic stays host-side"))

# primitives that call back into Python from inside the compiled program
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback"}

# jaxpr primitive names that carry a mesh-axis parameter ('axes' on psum/
# pmin/pmax, 'axis_name' on the rest — reduce_scatter is psum_scatter's
# primitive name).
_COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "axis_index", "pgather", "psum2",
}


def _canonical_axes() -> Tuple[str, ...]:
    from ..utils.groups import MESH_AXES
    return MESH_AXES


def _eqn_axes(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        return (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _sub_jaxprs(eqn) -> Iterable[Any]:
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            core = getattr(item, "jaxpr", None)
            if core is not None and hasattr(core, "eqns"):
                yield core            # ClosedJaxpr
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                yield item            # raw Jaxpr


def _mesh_axis_sizes(mesh) -> Dict[str, int]:
    shape = getattr(mesh, "shape", None)
    if shape is None:
        return {}
    return dict(shape)


class JaxprAuditor:
    def __init__(self, name: str, canonical: Optional[Sequence[str]] = None,
                 topology_sizes: Optional[Dict[str, int]] = None):
        self.name = name
        self.canonical = tuple(canonical) if canonical is not None else _canonical_axes()
        if topology_sizes is None:
            from ..runtime import topology as topo
            topology_sizes = (dict(topo.get_topology().mesh.shape)
                              if topo.is_initialized() else {})
        self.topology_sizes = topology_sizes
        self.findings: List[Finding] = []

    def _emit(self, rule: Rule, message: str) -> None:
        self.findings.append(Finding(
            rule_id=rule.rule_id, path=f"<trace:{self.name}>", line=0,
            severity=rule.severity, message=message, fix_hint=rule.fix_hint))

    def _check_mesh(self, mesh, where: str) -> Tuple[str, ...]:
        sizes = _mesh_axis_sizes(mesh)
        for axis, size in sizes.items():
            if axis not in self.canonical:
                self._emit(NON_CANONICAL_AXIS,
                           f"{where} mesh declares non-canonical axis "
                           f"{axis!r} (canonical: {self.canonical})")
            want = self.topology_sizes.get(axis)
            if want is not None and want != size:
                self._emit(TOPOLOGY_MISMATCH,
                           f"{where} mesh has {axis!r} size {size}, global "
                           f"topology has {want}")
        return tuple(sizes)

    def _check_spec_axes(self, spec, where: str) -> None:
        for entry in spec or ():
            entries = entry if isinstance(entry, (tuple, list)) else (entry,)
            for a in entries:
                if isinstance(a, str) and a not in self.canonical:
                    self._emit(NON_CANONICAL_AXIS,
                               f"{where} PartitionSpec uses non-canonical "
                               f"axis {a!r}")

    def walk(self, jaxpr, bound: Tuple[str, ...] = ()) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                mesh = eqn.params.get("mesh")
                mesh_axes = self._check_mesh(mesh, "shard_map")
                auto = eqn.params.get("auto") or frozenset()
                inner_bound = tuple(set(bound) | (set(mesh_axes) - set(auto)))
                for sub in _sub_jaxprs(eqn):
                    self.walk(sub, inner_bound)
                continue
            if prim == "sharding_constraint":
                sharding = eqn.params.get("sharding")
                spec = getattr(sharding, "spec", None)
                if spec is not None:
                    self._check_spec_axes(spec, "with_sharding_constraint")
                mesh = getattr(sharding, "mesh", None)
                if mesh is not None:
                    self._check_mesh(mesh, "with_sharding_constraint")
            if prim in _CALLBACK_PRIMS:
                self._emit(HOST_CALLBACK_IN_GRAPH,
                           f"{prim} primitive inside the audited graph")
            if prim in _COLLECTIVE_PRIMS:
                for axis in _eqn_axes(eqn):
                    if axis not in bound:
                        self._emit(UNBOUND_AXIS,
                                   f"{prim} over axis {axis!r} which is not "
                                   f"bound here (bound: {sorted(bound)})")
                    elif axis not in self.canonical:
                        self._emit(NON_CANONICAL_AXIS,
                                   f"{prim} over non-canonical axis {axis!r}")
            for sub in _sub_jaxprs(eqn):
                self.walk(sub, bound)


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def _aval_key(aval) -> Tuple:
    return (tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype", "")))


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
    return int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize


def check_donation(name: str, closed_jaxpr, arg_leaf_counts: Sequence[int],
                   donate_argnums: Sequence[int],
                   big_bytes: int = 1 << 20) -> List[Finding]:
    """Audit donation against the traced jaxpr.

    ``arg_leaf_counts[i]`` is the number of flat invars argument ``i``
    contributed (pytree leaves); ``donate_argnums`` are fn-level argument
    indices, exactly as passed to ``jax.jit``.
    """
    findings: List[Finding] = []
    jaxpr = closed_jaxpr.jaxpr
    in_avals = [v.aval for v in jaxpr.invars]
    out_avals = [v.aval for v in jaxpr.outvars]

    # map argnum -> slice of flat invars
    offsets = np.cumsum([0] + list(arg_leaf_counts))
    donated = set()
    for argnum in donate_argnums:
        donated.update(range(offsets[argnum], offsets[argnum + 1]))

    # greedy aval matching: donated inputs claim outputs first (that is the
    # aliasing XLA will attempt), then undonated-large inputs look for
    # leftovers.
    free_out: Dict[Tuple, int] = {}
    for aval in out_avals:
        k = _aval_key(aval)
        free_out[k] = free_out.get(k, 0) + 1

    def claim(aval) -> bool:
        k = _aval_key(aval)
        if free_out.get(k, 0) > 0:
            free_out[k] -= 1
            return True
        return False

    for i in sorted(donated):
        if i >= len(in_avals):
            continue
        aval = in_avals[i]
        if not claim(aval):
            findings.append(Finding(
                rule_id=DONATION_UNUSABLE.rule_id, path=f"<trace:{name}>",
                line=0, severity=DONATION_UNUSABLE.severity,
                message=f"donated input #{i} {_aval_key(aval)} has no "
                        "matching output to alias — donation is dropped",
                fix_hint=DONATION_UNUSABLE.fix_hint))

    for i, aval in enumerate(in_avals):
        if i in donated or _aval_bytes(aval) < big_bytes:
            continue
        if claim(aval):
            findings.append(Finding(
                rule_id=UNDONATED_ACCUMULATOR.rule_id, path=f"<trace:{name}>",
                line=0, severity=UNDONATED_ACCUMULATOR.severity,
                message=f"input #{i} {_aval_key(aval)} "
                        f"({_aval_bytes(aval)} B) has a matching output but "
                        "is not donated — peak HBM holds both copies",
                fix_hint=UNDONATED_ACCUMULATOR.fix_hint))
    return findings


# ---------------------------------------------------------------------------
# retrace signatures
# ---------------------------------------------------------------------------

def trace_signature(args: Sequence[Any], kwargs: Optional[Dict] = None) -> Tuple:
    """Hashable abstraction of one call's signature: pytree structure +
    (shape, dtype) per array leaf, literal value per static leaf — the same
    identity jit uses to decide whether to retrace."""
    import jax

    leaves, treedef = jax.tree.flatten((tuple(args), kwargs or {}))
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append(("array", tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append(("static", repr(leaf)))
    return (str(treedef), tuple(sig))


def check_retrace(name: str, arg_sets: Sequence[Sequence[Any]],
                  max_signatures: int = 1) -> List[Finding]:
    sigs = {trace_signature(args) for args in arg_sets}
    if len(sigs) <= max_signatures:
        return []
    return [Finding(
        rule_id=RETRACE_HAZARD.rule_id, path=f"<trace:{name}>", line=0,
        severity=RETRACE_HAZARD.severity,
        message=f"{len(arg_sets)} representative input sets produce "
                f"{len(sigs)} distinct trace signatures "
                f"(expected <= {max_signatures}) — each is a recompile",
        fix_hint=RETRACE_HAZARD.fix_hint)]


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def trace_and_check(fn, *args, name: Optional[str] = None,
                    donate_argnums: Sequence[int] = (),
                    big_bytes: int = 1 << 20,
                    canonical: Optional[Sequence[str]] = None,
                    topology_sizes: Optional[Dict[str, int]] = None,
                    **kwargs) -> List[Finding]:
    """Trace ``fn(*args, **kwargs)`` and run the full jaxpr audit.

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct`` trees —
    nothing is executed, only traced.
    """
    import jax

    name = name or getattr(fn, "__name__", "fn")
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    auditor = JaxprAuditor(name, canonical=canonical,
                           topology_sizes=topology_sizes)
    auditor.walk(closed.jaxpr)
    leaf_counts = [len(jax.tree.leaves(a)) for a in args]
    findings = auditor.findings + check_donation(
        name, closed, leaf_counts, donate_argnums, big_bytes=big_bytes)
    return sort_findings(findings)
