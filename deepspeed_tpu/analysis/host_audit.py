"""Layer F: cross-host divergence & host-seam concurrency audit.

The two classic multi-host killers have no runtime signal on a one-host
dev box: (1) a collective launched under a condition derived from *host
identity* (rank, process index, hostname, env) deadlocks the fleet the
first time a second host exists — every host must issue the identical
collective sequence; (2) the repo's six-plus worker threads (async
checkpoint, NVMe queues, swapper groups, watchdog, tune controller)
cross the host seam through shared state and locks, and an inversion or
an unguarded publish only manifests under real multi-host timing. Layer
F makes both static, in the Layer-A mold (pure AST, no jax import, runs
in milliseconds under the tier-1 gate):

**Cross-host divergence pass** (over ``comm/``, ``runtime/zero/``,
``moe/``, ``sequence/``, ``runtime/pipe/``, ``checkpoint/``):

- ``rank-divergent-collective`` — a collective launch (``dist.*``,
  ``jax.lax`` collectives, ``ppermute``, ``barrier``) reachable only
  under a rank/host-identity-derived condition, including the
  early-return form (``if rank != 0: return`` … collective). The
  :data:`SANCTIONED_RANK0` registry names the audited legitimate sites;
  a registry entry that no longer matches anything is itself reported
  (stale sanctions must not accumulate).
- ``unordered-collective-iteration`` — collective launches or
  bucket/plan construction driven by iteration over a ``set`` (or other
  unordered producer like ``os.listdir``): Python set order is
  hash-seed-dependent, so two hosts silently build different launch
  orders.

**Host-seam concurrency pass** (over the whole package): builds the
static thread/lock graph — which functions run on worker threads
(``Thread(target=...)``/``executor.submit`` closure, per module), which
locks exist (creation sites), which lock acquisitions nest (directly or
through same-module calls made while holding a lock):

- ``lock-order-inversion`` — a cycle in the acquisition-order graph.
- ``unguarded-shared-mutation`` — generalizes Layer A's
  ``unguarded-worker-state``: ANY function (not just the thread target)
  assigning, outside a lock, shared state that a worker-reachable
  function reads.
- ``blocking-under-lock`` — a blocking call (``Future.result``,
  ``device_get``/``block_until_ready``, aio/Event ``wait``, ``join``,
  ``sleep``, or any collective) while holding a lock: the lock's
  critical section inherits the block, and a collective under a lock is
  cross-host deadlock bait.

The static half is validated dynamically by two harnesses: the
**virtual multi-host divergence harness** (:func:`virtual_host_ledgers`
/ :func:`diff_host_ledgers`) re-traces registered entry specs once per
virtual host with patched rank identity and diffs the per-host
``CollectiveLedger`` sequences, and **lockdep-lite**
(``analysis/lockdep.py``) records real acquisition order under the
chaos/durability suites and cross-checks it against the static graph
(:func:`crosscheck_observed`).

Findings carry the ``<host:`` path marker (``<host:<repo-relative
file>>`` for static findings, ``<host:virtual:<entry>>`` for harness
findings) so the baseline machinery treats Layer F as its own layer.
Per-line suppression is the shared ``# dstpu: ignore[rule-id]``.
"""

from __future__ import annotations

import ast
import contextlib
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import (Finding, SEVERITY_ERROR, SEVERITY_WARNING, dedupe,
                       sort_findings)
from .registry import LAYER_HOSTS, Rule, register
from .ast_rules import (ModuleContext, _callee, _is_lock_guard,
                        _last_segment, dotted_name)

HOST_PREFIX = "<host:"

#: packages the divergence pass walks — the collective-launching surface
#: a second host must replay identically (ISSUE: comm, zero, moe,
#: sequence, pipe, checkpoint). The concurrency pass runs repo-wide.
DIVERGENCE_DIRS = (
    "deepspeed_tpu/comm",
    "deepspeed_tpu/runtime/zero",
    "deepspeed_tpu/moe",
    "deepspeed_tpu/sequence",
    "deepspeed_tpu/runtime/pipe",
    "deepspeed_tpu/checkpoint",
)

# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------
RANK_DIVERGENT = register(Rule(
    rule_id="rank-divergent-collective", layer=LAYER_HOSTS,
    severity=SEVERITY_ERROR,
    description="Collective launch reachable only under a rank/host-"
                "identity condition (get_rank/process_index/hostname/env) "
                "— the other hosts block forever on the launch this host "
                "skipped",
    fix_hint="launch the collective unconditionally on every rank and "
             "guard only the host-side I/O; if the site is genuinely "
             "uniform-by-construction, add it to "
             "analysis/host_audit.py SANCTIONED_RANK0 with a reason",
))

UNORDERED_ITER = register(Rule(
    rule_id="unordered-collective-iteration", layer=LAYER_HOSTS,
    severity=SEVERITY_ERROR,
    description="Collective launches or bucket/plan construction driven "
                "by iteration over a set/unordered producer — hash-seed-"
                "dependent order silently desyncs the cross-host launch "
                "sequence",
    fix_hint="iterate sorted(...) (or an explicitly ordered list) so "
             "every host builds the identical sequence",
))

LOCK_INVERSION = register(Rule(
    rule_id="lock-order-inversion", layer=LAYER_HOSTS,
    severity=SEVERITY_ERROR,
    description="Cycle in the static lock acquisition graph (lock B "
                "taken while holding A on one path, A while holding B on "
                "another) — a classic cross-thread deadlock",
    fix_hint="impose one global acquisition order (document it on the "
             "lock attributes) or collapse the critical sections onto a "
             "single lock",
))

UNGUARDED_SHARED = register(Rule(
    rule_id="unguarded-shared-mutation", layer=LAYER_HOSTS,
    severity=SEVERITY_WARNING,
    description="Assignment, outside a lock, to shared state that a "
                "worker thread reads (generalizes unguarded-worker-state "
                "beyond the thread target itself to every cross-thread "
                "writer)",
    fix_hint="hold the owning lock around the assignment, or publish "
             "through a queue/Future handoff the worker consumes",
))

BLOCKING_UNDER_LOCK = register(Rule(
    rule_id="blocking-under-lock", layer=LAYER_HOSTS,
    severity=SEVERITY_WARNING,
    description="Blocking call (Future.result/device_get/"
                "block_until_ready/wait/join/sleep or a collective) while "
                "holding a lock — every thread contending the lock "
                "inherits the stall, and a collective under a lock can "
                "deadlock across hosts",
    fix_hint="snapshot the shared state under the lock, release it, then "
             "block; never launch collectives or device syncs inside a "
             "critical section",
))


# ---------------------------------------------------------------------------
# sanctioned-rank-0 registry
# ---------------------------------------------------------------------------
#: (path suffix, enclosing function, collective last-segment) -> reason.
#: The audited legitimate rank-conditional collective sites: places where
#: every rank reaches the launch by construction and only the host-side
#: work is rank-gated, but the guard structure makes that invisible to
#: the AST pass. Entries are load-bearing: one that stops matching any
#: finding is reported stale (the shrink-only discipline of the lint
#: baselines, applied to sanctions). Workflow: docs/STATIC_ANALYSIS.md.
SANCTIONED_RANK0: Dict[Tuple[str, str, str], str] = {
}


def _sanction_key(path: str, fn_name: str, collective: str
                  ) -> Optional[Tuple[str, str, str]]:
    norm = path.replace("\\", "/")
    for (suffix, fn, coll), _reason in SANCTIONED_RANK0.items():
        if norm.endswith(suffix) and fn == fn_name and coll == collective:
            return (suffix, fn, coll)
    return None


# ---------------------------------------------------------------------------
# shared collective-launch detection
# ---------------------------------------------------------------------------
#: call last-segments that are unambiguously collective launches
_COLLECTIVE_LAUNCH_SEGS = {
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "all_to_all_single", "ppermute", "pshuffle", "psum", "psum_scatter",
    "pmean", "pmax", "pmin", "broadcast", "barrier", "monitored_barrier",
    "inference_all_reduce", "sync_global_devices",
}
#: ambiguous last-segments (functools.reduce, list gather helpers...)
#: that only count as collectives with a comm-namespace prefix
_COLLECTIVE_AMBIGUOUS_SEGS = {"reduce", "gather", "scatter", "send", "recv"}
_COMM_NS_RE = re.compile(r"(^|\.)(dist|comm|_comm|lax|jax\.lax)\.")


def _is_collective_launch(name: Optional[str]) -> bool:
    if not name:
        return False
    seg = _last_segment(name)
    if seg in _COLLECTIVE_LAUNCH_SEGS:
        return True
    return seg in _COLLECTIVE_AMBIGUOUS_SEGS and bool(
        _COMM_NS_RE.search(name + "."))


def _collective_calls(node: ast.AST) -> Iterable[ast.Call]:
    """Collective launches anywhere under ``node`` (nested defs skipped —
    they get their own scan)."""
    for child in ast.walk(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and child is not node:
            continue
        if isinstance(child, ast.Call) and \
                _is_collective_launch(_callee(child)):
            yield child


# ---------------------------------------------------------------------------
# rank/host-identity taint
# ---------------------------------------------------------------------------
_IDENTITY_CALL_SEGS = {"get_rank", "process_index", "get_local_rank",
                       "gethostname", "getfqdn"}
_IDENTITY_CALL_DOTTED = {"platform.node", "os.uname", "socket.gethostname",
                         "socket.getfqdn"}
#: attribute names that carry host identity wherever they live
_IDENTITY_ATTR_RE = re.compile(
    r"^(rank|global_rank|local_rank|process_index|node_rank|host|hostname)$")
#: env keys that are per-host by convention; uniform config env vars
#: (feature flags) deliberately do NOT taint
_IDENTITY_ENV_RE = re.compile(r"(RANK|HOST|NODE|SLURM|COORD|MASTER)", re.I)


def _env_key_is_identity(call: ast.Call) -> bool:
    for arg in list(call.args) + [k.value for k in call.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return bool(_IDENTITY_ENV_RE.search(arg.value))
    return True  # dynamic key: assume identity


def _call_is_identity(call: ast.Call) -> bool:
    name = _callee(call)
    if not name:
        return False
    seg = _last_segment(name)
    if seg in _IDENTITY_CALL_SEGS:
        return True
    if any(name == d or name.endswith("." + d)
           for d in _IDENTITY_CALL_DOTTED):
        return True
    if seg == "getenv" or (seg == "get" and name.endswith("environ.get")):
        return _env_key_is_identity(call)
    return False


def _expr_tainted(expr: ast.AST, tainted_names: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _call_is_identity(node):
            return True
        if isinstance(node, ast.Name) and node.id in tainted_names:
            return True
        if isinstance(node, ast.Attribute) and \
                _IDENTITY_ATTR_RE.match(node.attr):
            return True
        if isinstance(node, ast.Subscript):
            base = dotted_name(node.value)
            if base and base.endswith("environ") and isinstance(
                    node.slice, ast.Constant) and isinstance(
                    node.slice.value, str):
                if _IDENTITY_ENV_RE.search(node.slice.value):
                    return True
    return False


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Local names carrying rank/host identity — fixpoint over simple
    assignments (``rank = dist.get_rank()``, ``is_zero = rank == 0``).
    Parameters NAMED like identity (``def save(rank):``) are seeded too:
    in the audited dirs a ``rank`` argument is always the caller's
    ``get_rank()`` threaded through."""
    tainted: Set[str] = set()
    fn_args = getattr(fn, "args", None)
    if fn_args is not None:
        for a in (list(fn_args.posonlyargs) + list(fn_args.args)
                  + list(fn_args.kwonlyargs)):
            if _IDENTITY_ATTR_RE.match(a.arg):
                tainted.add(a.arg)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            targets: List[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and getattr(node, "value", None) is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None or not _expr_tainted(value, tainted):
                continue
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    if isinstance(e, ast.Name) and e.id not in tainted:
                        tainted.add(e.id)
                        changed = True
    return tainted


# ---------------------------------------------------------------------------
# rank-divergent-collective
# ---------------------------------------------------------------------------
def _body_terminates(body: Sequence[ast.stmt]) -> bool:
    """True when control cannot fall out of ``body``'s end (the
    ``if rank != 0: return`` early-exit shape)."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(last, ast.If):
        return _body_terminates(last.body) and _body_terminates(last.orelse)
    return False


def _check_rank_divergence(ctx: ModuleContext) -> Iterable[Finding]:
    matched_sanctions: Set[Tuple[str, str, str]] = set()

    def scan_fn(fn):
        tainted = _tainted_names(fn)

        def emit(call: ast.Call, guard_line: int):
            coll = _last_segment(_callee(call)) or "?"
            key = _sanction_key(ctx.path, fn.name, coll)
            if key is not None:
                matched_sanctions.add(key)
                return
            yield Finding(
                rule_id=RANK_DIVERGENT.rule_id, path=ctx.path,
                line=call.lineno, severity=RANK_DIVERGENT.severity,
                message=f"{coll}() in {fn.name}() is reachable only under "
                        f"the rank/host-identity condition at line "
                        f"{guard_line} — other hosts never launch it",
                fix_hint=RANK_DIVERGENT.fix_hint)

        def walk(body: Sequence[ast.stmt], guard_line: Optional[int]):
            g = guard_line
            for stmt in body:
                if isinstance(stmt, ast.If):
                    test_tainted = _expr_tainted(stmt.test, tainted)
                    inner = stmt.lineno if test_tainted else g
                    yield from walk(stmt.body, inner)
                    yield from walk(stmt.orelse, inner)
                    if test_tainted and (_body_terminates(stmt.body)
                                         or _body_terminates(stmt.orelse)):
                        # one side returns/raises: the fallthrough only
                        # runs on the ranks the test let through
                        g = g if g is not None else stmt.lineno
                    continue
                if isinstance(stmt, ast.While):
                    inner = stmt.lineno \
                        if _expr_tainted(stmt.test, tainted) else g
                    yield from walk(stmt.body, inner)
                    yield from walk(stmt.orelse, g)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    yield from walk(stmt.body, g)
                    yield from walk(stmt.orelse, g)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    yield from walk(stmt.body, g)
                    continue
                if isinstance(stmt, ast.Try):
                    yield from walk(stmt.body, g)
                    for h in stmt.handlers:
                        yield from walk(h.body, g)
                    yield from walk(stmt.orelse, g)
                    yield from walk(stmt.finalbody, g)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs scanned on their own
                if g is not None:
                    for call in _collective_calls(stmt):
                        yield from emit(call, g)
                # conditional expressions on identity inside a plain
                # statement: `dist.barrier() if rank == 0 else None`
                for node in ast.walk(stmt):
                    if isinstance(node, ast.IfExp) and \
                            _expr_tainted(node.test, tainted):
                        for sub in (node.body, node.orelse):
                            for call in _collective_calls(sub):
                                yield from emit(call, node.lineno)

        yield from walk(fn.body, None)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from scan_fn(node)
    for key in SANCTIONED_RANK0:
        suffix, fn, coll = key
        if ctx.path.replace("\\", "/").endswith(suffix) \
                and key not in matched_sanctions:
            yield Finding(
                rule_id=RANK_DIVERGENT.rule_id, path=ctx.path, line=0,
                severity=SEVERITY_WARNING,
                message=f"stale SANCTIONED_RANK0 entry ({suffix!r}, "
                        f"{fn!r}, {coll!r}) matches no finding — remove "
                        "it from analysis/host_audit.py",
                fix_hint="sanctions shrink like baselines: delete entries "
                         "whose site was fixed or deleted")


# ---------------------------------------------------------------------------
# unordered-collective-iteration
# ---------------------------------------------------------------------------
_UNORDERED_PRODUCER_SEGS = {"set", "frozenset", "listdir", "scandir",
                            "glob", "iglob", "keys", "difference", "union",
                            "intersection", "symmetric_difference"}
_ORDERED_WRAPPER_SEGS = {"sorted", "list", "tuple", "enumerate"}
_PLAN_NAME_RE = re.compile(r"(bucket|plan|schedule|order)", re.I)


def _iterable_is_unordered(node: ast.AST, set_names: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in set_names
    if isinstance(node, ast.Call):
        seg = _last_segment(_callee(node))
        if seg in _ORDERED_WRAPPER_SEGS and seg != "list":
            return False
        if seg == "list" and node.args:
            return _iterable_is_unordered(node.args[0], set_names)
        if seg == "keys":
            # dicts are insertion-ordered; flag only set-typed receivers
            recv = node.func.value if isinstance(node.func, ast.Attribute) \
                else None
            return isinstance(recv, ast.Name) and recv.id in set_names
        if seg in _UNORDERED_PRODUCER_SEGS:
            return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
        # set algebra: a | b, a & b, a - b on set-typed names
        return _iterable_is_unordered(node.left, set_names) \
            or _iterable_is_unordered(node.right, set_names)
    return False


def _set_typed_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if isinstance(node.value, (ast.Set, ast.SetComp)) or (
                    isinstance(node.value, ast.Call)
                    and _last_segment(_callee(node.value))
                    in ("set", "frozenset")):
                names.add(node.targets[0].id)
    return names


def _builds_plan(body: Sequence[ast.stmt]) -> Optional[ast.AST]:
    """A bucket/plan-construction statement inside a loop body: an
    append/extend/add on (or a subscript-store into) a *_bucket/*_plan/
    *_order/*_schedule name."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr in (
                    "append", "extend", "add", "setdefault"):
                target = dotted_name(node.func.value)
                if target and _PLAN_NAME_RE.search(target):
                    return node
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = dotted_name(t.value)
                        if base and _PLAN_NAME_RE.search(base):
                            return node
    return None


def _check_unordered_iteration(ctx: ModuleContext) -> Iterable[Finding]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        set_names = _set_typed_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            if not _iterable_is_unordered(node.iter, set_names):
                continue
            colls = list(_collective_calls(node))
            plan = _builds_plan(node.body)
            if colls:
                coll = _last_segment(_callee(colls[0])) or "?"
                yield Finding(
                    rule_id=UNORDERED_ITER.rule_id, path=ctx.path,
                    line=node.lineno, severity=UNORDERED_ITER.severity,
                    message=f"{coll}() launched from a loop over an "
                            f"unordered iterable in {fn.name}() — launch "
                            "order differs per host",
                    fix_hint=UNORDERED_ITER.fix_hint)
            elif plan is not None:
                yield Finding(
                    rule_id=UNORDERED_ITER.rule_id, path=ctx.path,
                    line=node.lineno, severity=UNORDERED_ITER.severity,
                    message=f"bucket/plan construction in {fn.name}() "
                            "iterates an unordered iterable — the derived "
                            "collective order differs per host",
                    fix_hint=UNORDERED_ITER.fix_hint)


# ---------------------------------------------------------------------------
# static thread/lock graph
# ---------------------------------------------------------------------------
_LOCK_CTOR_SEGS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
                   "Condition"}
_LOCKISH_ATTR_RE = re.compile(
    r"(lock|mutex|cond|cv|sem|queue|event|stop)", re.I)
_BLOCKING_SEGS = {"result", "wait", "join", "sleep", "device_get",
                  "block_until_ready", "effects_barrier"}


class HostGraph:
    """The static thread/lock picture of the repo, accumulated over every
    audited module — the artifact ``tools/thread_report.py`` renders and
    lockdep-lite cross-checks.

    - ``lock_sites``: lock key -> [(path, line)] creation sites
      (``self._lock = threading.Lock()`` under class C -> ``C._lock``)
    - ``edges``: (held key, acquired key) -> (path, line) first witness
    - ``workers``: (path, worker fn) -> sorted attrs the worker reads
    - ``threads``: [(path, line, target name)] spawn sites
    """

    def __init__(self):
        self.lock_sites: Dict[str, List[Tuple[str, int]]] = {}
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.workers: Dict[Tuple[str, str], List[str]] = {}
        self.threads: List[Tuple[str, int, str]] = []

    def add_lock_site(self, key: str, path: str, line: int) -> None:
        self.lock_sites.setdefault(key, []).append((path, line))

    def add_edge(self, held: str, acquired: str, path: str, line: int
                 ) -> None:
        if held != acquired:
            self.edges.setdefault((held, acquired), (path, line))

    def key_for_site(self, path: str, line: int) -> Optional[str]:
        norm = path.replace("\\", "/")
        for key, sites in self.lock_sites.items():
            for p, ln in sites:
                if ln == line and (norm.endswith(p.replace("\\", "/"))
                                   or p.replace("\\", "/").endswith(norm)):
                    return key
        return None

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the acquisition graph (DFS, deduped by
        node set — the graph is tiny)."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        seen_sets: Set[frozenset] = set()
        out: List[List[str]] = []

        def dfs(node: str, stack: List[str], on_stack: Set[str]):
            for nxt in adj.get(node, []):
                if nxt in on_stack:
                    cyc = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(cyc)
                    continue
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

        for start in sorted(adj):
            dfs(start, [start], {start})
        return out


def _tree_memo(tree: ast.AST, key: str, build):
    """Per-tree cache for derived structures (class map, function defs,
    worker targets). The five rules and the graph builder each re-derive
    the same structures per module; caching on the tree node itself keeps
    the lifetime tied to the tree (no id-reuse hazard, no global growth)."""
    cache = getattr(tree, "_host_memo", None)
    if cache is None:
        cache = {}
        try:
            tree._host_memo = cache  # type: ignore[attr-defined]
        except AttributeError:
            return build()
    if key not in cache:
        cache[key] = build()
    return cache[key]


def _enclosing_class_map(tree: ast.AST) -> Dict[int, str]:
    """id(function node) -> enclosing class name."""
    return _tree_memo(tree, "cls_of", lambda: _enclosing_class_map_u(tree))


def _enclosing_class_map_u(tree: ast.AST) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for child in ast.walk(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    out.setdefault(id(child), node.name)
    return out


def _lock_key(expr: ast.AST, cls: Optional[str], mod: str) -> Optional[str]:
    """Normalized graph key for a lock expression: ``self._lock`` under
    class C -> ``C._lock``; module global ``_LOCK`` -> ``mod._LOCK``."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return None
    parts = name.split(".")
    if parts[0] in ("self", "cls") and len(parts) >= 2:
        owner = cls or mod
        return f"{owner}.{'.'.join(parts[1:])}"
    if len(parts) == 1:
        return f"{mod}.{parts[0]}"
    return name


def _module_basename(path: str) -> str:
    return os.path.splitext(os.path.basename(path))[0]


def _function_defs(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    return _tree_memo(tree, "defs", lambda: _function_defs_u(tree))


def _function_defs_u(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _called_names(stmt: ast.AST) -> Iterable[Tuple[ast.Call, str]]:
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            seg = _last_segment(_callee(node))
            if seg:
                yield node, seg


def _direct_worker_targets(tree: ast.AST) -> Dict[str, Tuple[int, str]]:
    """worker fn name -> (spawn line, spawn kind) for Thread(target=...)
    and executor.submit(fn)/apply_async(fn) sites."""
    return _tree_memo(tree, "workers", lambda: _direct_worker_targets_u(tree))


def _direct_worker_targets_u(tree: ast.AST) -> Dict[str, Tuple[int, str]]:
    out: Dict[str, Tuple[int, str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        seg = _last_segment(_callee(node))
        if seg == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _last_segment(dotted_name(kw.value))
                    if target:
                        out.setdefault(target, (node.lineno, "Thread"))
        elif seg in ("submit", "apply_async") and node.args:
            target = _last_segment(dotted_name(node.args[0]))
            if target:
                out.setdefault(target, (node.lineno, seg))
    return out


def _worker_closure(tree: ast.AST,
                    roots: Optional[Set[str]] = None) -> Set[str]:
    """Worker-reachable function names: direct Thread/submit targets (or
    the given ``roots``) plus every same-module function they
    (transitively) call by name."""
    if roots is None:
        return _tree_memo(tree, "closure",
                          lambda: _worker_closure_u(tree, None))
    return _worker_closure_u(tree, roots)


def _worker_closure_u(tree: ast.AST,
                      roots: Optional[Set[str]] = None) -> Set[str]:
    defs = _function_defs(tree)
    reachable = set(_direct_worker_targets(tree)) \
        if roots is None else set(roots)
    frontier = [n for n in reachable if n in defs]
    while frontier:
        name = frontier.pop()
        for fn in defs.get(name, []):
            for _call, seg in _called_names(fn):
                if seg in defs and seg not in reachable:
                    reachable.add(seg)
                    frontier.append(seg)
    return reachable


def _attr_reads(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                out.add(node.attr)
    return out


def _build_module_graph(ctx: ModuleContext, graph: HostGraph) -> None:
    mod = _module_basename(ctx.path)
    cls_of = _enclosing_class_map(ctx.tree)
    defs = _function_defs(ctx.tree)

    # lock creation sites: self.X = threading.Lock() / _LOCK = Lock()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        if _last_segment(_callee(node.value)) not in _LOCK_CTOR_SEGS:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id in ("self", "cls"):
                cls = _class_of_line(ctx.tree, node.lineno)
                key = f"{cls or mod}.{t.attr}"
                graph.add_lock_site(key, ctx.path, node.lineno)
            elif isinstance(t, ast.Name):
                graph.add_lock_site(f"{mod}.{t.id}", ctx.path, node.lineno)

    # thread spawn sites
    for name, (line, kind) in _direct_worker_targets(ctx.tree).items():
        graph.threads.append((ctx.path, line, name))

    # worker read-sets
    for name in _worker_closure(ctx.tree):
        for fn in defs.get(name, []):
            reads = _attr_reads(fn)
            if reads:
                key = (ctx.path, name)
                merged = set(graph.workers.get(key, [])) | reads
                graph.workers[key] = sorted(merged)

    # per-function: locks acquired directly (with-blocks)
    def direct_locks(fn) -> Set[str]:
        cls = cls_of.get(id(fn))
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lock_guard(item):
                        key = _lock_key(item.context_expr, cls, mod)
                        if key:
                            out.add(key)
        return out

    fn_locks: Dict[str, Set[str]] = {}
    for name, fns in defs.items():
        s: Set[str] = set()
        for fn in fns:
            s |= direct_locks(fn)
        fn_locks[name] = s

    # transitive: locks reachable through same-module calls
    closure: Dict[str, Set[str]] = {n: set(s) for n, s in fn_locks.items()}
    changed = True
    while changed:
        changed = False
        for name, fns in defs.items():
            for fn in fns:
                for _call, seg in _called_names(fn):
                    if seg in closure and not (closure[seg]
                                               <= closure[name]):
                        closure[name] |= closure[seg]
                        changed = True

    # acquisition edges: nested withs + calls made while holding a lock
    def walk_held(fn, cls):
        def rec(body, held: List[str]):
            for stmt in body:
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    keys = [k for k in
                            (_lock_key(i.context_expr, cls, mod)
                             for i in stmt.items if _is_lock_guard(i))
                            if k]
                    for k in keys:
                        if held:
                            graph.add_edge(held[-1], k, ctx.path,
                                           stmt.lineno)
                    rec(stmt.body, held + keys)
                    continue
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if held:
                    for call, seg in _called_names(stmt):
                        for k in closure.get(seg, ()):
                            graph.add_edge(held[-1], k, ctx.path,
                                           call.lineno)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        rec(sub, held)
                for h in getattr(stmt, "handlers", []):
                    rec(h.body, held)

        rec(fn.body, [])

    for name, fns in defs.items():
        for fn in fns:
            walk_held(fn, cls_of.get(id(fn)))


def _class_of_line(tree: ast.AST, line: int) -> Optional[str]:
    best = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.lineno <= line <= (
                getattr(node, "end_lineno", None) or node.lineno):
            if best is None or node.lineno > best[0]:
                best = (node.lineno, node.name)
    return best[1] if best else None


# ---------------------------------------------------------------------------
# lock-order-inversion (global, over the accumulated graph)
# ---------------------------------------------------------------------------
def _inversion_findings(graph: HostGraph) -> Iterable[Finding]:
    for cyc in graph.cycles():
        edge = (cyc[0], cyc[1])
        path, line = graph.edges.get(edge, ("", 0))
        yield Finding(
            rule_id=LOCK_INVERSION.rule_id, path=path or cyc[0],
            line=line, severity=LOCK_INVERSION.severity,
            message="lock acquisition cycle: " + " -> ".join(cyc),
            fix_hint=LOCK_INVERSION.fix_hint)


# ---------------------------------------------------------------------------
# unguarded-shared-mutation
# ---------------------------------------------------------------------------
def _check_unguarded_shared(ctx: ModuleContext) -> Iterable[Finding]:
    all_targets = _direct_worker_targets(ctx.tree)
    # Long-running Thread targets only: executor.submit tasks get a
    # happens-before edge at submission (the queue handoff publishes every
    # prior write) and their internals are Layer A's unguarded-worker-
    # state. A `# dstpu: ignore[unguarded-shared-mutation]` on the spawn
    # line sanctions a whole worker whose exclusion is protocol-level
    # (e.g. the escalation saver, which runs only once the main thread is
    # declared wedged).
    direct = {n for n, (line, kind) in all_targets.items()
              if kind == "Thread"
              and not ctx.suppressed(line, UNGUARDED_SHARED.rule_id)}
    reachable = _worker_closure(ctx.tree, roots=direct)
    if not reachable:
        return
    defs = _function_defs(ctx.tree)
    worker_reads: Set[str] = set()
    for name in reachable:
        for fn in defs.get(name, []):
            worker_reads |= _attr_reads(fn)
    worker_reads = {a for a in worker_reads
                    if not _LOCKISH_ATTR_RE.search(a)}
    if not worker_reads:
        return

    for name, fns in defs.items():
        if name in direct or name.startswith("__"):
            # direct targets are Layer A's unguarded-worker-state;
            # dunders (init/enter) run before the thread exists
            continue
        for fn in fns:
            def rec(body, guarded):
                for stmt in body:
                    if isinstance(stmt, (ast.With, ast.AsyncWith)):
                        rec(stmt.body, guarded or any(
                            _is_lock_guard(i) for i in stmt.items))
                        continue
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if not guarded:
                        attr = _self_assign_attr(stmt)
                        if attr and attr in worker_reads:
                            findings.append(Finding(
                                rule_id=UNGUARDED_SHARED.rule_id,
                                path=ctx.path, line=stmt.lineno,
                                severity=UNGUARDED_SHARED.severity,
                                message=f"{name}() assigns shared "
                                        f"attribute {attr!r} outside a "
                                        "lock while a worker thread reads "
                                        "it",
                                fix_hint=UNGUARDED_SHARED.fix_hint))
                    for a in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, a, None)
                        if sub:
                            rec(sub, guarded)
                    for h in getattr(stmt, "handlers", []):
                        rec(h.body, guarded)

            findings: List[Finding] = []
            rec(fn.body, False)
            yield from findings


def _self_assign_attr(stmt: ast.AST) -> Optional[str]:
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return None
    for t in targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            while isinstance(e, (ast.Subscript, ast.Starred)):
                e = e.value
            if isinstance(e, ast.Attribute) and isinstance(
                    e.value, ast.Name) and e.value.id in ("self", "cls"):
                return e.attr
    return None


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------
def _check_blocking_under_lock(ctx: ModuleContext) -> Iterable[Finding]:
    mod = _module_basename(ctx.path)
    cls_of = _enclosing_class_map(ctx.tree)
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = cls_of.get(id(fn))

        def scan_calls(node, held: List[str]):
            for call, seg in _called_names(node):
                name = _callee(call) or seg
                blocking = seg in _BLOCKING_SEGS \
                    or _is_collective_launch(name)
                if not blocking:
                    continue
                if seg == "wait":
                    # Condition.wait releases the lock it guards:
                    # `with self._cv: self._cv.wait()` is the sanctioned
                    # pattern, not a stall
                    recv = _lock_key(
                        call.func.value, cls, mod) if isinstance(
                        call.func, ast.Attribute) else None
                    if recv is not None and recv in held:
                        continue
                yield Finding(
                    rule_id=BLOCKING_UNDER_LOCK.rule_id,
                    path=ctx.path, line=call.lineno,
                    severity=BLOCKING_UNDER_LOCK.severity,
                    message=f"{seg}() called in {fn.name}() while "
                            f"holding {held[-1]} — the critical "
                            "section inherits the block",
                    fix_hint=BLOCKING_UNDER_LOCK.fix_hint)

        def rec(body, held: List[str]):
            for stmt in body:
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    keys = [k for k in
                            (_lock_key(i.context_expr, cls, mod)
                             for i in stmt.items if _is_lock_guard(i))
                            if k]
                    yield from rec(stmt.body, held + keys)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if isinstance(stmt, (ast.If, ast.While, ast.For,
                                     ast.AsyncFor, ast.Try)):
                    # header expressions here; bodies via recursion (a
                    # single full walk would double-count nested calls)
                    if held:
                        for header in (getattr(stmt, "test", None),
                                       getattr(stmt, "iter", None)):
                            if header is not None:
                                yield from scan_calls(header, held)
                    for a in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, a, None)
                        if sub:
                            yield from rec(sub, held)
                    for h in getattr(stmt, "handlers", []):
                        yield from rec(h.body, held)
                    continue
                if held:
                    yield from scan_calls(stmt, held)

        yield from rec(fn.body, [])


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def _in_divergence_scope(path: str) -> bool:
    norm = path.replace("\\", "/")
    return any(d in norm for d in DIVERGENCE_DIRS)


def audit_host_files(paths: Optional[List[str]] = None
                     ) -> Tuple[List[Finding], HostGraph]:
    """Run both static passes -> (findings with ``<host:`` markers,
    the accumulated :class:`HostGraph`)."""
    from .cli import _relpath, collect_py_files, _package_root

    files = collect_py_files(paths or [_package_root()])
    graph = HostGraph()
    findings: List[Finding] = []
    for path in files:
        rel = _relpath(path)
        if "analysis/" in rel.replace("\\", "/"):
            continue  # the auditor's own fixtures/self-matches
        try:
            with open(path, "r", encoding="utf-8") as fh:
                ctx = ModuleContext(rel, fh.read())
        except (SyntaxError, OSError):
            continue  # Layer A owns syntax errors
        raw: List[Finding] = []
        if _in_divergence_scope(rel):
            raw += list(_check_rank_divergence(ctx))
            raw += list(_check_unordered_iteration(ctx))
        raw += list(_check_unguarded_shared(ctx))
        raw += list(_check_blocking_under_lock(ctx))
        _build_module_graph(ctx, graph)
        findings += [f for f in raw
                     if not ctx.suppressed(f.line, f.rule_id)]
    findings += list(_inversion_findings(graph))
    marked = [Finding(rule_id=f.rule_id, path=f"{HOST_PREFIX}{f.path}>",
                      line=f.line, severity=f.severity, message=f.message,
                      fix_hint=f.fix_hint)
              for f in findings]
    return sort_findings(dedupe(marked)), graph


def run_host_layer(paths: Optional[List[str]] = None) -> List[Finding]:
    """CLI entry (``dstpu lint --hosts``): findings only."""
    findings, _graph = audit_host_files(paths)
    return findings


def build_host_graph(paths: Optional[List[str]] = None) -> HostGraph:
    """The static thread/lock graph alone (``tools/thread_report.py``
    and the lockdep cross-check)."""
    _findings, graph = audit_host_files(paths)
    return graph


# ---------------------------------------------------------------------------
# virtual multi-host divergence harness
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def as_virtual_host(host: int, hosts: int):
    """Present the process as virtual host ``host`` of ``hosts`` to every
    ``dist.get_rank()``/``get_world_size()`` caller. The 8-device CPU
    mesh stays one real process — only the *identity* the host-side code
    branches on is partitioned, which is exactly the surface
    ``rank-divergent-collective`` audits.

    Limit (documented in docs/STATIC_ANALYSIS.md): code that calls
    ``jax.process_index()`` directly, bypassing the comm frontend, does
    not see the virtual identity; the static pass taints those calls
    instead."""
    from ..comm import comm as comm_mod
    from .. import comm as comm_pkg

    saved = (comm_mod.get_rank, comm_mod.get_world_size,
             comm_pkg.get_rank, comm_pkg.get_world_size)
    comm_mod.get_rank = lambda: host
    comm_mod.get_world_size = lambda: hosts
    comm_pkg.get_rank = comm_mod.get_rank
    comm_pkg.get_world_size = comm_mod.get_world_size
    try:
        yield
    finally:
        (comm_mod.get_rank, comm_mod.get_world_size,
         comm_pkg.get_rank, comm_pkg.get_world_size) = saved


def _ledger_sequence(ledger) -> List[Tuple[str, int, Tuple, int]]:
    return [(r["op"], r["wire_bytes"], tuple(r["axes"]), r["count"])
            for r in ledger.records]


def virtual_host_ledgers(name: str, hosts: int = 2):
    """Trace entry spec ``name`` once per virtual host and return the
    per-host ``CollectiveLedger`` list. The spec is REBUILT per host
    (``build_spec`` resets topology and constructs fresh closures) so jax
    cannot serve a cached trace that would record nothing for hosts > 0;
    an empty ledger on one host while another recorded launches is
    reported by :func:`diff_host_ledgers` rather than silently passing."""
    import jax

    from .. import comm as dist
    from .entry_points import build_spec

    ledgers = []
    for h in range(hosts):
        with as_virtual_host(h, hosts):
            spec = build_spec(name)
            ledger = dist.CollectiveLedger()
            with dist.record_into(ledger):
                with spec.mesh_ctx():
                    jax.eval_shape(spec.fn, *spec.args)
        ledgers.append(ledger)
    return ledgers


def diff_host_ledgers(ledgers) -> List[str]:
    """Divergences between per-host collective launch sequences
    (kind/bytes/axes/order must be identical). Empty list = identical."""
    if not ledgers:
        return []
    seqs = [_ledger_sequence(l) for l in ledgers]
    ref = seqs[0]
    out: List[str] = []
    counts = {len(s) for s in seqs}
    if len(counts) > 1 and 0 in counts and max(counts) > 0:
        out.append("host ledger empty while another host recorded "
                   "launches — stale trace cache or rank-gated trace")
    for h, seq in enumerate(seqs[1:], start=1):
        if len(seq) != len(ref):
            out.append(f"host {h} launched {len(seq)} collective(s), "
                       f"host 0 launched {len(ref)}")
        for i, (a, b) in enumerate(zip(ref, seq)):
            if a != b:
                out.append(f"host {h} launch #{i}: {b} != host 0's {a}")
    return out


def audit_virtual_hosts(names: Iterable[str], hosts: int = 2
                        ) -> List[Finding]:
    """Run the divergence harness over entry specs -> findings (empty
    when every host's launch sequence is identical)."""
    findings: List[Finding] = []
    for name in names:
        for msg in diff_host_ledgers(virtual_host_ledgers(name, hosts)):
            findings.append(Finding(
                rule_id=RANK_DIVERGENT.rule_id,
                path=f"{HOST_PREFIX}virtual:{name}>", line=0,
                severity=SEVERITY_ERROR,
                message=f"virtual {hosts}-host divergence: {msg}",
                fix_hint=RANK_DIVERGENT.fix_hint))
    return sort_findings(findings)
