"""Pluggable rule registry.

Every rule — AST (Layer A), jaxpr (Layer B) or post-SPMD compiled artifact
(Layer C) — registers a :class:`Rule`
descriptor here. The CLI's ``--fix-hints`` and the docs table are generated
from this registry, and suppression comments (``# dstpu: ignore[rule-id]``)
are validated against it, so adding a rule is: write the checker, register
the descriptor, add fixtures. Nothing else to touch.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

LAYER_AST = "ast"
LAYER_JAXPR = "jaxpr"
LAYER_SPMD = "spmd"
LAYER_SCHEDULE = "schedule"
LAYER_FEASIBILITY = "feasibility"
LAYER_HOSTS = "hosts"


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    layer: str           # LAYER_AST | LAYER_JAXPR | LAYER_SPMD |
                         # LAYER_SCHEDULE | LAYER_FEASIBILITY | LAYER_HOSTS
    severity: str        # default severity of findings from this rule
    description: str     # one-liner for docs / --fix-hints
    fix_hint: str        # how to fix, rendered with the finding

    def __post_init__(self):
        assert self.layer in (LAYER_AST, LAYER_JAXPR, LAYER_SPMD,
                              LAYER_SCHEDULE, LAYER_FEASIBILITY,
                              LAYER_HOSTS), self.layer


_RULES: Dict[str, Rule] = {}
# Layer-A checkers: fn(module_ctx) -> iterable[Finding]; registered per rule
# so the linter discovers them from the registry rather than a hardcoded list.
_AST_CHECKERS: Dict[str, Callable] = {}


def register(rule: Rule, checker: Optional[Callable] = None) -> Rule:
    if rule.rule_id in _RULES:
        raise ValueError(f"duplicate rule id {rule.rule_id!r}")
    _RULES[rule.rule_id] = rule
    if checker is not None:
        _AST_CHECKERS[rule.rule_id] = checker
    return rule


def ast_rule(rule: Rule):
    """Decorator form for Layer-A checkers."""
    def wrap(fn):
        register(rule, fn)
        return fn
    return wrap


def get(rule_id: str) -> Rule:
    return _RULES[rule_id]


def is_known(rule_id: str) -> bool:
    return rule_id in _RULES


def all_rules() -> List[Rule]:
    return [_RULES[k] for k in sorted(_RULES)]


def ast_checkers() -> Dict[str, Callable]:
    return dict(_AST_CHECKERS)
