"""Baseline (grandfathering) for lint findings.

The suite fails CI on any finding NOT present in the checked-in baseline
(``tools/lint_baseline.json``). The workflow:

- new violation      -> CI fails; fix it (preferred) or suppress inline
- grandfathered one  -> listed in the baseline; fix it and regenerate with
  ``dstpu lint --write-baseline`` so the file only ever shrinks
- baseline entry whose finding no longer fires -> reported as *stale* so
  the file cannot rot

Keys are ``path::rule_id::message`` (no line numbers — those shift on every
unrelated edit and would churn the file)."""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .findings import Finding, sort_findings


TRACE_PREFIX = "<trace:"
SPMD_PREFIX = "<spmd:"
SCHED_PREFIX = "<sched:"
PLAN_PREFIX = "<plan:"
HOST_PREFIX = "<host:"

#: the six layers a finding can come from, keyed by its path marker.
#: Layers don't always run together (the jaxpr audit needs a working JAX,
#: the SPMD/schedule/feasibility audits additionally compile), so baseline
#: diffs must only cover the layers that actually ran — otherwise an
#: AST-only run reports grandfathered jaxpr/spmd/schedule/feasibility
#: entries as stale, and ``--write-baseline`` silently drops them.
LAYER_KEYS = ("ast", "jaxpr", "spmd", "schedule", "feasibility", "hosts")

#: path markers of the entry-point layers — the layers whose baseline
#: entries are keyed by a registered entry-point name rather than a
#: source file. Layer F ("hosts") is deliberately ABSENT: its ``<host:``
#: marker wraps a repo-relative file path (or ``virtual:<entry>`` for the
#: divergence harness), so its baseline entries must never be pruned by
#: the unknown-entry-point sweep.
ENTRY_PREFIXES = {"jaxpr": TRACE_PREFIX, "spmd": SPMD_PREFIX,
                  "schedule": SCHED_PREFIX, "feasibility": PLAN_PREFIX}


def finding_layer(f: Finding) -> str:
    if f.path.startswith(TRACE_PREFIX):
        return "jaxpr"
    if f.path.startswith(SPMD_PREFIX):
        return "spmd"
    if f.path.startswith(SCHED_PREFIX):
        return "schedule"
    if f.path.startswith(PLAN_PREFIX):
        return "feasibility"
    if f.path.startswith(HOST_PREFIX):
        return "hosts"
    return "ast"


def entry_name(path: str) -> Optional[str]:
    """The registered entry-point name a ``<trace:...>``/``<spmd:...>``/
    ``<sched:...>`` finding path refers to; None for AST (file) paths."""
    for prefix in ENTRY_PREFIXES.values():
        if path.startswith(prefix) and path.endswith(">"):
            return path[len(prefix):-1]
    return None


def prune_unknown_entries(findings: List[Finding], known: Iterable[str]
                          ) -> Tuple[List[Finding], List[Finding]]:
    """Drop baseline entries whose path names an entry point that no
    longer exists in the registry -> (kept, pruned). Without this,
    ``--write-baseline`` on a partial layer run carries grandfathered
    findings for deleted specs forever (they can never fire again, so
    they can never go stale either)."""
    known = set(known)
    kept, pruned = [], []
    for f in findings:
        name = entry_name(f.path)
        (pruned if name is not None and name not in known else kept).append(f)
    return kept, pruned


def by_layer(findings: List[Finding]) -> Dict[str, List[Finding]]:
    out: Dict[str, List[Finding]] = {k: [] for k in LAYER_KEYS}
    for f in findings:
        out[finding_layer(f)].append(f)
    return out


def split_layers(findings: List[Finding]) -> Tuple[List[Finding], ...]:
    """-> (ast, jaxpr, spmd, schedule, feasibility, hosts) findings, by
    path marker."""
    layers = by_layer(findings)
    return tuple(layers[k] for k in LAYER_KEYS)


def default_baseline_path() -> str:
    # tools/lint_baseline.json at the repo root (two levels up from here)
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "tools", "lint_baseline.json")


def load_baseline(path: str) -> List[Finding]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return [Finding.from_dict(d) for d in data.get("findings", [])]


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "comment": "Grandfathered dstpu-lint findings. Shrink, never grow: "
                   "fix the finding and regenerate with "
                   "`dstpu lint --write-baseline`.",
        "findings": [f.to_dict() for f in sort_findings(findings)],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def diff_against_baseline(findings: List[Finding], baseline: List[Finding]
                          ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new_findings, stale_baseline_entries)."""
    # multiset semantics: two identical findings on different lines of one
    # file need two baseline entries
    def multiset(fs: List[Finding]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in fs:
            out[f.baseline_key()] = out.get(f.baseline_key(), 0) + 1
        return out

    base = multiset(baseline)
    new: List[Finding] = []
    for f in sort_findings(findings):
        k = f.baseline_key()
        if base.get(k, 0) > 0:
            base[k] -= 1
        else:
            new.append(f)
    cur = multiset(findings)
    stale: List[Finding] = []
    for f in sort_findings(baseline):
        k = f.baseline_key()
        if cur.get(k, 0) > 0:
            cur[k] -= 1
        else:
            stale.append(f)
    return new, stale
