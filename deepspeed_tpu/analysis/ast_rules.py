"""Layer A: AST lint rules for TPU-graph invariants.

Pure-Python static analysis — no jax import, safe to run on every file of
the repo in milliseconds. The rules encode the failure modes that break
"hot path stays inside XLA":

- ``host-sync-in-trace``   device->host pulls inside traced code
- ``nondet-in-trace``      Python-side nondeterminism baked in at trace time
- ``traced-branch``        Python control flow on traced array values
- ``missing-donate``       step/optimizer jits that don't donate their state
- ``literal-axis-name``    collective axis names as bare string literals

*Traced scope* is detected structurally: a function is considered traced if
it (a) carries a ``jit``/``pjit``-style decorator, or (b) is passed (by
name, anywhere in the module) to a tracing wrapper — ``jax.jit``,
``shard_map``, ``jax.grad``, ``jax.vmap``, ``jax.lax.scan`` etc. Nested
``def``s inside a traced function are traced too. This over-approximates
(a helper traced in one call site may also run eagerly elsewhere) which is
the right bias for an invariant gate; per-line suppression is
``# dstpu: ignore[rule-id]``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from .findings import Finding, SEVERITY_ERROR, SEVERITY_WARNING, dedupe, sort_findings
from .registry import LAYER_AST, Rule, ast_checkers, ast_rule

# Keep in sync with runtime/topology.py MESH_AXES (not imported: Layer A must
# not import jax, and topology pulls jax at module level).
CANONICAL_AXIS_NAMES = ("pipe", "data", "mics", "expert", "seq", "model")

# Callables that trace their function argument into a jaxpr.
_TRACE_WRAPPERS = {
    "jit", "pjit", "shard_map", "grad", "value_and_grad", "vmap", "pmap",
    "checkpoint", "remat", "make_jaxpr", "scan", "fori_loop", "while_loop",
    "cond", "switch", "custom_vjp", "custom_jvp", "eval_shape",
}
_JIT_NAMES = {"jit", "pjit"}

# Collective call names (jax.lax primitives + the deepspeed_tpu.comm
# frontend) whose axis arguments must use the canonical constants.
_COLLECTIVE_FNS = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "psum_scatter", "all_to_all", "axis_index", "axis_size", "all_reduce",
    "reduce_scatter", "broadcast", "gather", "scatter", "reduce",
    "all_to_all_single", "inference_all_reduce",
}
_AXIS_KWARGS = {"axis", "axes", "axis_name", "sequence_process_group"}

_SUPPRESS_RE = re.compile(r"#\s*dstpu:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")
_STEPPY_RE = re.compile(r"(step|update|apply|train|optim)", re.IGNORECASE)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for Attribute chains, 'psum' for Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _callee(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


class ModuleContext:
    """Parsed module + traced-scope map handed to every Layer-A checker."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._traced_names = self._collect_traced_names()
        self.traced_scopes = self._collect_traced_scopes()

    # -- traced-scope discovery ------------------------------------------
    def _collect_traced_names(self) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            seg = _last_segment(_callee(node))
            if seg not in _TRACE_WRAPPERS:
                continue
            # functools.partial(jax.jit, fn) and jax.jit(fn) both put the
            # traced callable in the positional args; scan/while take it
            # first too.
            for arg in node.args:
                target = _last_segment(dotted_name(arg))
                if target:
                    names.add(target)
        return names

    def _has_trace_decorator(self, fn: ast.AST) -> bool:
        for dec in getattr(fn, "decorator_list", []):
            node = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(dec, ast.Call) and _last_segment(_callee(dec)) == "partial":
                for a in dec.args:
                    if _last_segment(dotted_name(a)) in _TRACE_WRAPPERS:
                        return True
            if _last_segment(dotted_name(node)) in _TRACE_WRAPPERS:
                return True
        return False

    def _collect_traced_scopes(self) -> List[ast.AST]:
        scopes = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in self._traced_names or self._has_trace_decorator(node):
                    scopes.append(node)
            elif isinstance(node, ast.Lambda):
                pass  # lambdas are traced via their wrapper call; handled below
        # lambdas passed directly to trace wrappers
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    _last_segment(_callee(node)) in _TRACE_WRAPPERS:
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        scopes.append(arg)
        return scopes

    # -- helpers ----------------------------------------------------------
    def traced_walk(self) -> Iterable[Tuple[ast.AST, ast.AST]]:
        """(scope, node) for every node inside a traced scope."""
        for scope in self.traced_scopes:
            for node in ast.walk(scope):
                yield scope, node

    def scope_params(self, scope: ast.AST) -> Set[str]:
        args = scope.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return set(names)

    def suppressed(self, line: int, rule_id: str) -> bool:
        if not 1 <= line <= len(self.lines):
            return False
        m = _SUPPRESS_RE.search(self.lines[line - 1])
        if not m:
            return False
        if m.group(1) is None:
            return True  # bare '# dstpu: ignore' silences everything
        ids = {s.strip() for s in m.group(1).split(",")}
        return rule_id in ids


def _finding(rule: Rule, ctx: ModuleContext, node: ast.AST, message: str) -> Finding:
    return Finding(rule_id=rule.rule_id, path=ctx.path,
                   line=getattr(node, "lineno", 0), severity=rule.severity,
                   message=message, fix_hint=rule.fix_hint)


# ---------------------------------------------------------------------------
# host-sync-in-trace
# ---------------------------------------------------------------------------
HOST_SYNC = Rule(
    rule_id="host-sync-in-trace", layer=LAYER_AST, severity=SEVERITY_ERROR,
    description="Device->host pull (float()/.item()/np.asarray/print/"
                "jax.device_get) inside traced code blocks the XLA pipeline",
    fix_hint="keep the value on device (jnp ops); move host readout outside "
             "the jit boundary, or use jax.debug.print for tracing output",
)

_NP_PULLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array"}
_DEVICE_GET = {"jax.device_get", "device_get"}


@ast_rule(HOST_SYNC)
def check_host_sync(ctx: ModuleContext):
    for scope, node in ctx.traced_walk():
        if not isinstance(node, ast.Call):
            continue
        name = _callee(node)
        seg = _last_segment(name)
        if seg == "print" and name == "print":
            yield _finding(HOST_SYNC, ctx, node,
                           "print() in traced code runs at trace time only "
                           "(or forces a host sync on a tracer)")
        elif seg == "item":
            yield _finding(HOST_SYNC, ctx, node,
                           ".item() forces a device->host transfer inside "
                           "traced code")
        elif name in _NP_PULLS:
            yield _finding(HOST_SYNC, ctx, node,
                           f"{name}() materializes a tracer on host inside "
                           "traced code")
        elif name in _DEVICE_GET:
            yield _finding(HOST_SYNC, ctx, node,
                           "jax.device_get inside traced code is a hidden "
                           "host sync")
        elif name in ("float", "int", "bool") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in ctx.scope_params(scope):
                yield _finding(HOST_SYNC, ctx, node,
                               f"{name}() on traced argument "
                               f"{arg.id!r} concretizes a tracer")


# ---------------------------------------------------------------------------
# nondet-in-trace
# ---------------------------------------------------------------------------
NONDET = Rule(
    rule_id="nondet-in-trace", layer=LAYER_AST, severity=SEVERITY_ERROR,
    description="Python-side nondeterminism (time.time, random.*, "
                "datetime.now) inside traced code is frozen at trace time",
    fix_hint="thread randomness through jax.random keys / pass timestamps "
             "in as arguments",
)

_NONDET_EXACT = {
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "uuid.uuid4", "os.urandom",
}
_NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")


@ast_rule(NONDET)
def check_nondet(ctx: ModuleContext):
    for _scope, node in ctx.traced_walk():
        if not isinstance(node, ast.Call):
            continue
        name = _callee(node)
        if not name:
            continue
        if name in _NONDET_EXACT or any(name.startswith(p) for p in _NONDET_PREFIXES):
            yield _finding(NONDET, ctx, node,
                           f"{name}() in traced code is evaluated once at "
                           "trace time and baked into the graph")


# ---------------------------------------------------------------------------
# traced-branch
# ---------------------------------------------------------------------------
TRACED_BRANCH = Rule(
    rule_id="traced-branch", layer=LAYER_AST, severity=SEVERITY_ERROR,
    description="Python if/while on a traced array value raises "
                "TracerBoolConversionError or silently branches at trace time",
    fix_hint="use jax.lax.cond / jnp.where / jax.lax.select on device values",
)

_ARRAY_NS_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.")


def _contains_array_call(expr: ast.AST) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = _callee(node)
            if name and any(name.startswith(p) for p in _ARRAY_NS_PREFIXES):
                return name
    return None


@ast_rule(TRACED_BRANCH)
def check_traced_branch(ctx: ModuleContext):
    for _scope, node in ctx.traced_walk():
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            name = _contains_array_call(node.test)
            if name:
                kind = {"If": "if", "While": "while",
                        "IfExp": "conditional expression"}[type(node).__name__]
            else:
                continue
            yield _finding(TRACED_BRANCH, ctx, node,
                           f"Python {kind} branches on {name}(...) — a traced "
                           "array value")
        elif isinstance(node, ast.Assert):
            name = _contains_array_call(node.test)
            if name:
                yield _finding(TRACED_BRANCH, ctx, node,
                               f"assert on {name}(...) concretizes a traced "
                               "value (and vanishes under -O)")


# ---------------------------------------------------------------------------
# missing-donate
# ---------------------------------------------------------------------------
MISSING_DONATE = Rule(
    rule_id="missing-donate", layer=LAYER_AST, severity=SEVERITY_WARNING,
    description="jit of a step/update/apply function without donate_argnums "
                "doubles peak HBM: input state and output state coexist",
    fix_hint="pass donate_argnums=(0,) (or donate_argnames) for the state "
             "argument of step/optimizer jits",
)


@ast_rule(MISSING_DONATE)
def check_missing_donate(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(_callee(node)) not in _JIT_NAMES:
            continue
        if not node.args:
            continue
        target = _last_segment(dotted_name(node.args[0]))
        if not target or not _STEPPY_RE.search(target):
            continue
        kw = {k.arg for k in node.keywords if k.arg}
        if not ({"donate_argnums", "donate_argnames"} & kw):
            yield _finding(MISSING_DONATE, ctx, node,
                           f"jit({target}) on a step/optimizer path without "
                           "donate_argnums/donate_argnames")


# ---------------------------------------------------------------------------
# literal-axis-name
# ---------------------------------------------------------------------------
LITERAL_AXIS = Rule(
    rule_id="literal-axis-name", layer=LAYER_AST, severity=SEVERITY_WARNING,
    description="Bare mesh-axis string literal at a collective call site; "
                "axis names must come from deepspeed_tpu.utils.groups "
                "constants so topology refactors stay atomic",
    fix_hint="import DATA_AXIS/MODEL_AXIS/EXPERT_AXIS/SEQ_AXIS/PIPE_AXIS/"
             "MICS_AXIS (or the compound *_AXES tuples) from "
             "deepspeed_tpu.utils.groups",
)


def _literal_axis_values(node: ast.AST) -> List[str]:
    """Canonical-axis string constants in an axis-argument expression."""
    out = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value in CANONICAL_AXIS_NAMES:
            out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            out.extend(_literal_axis_values(elt))
    return out


# axis_index/axis_size take the axis as their FIRST argument; every other
# collective takes the operand first and the axis second.
_AXIS_ARG0_FNS = {"axis_index", "axis_size"}


@ast_rule(LITERAL_AXIS)
def check_literal_axis(ctx: ModuleContext):
    # collective call sites: positional axis args + axis kwargs
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                _last_segment(_callee(node)) in _COLLECTIVE_FNS:
            start = 0 if _last_segment(_callee(node)) in _AXIS_ARG0_FNS else 1
            exprs = list(node.args[start:]) + \
                [k.value for k in node.keywords if k.arg in _AXIS_KWARGS]
            for expr in exprs:
                for val in _literal_axis_values(expr):
                    yield _finding(LITERAL_AXIS, ctx, node,
                                   f"collective called with literal axis "
                                   f"{val!r}")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # literal axis defaults in signatures (axis: AxisNames = "data")
            args = node.args
            pos = args.posonlyargs + args.args
            for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                    args.defaults):
                if arg.arg in _AXIS_KWARGS:
                    for val in _literal_axis_values(default):
                        yield Finding(
                            rule_id=LITERAL_AXIS.rule_id, path=ctx.path,
                            line=default.lineno, severity=LITERAL_AXIS.severity,
                            message=f"parameter {arg.arg!r} of "
                                    f"{node.name}() defaults to literal axis "
                                    f"{val!r}",
                            fix_hint=LITERAL_AXIS.fix_hint)
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None and arg.arg in _AXIS_KWARGS:
                    for val in _literal_axis_values(default):
                        yield Finding(
                            rule_id=LITERAL_AXIS.rule_id, path=ctx.path,
                            line=default.lineno, severity=LITERAL_AXIS.severity,
                            message=f"parameter {arg.arg!r} of "
                                    f"{node.name}() defaults to literal axis "
                                    f"{val!r}",
                            fix_hint=LITERAL_AXIS.fix_hint)
        elif isinstance(node, ast.ClassDef):
            # dataclass-style field defaults: `axis: str = "data"`
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.target.id in _AXIS_KWARGS:
                    for val in _literal_axis_values(stmt.value):
                        yield Finding(
                            rule_id=LITERAL_AXIS.rule_id, path=ctx.path,
                            line=stmt.lineno, severity=LITERAL_AXIS.severity,
                            message=f"field {stmt.target.id!r} of class "
                                    f"{node.name} defaults to literal axis "
                                    f"{val!r}",
                            fix_hint=LITERAL_AXIS.fix_hint)


# ---------------------------------------------------------------------------
# telemetry-hot-path-sync
# ---------------------------------------------------------------------------
TELEMETRY_HOT_SYNC = Rule(
    rule_id="telemetry-hot-path-sync", layer=LAYER_AST, severity=SEVERITY_ERROR,
    description="Device sync (block_until_ready/effects_barrier/device_get) "
                "or host-callback primitive in traced step code or in "
                "telemetry/timer span hooks — telemetry must be zero-overhead "
                "when off and fence-point-only when on",
    fix_hint="sample at declared fence points via telemetry.clock.fence() "
             "(the one sanctioned sync); never sync per phase/step in span "
             "hooks; host callbacks (pure_callback/io_callback/"
             "debug.callback) do not belong in step graphs",
)

_SYNC_CALLS = {"block_until_ready", "effects_barrier"}
_HOST_CALLBACK_CALLS = {"pure_callback", "io_callback"}
# jax.debug.callback's last attribute segment is just "callback" — too
# generic to match by segment, so it matches on the dotted suffix
_HOST_CALLBACK_DOTTED = ("debug.callback",)


def _is_host_callback(name: Optional[str]) -> bool:
    if not name:
        return False
    return (_last_segment(name) in _HOST_CALLBACK_CALLS
            or any(name == d or name.endswith("." + d)
                   for d in _HOST_CALLBACK_DOTTED))
# modules bound by the fence-point contract: every span/timer hook in them
# runs on the per-step hot path of whoever enables telemetry
_HOT_PATH_MODULES = ("deepspeed_tpu/telemetry/", "deepspeed_tpu/utils/timer.py")


@ast_rule(TELEMETRY_HOT_SYNC)
def check_telemetry_hot_sync(ctx: ModuleContext):
    # 1) traced scopes anywhere in the repo: a sync or host-callback
    #    primitive inside the step graph (host-sync-in-trace covers the
    #    device_get/np.asarray pulls; this covers the rest)
    for _scope, node in ctx.traced_walk():
        if not isinstance(node, ast.Call):
            continue
        name = _callee(node)
        seg = _last_segment(name)
        if seg in _SYNC_CALLS:
            yield _finding(TELEMETRY_HOT_SYNC, ctx, node,
                           f"{seg}() inside traced code serializes the "
                           "dispatch pipeline")
        elif _is_host_callback(name):
            yield _finding(TELEMETRY_HOT_SYNC, ctx, node,
                           f"{name}() injects a host callback into the step "
                           "graph — telemetry must stay host-side")
    # 2) telemetry/timer modules: syncs allowed ONLY inside fence()
    norm = ctx.path.replace("\\", "/")
    if not any(m in norm for m in _HOT_PATH_MODULES):
        return
    fence_nodes = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "fence":
            fence_nodes.update(id(n) for n in ast.walk(node))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) in fence_nodes:
            continue
        name = _callee(node)
        seg = _last_segment(name)
        if seg in _SYNC_CALLS or name in _DEVICE_GET:
            yield _finding(TELEMETRY_HOT_SYNC, ctx, node,
                           f"{seg}() in a telemetry/timer module outside "
                           "clock.fence() — span hooks must never sync")


# ---------------------------------------------------------------------------
# unguarded-worker-state
# ---------------------------------------------------------------------------
UNGUARDED_WORKER_STATE = Rule(
    rule_id="unguarded-worker-state", layer=LAYER_AST,
    severity=SEVERITY_WARNING,
    description="A host-side worker thread (Thread(target=...), "
                "executor.submit(fn)) mutating shared object/module state "
                "outside a lock or queue handoff races the main thread — "
                "async checkpoint workers, NVMe queues, watchdogs and "
                "elastic agents must publish through a Lock/Condition or a "
                "Queue.put",
    fix_hint="hold the owning object's lock (`with self._lock:`) around the "
             "mutation, or hand the value to the consumer through a "
             "queue.Queue instead of assigning shared attributes",
)

# context-manager names that count as a lock guard; matched against the
# last dotted segment of the `with` expression (self._lock, cls.mutex,
# threading.Lock(), cond, semaphore ...)
_LOCK_NAME_RE = re.compile(r"(lock|mutex|cond|cv|sem)", re.IGNORECASE)


def _is_lock_guard(item: ast.withitem) -> bool:
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    seg = _last_segment(name)
    return bool(seg and _LOCK_NAME_RE.search(seg))


def _worker_fn_names(tree: ast.AST) -> Set[str]:
    """Function names handed to Thread(target=...) or executor.submit(fn)
    anywhere in the module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        seg = _last_segment(_callee(node))
        if seg == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _last_segment(dotted_name(kw.value))
                    if target:
                        names.add(target)
        elif seg in ("submit", "apply_async"):
            if node.args:
                target = _last_segment(dotted_name(node.args[0]))
                if target:
                    names.add(target)
    return names


def _shared_mutation_target(node: ast.AST, local_names: Set[str],
                            global_names: Set[str]) -> Optional[str]:
    """Dotted name of the shared state a statement mutates, or None.

    Shared = an attribute chain (self.x, module.flag, self.d[k]) or a
    module-global the worker declared ``global``. Plain locals are private
    to the worker and never flagged."""
    targets: List[ast.AST] = []
    if isinstance(node, (ast.Assign,)):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    else:
        return None
    flat: List[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    for t in flat:
        while isinstance(t, (ast.Subscript, ast.Starred)):
            t = t.value
        if isinstance(t, ast.Attribute):
            return dotted_name(t) or t.attr
        if isinstance(t, ast.Name) and t.id in global_names \
                and t.id not in local_names:
            return t.id
    return None


@ast_rule(UNGUARDED_WORKER_STATE)
def check_unguarded_worker_state(ctx: ModuleContext):
    workers = _worker_fn_names(ctx.tree)
    if not workers:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in workers:
            continue
        global_names: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Global):
                global_names.update(n.names)
        local_names = {a.arg for a in (node.args.posonlyargs + node.args.args
                                       + node.args.kwonlyargs)}

        def scan(body, guarded):
            for stmt in body:
                if isinstance(stmt, ast.With):
                    yield from scan(stmt.body,
                                    guarded or any(_is_lock_guard(i)
                                                   for i in stmt.items))
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue  # nested defs get their own worker analysis
                if not guarded:
                    shared = _shared_mutation_target(stmt, local_names,
                                                    global_names)
                    if shared is not None:
                        yield stmt, shared
                for child_body in (getattr(stmt, "body", []),
                                   getattr(stmt, "orelse", []),
                                   getattr(stmt, "finalbody", [])):
                    if child_body:
                        yield from scan(child_body, guarded)
                for handler in getattr(stmt, "handlers", []):
                    yield from scan(handler.body, guarded)

        for stmt, shared in scan(node.body, False):
            yield _finding(
                UNGUARDED_WORKER_STATE, ctx, stmt,
                f"worker {node.name}() mutates shared state {shared!r} "
                "outside a lock — racing the thread that reads it")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def lint_source(path: str, source: str) -> List[Finding]:
    try:
        ctx = ModuleContext(path, source)
    except SyntaxError as e:
        return [Finding(rule_id="syntax-error", path=path, line=e.lineno or 0,
                        severity=SEVERITY_ERROR, message=str(e.msg))]
    findings: List[Finding] = []
    for rule_id, checker in ast_checkers().items():
        for f in checker(ctx):
            if not ctx.suppressed(f.line, rule_id):
                findings.append(f)
    return sort_findings(dedupe(findings))


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(path, fh.read())
