"""lockdep-lite: instrumented locks that record real acquisition order.

The dynamic half of Layer F's host-seam concurrency pass
(``analysis/host_audit.py``): the static lock graph is an
over-approximation built from ``with`` nesting and same-module calls, so
it needs a ground-truth check — and a pure runtime detector needs the
static graph to see orders that never happened to interleave in a test
run. The shim closes the loop the way the kernel's lockdep does, scaled
to this repo's handful of host-side locks:

- :func:`install` swaps ``threading.Lock``/``RLock`` for wrappers that
  remember their **creation site** (``file:line`` — the same key the
  static graph records for ``self._lock = threading.Lock()``) and, on
  every acquire, record an ordered edge *held-top -> acquired* into a
  :class:`LockdepRegistry`, per real thread.
- :meth:`LockdepRegistry.cycles` finds inversions in the observed graph
  alone (the seeded-inversion reproducer).
- :func:`crosscheck` maps observed creation-site labels back to static
  lock keys via :meth:`HostGraph.key_for_site` and verifies the merged
  static+observed graph stays acyclic — an observed order contradicting
  the static order is exactly a latent inversion that one more thread
  interleaving would deadlock.

Used by the chaos/durability/autotuning test drives
(``tests/unit/analysis/test_host_audit.py``) and
``tools/thread_report.py``. Never imported by runtime code — zero
overhead outside the harness.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple


def _site_label(depth: int = 2) -> str:
    """``<repo-relative file>:<line>`` of the caller's caller — the lock
    construction site, matching the static graph's creation-site keys."""
    frame = sys._getframe(depth)
    path = frame.f_code.co_filename
    parts = path.replace("\\", "/").split("/")
    if "deepspeed_tpu" in parts:
        path = "/".join(parts[parts.index("deepspeed_tpu"):])
    else:
        path = "/".join(parts[-2:])
    return f"{path}:{frame.f_lineno}"


class LockdepRegistry:
    """Observed acquisition-order edges, per real thread."""

    def __init__(self):
        self._guard = threading.Lock()  # a REAL lock: the registry must
        # never record itself
        self._tls = threading.local()
        #: (held label, acquired label) -> (thread name, ordinal)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        #: label -> creation site count (several locks can share a site)
        self.locks: Dict[str, int] = {}
        self._ordinal = 0

    # -- bookkeeping called by the instrumented locks --------------------
    def note_created(self, label: str) -> None:
        with self._guard:
            self.locks[label] = self.locks.get(label, 0) + 1

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquired(self, label: str) -> None:
        held = self._held()
        if held and held[-1] != label:
            edge = (held[-1], label)
            with self._guard:
                if edge not in self.edges:
                    self._ordinal += 1
                    self.edges[edge] = (threading.current_thread().name,
                                        self._ordinal)
        held.append(label)

    def note_released(self, label: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == label:
                del held[i]
                break

    # -- analysis ---------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        return _find_cycles(set(self.edges))

    def observed_order(self) -> List[Tuple[str, str, str, int]]:
        """[(held, acquired, thread, ordinal)] sorted by first
        observation — the reviewable artifact ``thread_report.py``
        prints."""
        return sorted(((a, b, t, o)
                       for (a, b), (t, o) in self.edges.items()),
                      key=lambda r: r[3])


class InstrumentedLock:
    """Drop-in ``threading.Lock``/``RLock`` recording into a registry."""

    def __init__(self, registry: LockdepRegistry, label: str,
                 reentrant: bool = False):
        self._registry = registry
        self.label = label
        self._real = (threading._original_rlock() if reentrant
                      else threading._original_lock()) \
            if hasattr(threading, "_original_lock") else None
        if self._real is None:  # constructed outside install()
            import _thread
            self._real = _thread.RLock() if reentrant \
                else _thread.allocate_lock()
        registry.note_created(label)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout) if blocking \
            else self._real.acquire(False)
        if got:
            self._registry.note_acquired(self.label)
        return got

    def release(self):
        self._registry.note_released(self.label)
        self._real.release()

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") \
            else False

    def __getattr__(self, name):
        # stdlib pokes at lock internals (`_at_fork_reinit`,
        # `acquire_lock`...): forward anything we don't wrap
        if name == "_real":
            raise AttributeError(name)
        return getattr(self._real, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedLock {self.label}>"


@contextlib.contextmanager
def install(registry: Optional[LockdepRegistry] = None):
    """Swap ``threading.Lock``/``RLock`` for instrumented factories for
    the duration of the context; yields the registry. Locks created
    inside the context keep recording after it exits (their registry
    reference survives), so a subsystem constructed under ``install``
    can be driven afterwards — only the *construction* window is
    patched."""
    reg = registry or LockdepRegistry()
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    # stash originals where InstrumentedLock can reach the REAL ctors
    # even while the names are patched
    threading._original_lock = orig_lock
    threading._original_rlock = orig_rlock

    def make_lock():
        return InstrumentedLock(reg, _site_label(), reentrant=False)

    def make_rlock():
        return InstrumentedLock(reg, _site_label(), reentrant=True)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    try:
        yield reg
    finally:
        threading.Lock, threading.RLock = orig_lock, orig_rlock
        del threading._original_lock
        del threading._original_rlock


# ---------------------------------------------------------------------------
# cross-check against the static graph
# ---------------------------------------------------------------------------
def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    seen: Set[frozenset] = set()
    out: List[List[str]] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str]):
        for nxt in adj.get(node, []):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    out.append(cyc)
                continue
            stack.append(nxt)
            on_stack.add(nxt)
            dfs(nxt, stack, on_stack)
            on_stack.discard(nxt)
            stack.pop()

    for start in sorted(adj):
        dfs(start, [start], {start})
    return out


def map_observed_edges(registry: LockdepRegistry, graph
                       ) -> List[Tuple[str, str]]:
    """Observed (creation-site) edges translated to static lock keys;
    edges touching a lock the static graph does not know (jax internals,
    executor plumbing created under ``install``) are dropped — the
    cross-check only speaks where both sides have an opinion."""
    out: List[Tuple[str, str]] = []
    for (a, b) in registry.edges:
        ka = _label_to_key(a, graph)
        kb = _label_to_key(b, graph)
        if ka and kb and ka != kb:
            out.append((ka, kb))
    return out


def _label_to_key(label: str, graph) -> Optional[str]:
    path, _, line = label.rpartition(":")
    try:
        return graph.key_for_site(path, int(line))
    except ValueError:
        return None


def crosscheck(registry: LockdepRegistry, graph) -> List[str]:
    """Merge the static acquisition graph with the observed (mapped)
    edges and report contradictions: a cycle in the merged graph that is
    acyclic in each half alone means the runtime took an order the
    static graph's order cannot coexist with. Returns human-readable
    violation strings (empty = consistent)."""
    static_edges = set(graph.edges)
    observed = set(map_observed_edges(registry, graph))
    merged = static_edges | observed
    violations = []
    for cyc in _find_cycles(merged):
        cyc_edges = set(zip(cyc, cyc[1:]))
        if cyc_edges <= static_edges:
            continue  # purely static cycle: lock-order-inversion's job
        if cyc_edges <= observed:
            kind = "observed-only cycle"
        else:
            kind = "observed order contradicts static order"
        violations.append(f"{kind}: " + " -> ".join(cyc))
    return violations
