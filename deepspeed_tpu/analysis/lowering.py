"""THE lower-and-inspect path: jit -> lower -> compile -> reports.

Both consumers of compiled-artifact introspection go through here:

- **telemetry** (:mod:`deepspeed_tpu.telemetry.memory`) asks "how many
  bytes will this step use" for the memory watermark report;
- **Layer C** (:mod:`.spmd_audit`) audits the partitioned program — the
  GSPMD-inserted collectives, the replicated intermediates, the aliasing
  XLA actually performed, and the same memory analysis checked against the
  committed budgets in ``tools/memory_budgets.json``.

Keeping one path means the number telemetry prints at runtime and the
number the auditor gates on are *the same computation* — a budget that
holds in CI holds in the telemetry flush, byte for byte.

Everything here is host-side: ``lower().compile()`` never executes the
program, and on the CPU host platform (the audit mesh) compilation of the
tiny entry points is sub-second to a few seconds each.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

#: memory_analysis fields every report carries (when the backend exposes
#: them). ``alias_size_in_bytes`` counts donated bytes XLA actually reused.
MEMORY_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "alias_size_in_bytes",
                 "generated_code_size_in_bytes")


def memory_report(compiled) -> Optional[Dict[str, float]]:
    """Byte sizes from an XLA ``Compiled``'s ``memory_analysis()``;
    None when the backend doesn't expose it."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    out = {}
    for f in MEMORY_FIELDS:
        v = getattr(mem, f, None)
        if v is not None:
            out[f] = float(v)
    return out or None


@dataclasses.dataclass
class LoweredArtifact:
    """One entry point, lowered and compiled with its real shardings."""
    name: str
    closed_jaxpr: Any          # jax.core.ClosedJaxpr (source-of-truth graph)
    compiled: Any              # jax.stages.Compiled
    arg_leaf_counts: Tuple[int, ...]
    donate_argnums: Tuple[int, ...]
    _hlo_text: Optional[str] = None

    @property
    def hlo_text(self) -> str:
        """Post-SPMD, post-optimization HLO — per-device shapes, explicit
        collective instructions, the module-level ``input_output_alias``
        table. Cached: ``as_text`` re-renders on every call."""
        if self._hlo_text is None:
            self._hlo_text = self.compiled.as_text()
        return self._hlo_text

    def memory(self) -> Optional[Dict[str, float]]:
        return memory_report(self.compiled)


def lower_entry(fn, args: Sequence[Any], *, kwargs: Optional[Dict] = None,
                donate_argnums: Sequence[int] = (),
                jit_kwargs: Optional[Dict] = None,
                name: Optional[str] = None) -> LoweredArtifact:
    """Trace AND compile ``fn`` exactly as the runtime would jit it.

    ``args`` may be concrete (sharded) arrays or ``ShapeDtypeStruct``
    trees carrying shardings — either way the compile sees the real
    input shardings, so the partitioner's decisions match production.
    ``jit_kwargs`` carries the production jit's extra arguments
    (``in_shardings``/``out_shardings``) — donation aliasing is decided
    at lowering against the OUTPUT shardings, so auditing without them
    would report donations dropped that production keeps. Call under the
    entry point's mesh context when the function relies on an ambient
    mesh.
    """
    import jax

    kwargs = kwargs or {}
    name = name or getattr(fn, "__name__", "fn")
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    compiled = (jax.jit(fn, donate_argnums=tuple(donate_argnums),
                        **(jit_kwargs or {}))
                .lower(*args, **kwargs).compile())
    leaf_counts = tuple(len(jax.tree.leaves(a)) for a in args)
    return LoweredArtifact(name=name, closed_jaxpr=closed, compiled=compiled,
                           arg_leaf_counts=leaf_counts,
                           donate_argnums=tuple(donate_argnums))


def lower_and_report(jitfn, *abstract_args) -> Optional[Dict[str, float]]:
    """Lower+compile an already-jitted ``jitfn`` on abstract avals and
    report its memory analysis. Compilation is cached by signature, so
    calling this for a shape the step already ran is near-free; a NEW
    shape pays one compile — call it per entry point, not per step.

    (Telemetry's historical entry; kept here so telemetry and the Layer-C
    auditor provably share one lowering path.)"""
    try:
        compiled = jitfn.lower(*abstract_args).compile()
    except Exception:
        return None
    return memory_report(compiled)
