"""dstpu-lint: static analysis enforcing TPU-graph invariants.

Four layers (see docs/STATIC_ANALYSIS.md):

- **Layer A** (:mod:`.ast_rules`) — pure-AST rules, no jax import, runs on
  every file: hidden host syncs, trace-time nondeterminism, Python
  branching on traced values, undonated step jits, literal axis names.
- **Layer B** (:mod:`.trace_harness`, :mod:`.entry_points`) —
  ``trace_and_check`` traces real entry points via ``jax.make_jaxpr`` and
  walks the jaxpr: collective axis binding/topology agreement, donation
  aliasing, retrace-signature counting.
- **Layer C** (:mod:`.spmd_audit`, :mod:`.lowering`, :mod:`.budgets`) —
  lowers+compiles each entry point with its real mesh/shardings and
  audits the post-SPMD artifact: partitioner-inserted collectives
  (``implicit-reshard``), replicated large intermediates, full-param scan
  residuals, donations XLA actually dropped, and compiled memory bytes
  against the shrink-only ``tools/memory_budgets.json``.
- **Layer D** (:mod:`.schedule_audit`) — walks the same compiled
  artifact's instruction SCHEDULE: classifies every collective
  overlapped/exposed/serialized (dot/conv FLOP slack vs a per-platform
  bytes/flop ratio, ``while`` bodies trip-count-scaled), gates exposed
  bytes against the shrink-only ``tools/exposure_budgets.json``, and
  emits per-entry collective placement maps
  (``tools/collective_maps/``).

Findings are structured (:mod:`.findings`), rules pluggable
(:mod:`.registry`), and the gate diffs against ``tools/lint_baseline.json``
(:mod:`.baseline`). CLI: ``dstpu lint`` / ``python tools/dstpu_lint.py``.
"""

from .findings import Finding, SEVERITY_ERROR, SEVERITY_WARNING  # noqa: F401
from .registry import Rule, all_rules, ast_rule, register  # noqa: F401
from .ast_rules import lint_file, lint_source  # noqa: F401

__all__ = ["Finding", "Rule", "all_rules", "ast_rule", "register",
           "lint_file", "lint_source", "trace_and_check"]


def trace_and_check(*args, **kwargs):
    """Lazy re-export: Layer B needs jax; Layer A users must not pay for it."""
    from .trace_harness import trace_and_check as _tc
    return _tc(*args, **kwargs)
