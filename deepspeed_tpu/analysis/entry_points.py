"""Layer B entry-point audits over the framework's real traced paths.

Each audit builds a tiny-but-real instance of a hot path — engine train
step, ZeRO++ gather/partition micro step, MoE dispatch, ring attention,
Ulysses attention — traces it with :func:`trace_and_check`, and returns the
findings. These run on the CPU host platform (``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=8``, the same virtual mesh the
unit tests use); nothing executes, only traces.

``audit_entry_points()`` is what ``dstpu lint --jaxpr`` and the
``test_lint_clean`` CI gate call.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from .findings import Finding, SEVERITY_ERROR
from .trace_harness import check_retrace, trace_and_check

_TINY = dict(max_seq_len=32, vocab_size=256, remat=False)


def _tiny_engine(config_extra=None, **model_kw):
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2_model

    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }
    config.update(config_extra or {})
    model = gpt2_model("gpt2-tiny", **dict(_TINY, **model_kw))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def _batch(engine, batch=8, seq=16):
    import numpy as np
    ids = np.zeros((batch, seq), dtype=np.int32)
    return engine._prepare_batch({"input_ids": ids})


def audit_engine_step() -> List[Finding]:
    """The fused train step: collectives bound, state donated, and the step
    must not retrace across steps (same shapes -> one signature)."""
    import jax.numpy as jnp

    engine = _tiny_engine()
    batch = _batch(engine)
    lr = jnp.asarray(1e-3, jnp.float32)
    with engine.mesh:
        findings = trace_and_check(
            engine._train_step_fn, engine.state, batch, lr,
            donate_argnums=(0,), name="engine-train-step")
    findings += check_retrace(
        "engine-train-step",
        [(engine.state, batch, lr), (engine.state, batch, lr)])
    return findings


def audit_zero_gather_partition() -> List[Finding]:
    """ZeRO++ micro step — the whole-tree BARRIER schedule, the
    ``overlap_comm: false`` escape hatch (engine._build_zeropp_micro_barrier):
    every collective must ride the canonical dp axes and the donated grad
    accumulator must alias."""
    engine = _tiny_engine(config_extra={"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True, "overlap_comm": False}})
    assert engine._zeropp, "config did not enable the ZeRO++ path"
    batch = _batch(engine)
    micro = engine._build_zeropp_micro()
    assert not engine._overlap_active, \
        "overlap_comm: false must select the barrier schedule"
    with engine.mesh:
        return trace_and_check(
            micro, engine.state["grad_acc"],
            engine.state["loss_scale"]["cur_scale"], engine.state["params"],
            batch, donate_argnums=(0,), name="zero-gather-partition")


def audit_zeropp_micro_overlap() -> List[Finding]:
    """The layer-granular pipelined ZeRO++ micro step (ISSUE 3 tentpole,
    engine._build_zeropp_micro_overlap + models/transformer.py
    scan_blocks_pipelined + runtime/zero/overlap.py): double-buffered
    param prefetch in the forward scan carry, backward-interleaved
    gradient reduce-scatter. The audit enforces axis binding (every
    collective in both scan bodies rides canonical dp axes), donation
    aliasing on the grad accumulator, and a stable retrace signature —
    the schedule recompiling per step would erase the win it exists for."""
    engine = _tiny_engine(config_extra={"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True, "zero_quantized_gradients": True}})
    assert engine._zeropp, "config did not enable the ZeRO++ path"
    batch = _batch(engine)
    micro = engine._build_zeropp_micro()
    assert engine._overlap_active, (
        "overlap_comm (stage-3 default true) must select the pipelined "
        f"schedule; fell back: {engine._overlap_fallback}")
    gacc = engine.state["grad_acc"]
    scale = engine.state["loss_scale"]["cur_scale"]
    with engine.mesh:
        findings = trace_and_check(
            micro, gacc, scale, engine.state["params"], batch,
            donate_argnums=(0,), name="zeropp-micro-overlap")
    findings += check_retrace(
        "zeropp-micro-overlap",
        [(gacc, scale, engine.state["params"], batch),
         (gacc, scale, engine.state["params"], batch)])
    return findings


def audit_moe_dispatch() -> List[Finding]:
    """MoE dispatch/combine: the expert exchange is expressed as sharding
    constraints over the expert axis — those specs must name canonical axes
    of the configured topology."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.moe.layer import MoE
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import TopologyConfig

    topo = topo_mod.initialize(TopologyConfig(expert=2, data=-1), force=True)
    moe = MoE(hidden_size=16, intermediate_size=32, num_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0))
    x = jnp.zeros((4, 8, 16), jnp.float32)
    with topo.mesh:
        return trace_and_check(lambda p, t: moe(p, t)[0], params, x,
                               name="moe-dispatch")


def audit_ring_attention() -> List[Finding]:
    """Ring attention: the K/V rotation must ppermute over the canonical
    seq axis inside a shard_map whose mesh matches the global topology."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import TopologyConfig
    from deepspeed_tpu.sequence.ring_attention import ring_attention

    topo_mod.initialize(TopologyConfig(seq=2, data=-1), force=True)
    q = jnp.zeros((4, 8, 4, 8), jnp.float32)
    return trace_and_check(ring_attention, q, q, q, name="ring-attention")


def audit_ulysses_attention() -> List[Finding]:
    """Ulysses: the head-scatter/seq-gather all-to-alls over the seq axis."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import TopologyConfig
    from deepspeed_tpu.sequence.layer import ulysses_attention

    topo_mod.initialize(TopologyConfig(seq=2, data=-1), force=True)

    def attn(q, k, v):
        import jax
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / q.shape[-1] ** 0.5
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)

    q = jnp.zeros((4, 8, 4, 8), jnp.float32)
    # attn is a static callable, not a traced array — close over it.
    return trace_and_check(lambda q, k, v: ulysses_attention(attn, q, k, v),
                           q, q, q, name="ulysses-attention")


def audit_flash_kernel() -> List[Finding]:
    """The in-repo Pallas flash training kernel (r6 tentpole,
    ops/transformer/pallas_flash.py): the jaxpr audit covers the wrapper's
    graph — the kernel must bind no collective and alias no donation. The
    scalar-prefetch contract (``q_offset``/``window`` are OPERANDS, not
    static config) is enforced by tracing them as ABSTRACT i32 scalars
    here: a regression that bakes either into the kernel's static
    configuration cannot concretize a tracer and surfaces as a hard
    trace-failed finding (and the numerics side is pinned by
    tests/unit/ops/test_pallas_flash.py::test_traced_q_offset_and_window,
    which feeds one jitted trace multiple values)."""
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer.pallas_flash import \
        flash_attention_kernel

    q = jnp.zeros((1, 64, 4, 16), jnp.float32)
    k = jnp.zeros((1, 64, 2, 16), jnp.float32)

    def fn(q, k, v, off, w):
        return flash_attention_kernel(q, k, v, causal=True, q_offset=off,
                                      window=w, interpret=True)

    i32 = lambda x: jnp.asarray(x, jnp.int32)
    return trace_and_check(fn, q, k, k, i32(0), i32(0),
                           name="flash-attention-kernel")


def audit_telemetry_off_parity() -> List[Finding]:
    """The telemetry zero-overhead contract (docs/OBSERVABILITY.md): the
    engine step entry point's jaxpr must be IDENTICAL with telemetry off
    and on — instrumentation is host-side spans around dispatches, never
    graph edits — and neither graph may contain a host-callback primitive
    (the auditor's ``host-callback-in-graph`` rule covers that part)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.telemetry import NULL_TELEMETRY, reset_telemetry

    from .trace_harness import TELEMETRY_GRAPH_DRIFT, JaxprAuditor

    lr = jnp.asarray(1e-3, jnp.float32)
    # ONE engine, traced twice: telemetry enabled (handle + global live),
    # then forced off — if the step graph consults either, the jaxprs
    # diverge. One build keeps the audit cheap inside the tier-1 gate.
    tmpdir = tempfile.mkdtemp(prefix="dstpu_telemetry_audit_")
    try:
        engine = _tiny_engine(config_extra={"telemetry": {
            "enabled": True, "watchdog": {"enabled": False},
            "trace": {"output_path": tmpdir}}})
        assert engine.telemetry.enabled, \
            "telemetry config block did not enable the subsystem"
        batch = _batch(engine)
        with engine.mesh:
            jaxpr_on = jax.make_jaxpr(engine._train_step_fn)(
                engine.state, batch, lr)
    finally:
        reset_telemetry()  # the audit must not leak a live recorder
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    engine.telemetry = NULL_TELEMETRY
    with engine.mesh:
        jaxpr_off = jax.make_jaxpr(engine._train_step_fn)(
            engine.state, batch, lr)
    auditor = JaxprAuditor("telemetry-off-parity")
    auditor.walk(jaxpr_on.jaxpr)
    findings = auditor.findings
    if str(jaxpr_off) != str(jaxpr_on):
        findings.append(Finding(
            rule_id=TELEMETRY_GRAPH_DRIFT.rule_id,
            path="<trace:telemetry-off-parity>", line=0,
            severity=SEVERITY_ERROR,
            message="engine train-step jaxpr differs between telemetry "
                    "disabled and enabled",
            fix_hint=TELEMETRY_GRAPH_DRIFT.fix_hint))
    return findings


ENTRY_POINTS: Dict[str, Callable[[], List[Finding]]] = {
    "engine-train-step": audit_engine_step,
    "zero-gather-partition": audit_zero_gather_partition,
    "zeropp-micro-overlap": audit_zeropp_micro_overlap,
    "moe-dispatch": audit_moe_dispatch,
    "ring-attention": audit_ring_attention,
    "ulysses-attention": audit_ulysses_attention,
    "flash-attention-kernel": audit_flash_kernel,
    "telemetry-off-parity": audit_telemetry_off_parity,
}


def audit_entry_points(names=None) -> List[Finding]:
    """Run the named audits (default: all). An audit that cannot even trace
    is itself a hard finding — a broken hot path must not pass silently."""
    from deepspeed_tpu.runtime import topology as topo_mod

    if names:
        unknown = sorted(set(names) - set(ENTRY_POINTS))
        if unknown:
            raise ValueError(
                f"unknown entry point(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(ENTRY_POINTS))})")
    findings: List[Finding] = []
    for name, fn in ENTRY_POINTS.items():
        if names and name not in names:
            continue
        topo_mod.reset()
        try:
            findings.extend(fn())
        except Exception as e:  # noqa: BLE001 - any trace failure is a finding
            findings.append(Finding(
                rule_id="trace-failed", path=f"<trace:{name}>", line=0,
                severity=SEVERITY_ERROR,
                message=f"entry point failed to trace: {type(e).__name__}: {e}",
                fix_hint="run the audit under JAX_PLATFORMS=cpu with "
                         "xla_force_host_platform_device_count>=8"))
    topo_mod.reset()
    return findings
