"""Registered entry points: the framework's real traced hot paths.

Each entry point is declared ONCE as an :class:`EntrySpec` — the callable,
its representative (sharded) arguments, its donation contract, the mesh it
runs under, and its compiled-layer expectations — and BOTH analysis layers
consume the same spec:

- **Layer B** (``dstpu lint --jaxpr``) traces the spec with
  :func:`trace_and_check` and walks the jaxpr (collective axis binding,
  donation aliasing, retrace signatures).
- **Layer C** (``dstpu lint --spmd``, :mod:`.spmd_audit`) lowers and
  compiles the spec with its real mesh/shardings and audits the
  post-SPMD artifact (GSPMD-inserted collectives, replicated
  intermediates, remat residuals, actual aliasing, memory budgets).

These run on the CPU host platform (``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=8``, the same virtual mesh the
unit tests use); nothing executes, only traces and compiles.

``audit_entry_points()`` is what ``dstpu lint --jaxpr`` and the
``test_lint_clean`` CI gate call.

Layer-C expectations on a spec:

- ``expected_spmd`` — HLO collective kinds the entry point's sharding
  design legitimately lets GSPMD insert (beyond the kinds implied by the
  source jaxpr's own collective primitives). This is the *declared
  contract* the ``implicit-reshard`` rule enforces: any other kind
  appearing in the compiled program is a finding.
- ``param_shapes`` — full (unpartitioned) parameter shapes, set only on
  the ZeRO-partitioned schedules where "residuals must never contain full
  params" is a design invariant (docs/ZERO_OVERLAP.md); the
  ``remat-residual-full-param`` rule walks scan residuals against it.
- ``gate_cheap`` — True for the specs the tier-1 CI gate compiles
  (no engine build, sub-second compiles); the full set runs via
  ``dstpu lint --spmd`` off-gate. See docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

from .findings import Finding, SEVERITY_ERROR
from .trace_harness import check_retrace, trace_and_check

_TINY = dict(max_seq_len=32, vocab_size=256, remat=False)


@dataclasses.dataclass
class EntrySpec:
    """One registered entry point, shared by Layers B and C."""
    name: str
    fn: Callable
    args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...] = ()
    mesh: Any = None                     # context manager; None = no mesh ctx
    retrace_args: Optional[Sequence[Tuple]] = None   # arg sets for check_retrace
    max_signatures: int = 1
    # --- Layer C contracts ---
    #: the production jit's extra arguments (in_shardings/out_shardings) —
    #: Layer C must compile the program production runs, or donation and
    #: partitioning drift from reality
    jit_kwargs: Optional[Dict[str, Any]] = None
    expected_spmd: FrozenSet[str] = frozenset()
    param_shapes: FrozenSet[Tuple[Tuple[int, ...], str]] = frozenset()
    gate_cheap: bool = False
    #: Layer D contract (docs/STATIC_ANALYSIS.md): the entry's schedule is
    #: DESIGNED to overlap its collectives — exposed bytes beyond the
    #: committed exposure budget escalate from a budget regression to the
    #: hard ``exposed-collective`` finding. Declared on the pipelined
    #: ZeRO micro and the ragged serving wave.
    overlap_contract: bool = False
    # bespoke Layer-B checks run by the builder (e.g. telemetry parity)
    extra_findings: List[Finding] = dataclasses.field(default_factory=list)

    def mesh_ctx(self):
        import contextlib
        return self.mesh if self.mesh is not None else contextlib.nullcontext()


#: the active candidate overrides (installed by :func:`candidate_overrides`,
#: consulted by ``_tiny_engine`` / ``_batch``): ``{"config": nested config
#: overrides, "model": gpt2_model kwargs, "batch": {"size", "seq"}}``.
#: Empty = HEAD defaults, which is every path except `dstpu plan`.
_CANDIDATE: Dict[str, Dict[str, Any]] = {}

#: the entries whose spec builders synthesize an engine from a config dict
#: — the only ones a candidate config can re-parameterize. The rest build
#: fixed toy programs; `dstpu plan` rejects candidates targeting them
#: rather than silently auditing the default program.
CANDIDATE_ENTRY_POINTS: Tuple[str, ...] = (
    "engine-train-step", "zero-gather-partition", "zeropp-micro-overlap",
    "telemetry-off-parity", "guardian-step-parity")


@contextlib.contextmanager
def candidate_overrides(config=None, model=None, batch=None):
    """Install candidate overrides for the duration of a spec build:
    ``config`` deep-merges over the builder's engine config (the same
    :func:`~deepspeed_tpu.runtime.config.deep_update` semantics the
    engine build validates under), ``model`` overrides the tiny-model
    kwargs (e.g. ``remat``), ``batch`` overrides the representative batch
    shape (``size``/``seq``). This is how `dstpu plan` re-parameterizes
    the EXISTING registry builders instead of growing a parallel set."""
    global _CANDIDATE
    old = _CANDIDATE
    _CANDIDATE = {"config": config or {}, "model": model or {},
                  "batch": batch or {}}
    try:
        yield
    finally:
        _CANDIDATE = old


def _tiny_engine(config_extra=None, **model_kw):
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2_model
    from deepspeed_tpu.runtime.config import deep_update

    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }
    deep_update(config, config_extra)
    deep_update(config, _CANDIDATE.get("config"))
    model_args = dict(_TINY)
    model_args.update(model_kw)
    model_args.update(_CANDIDATE.get("model", {}))
    model = gpt2_model("gpt2-tiny", **model_args)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def _batch(engine, batch=8, seq=16):
    import numpy as np
    over = _CANDIDATE.get("batch", {})
    batch = int(over.get("size", batch))
    seq = int(over.get("seq", seq))
    ids = np.zeros((batch, seq), dtype=np.int32)
    return engine._prepare_batch({"input_ids": ids})


def _full_param_shapes(model) -> FrozenSet[Tuple[Tuple[int, ...], str]]:
    """Full (unpartitioned) parameter shapes of ``model`` — what a gathered
    layer weight looks like. The remat-residual rule flags scan residuals
    matching any of these."""
    import jax

    tree = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    return frozenset((tuple(l.shape), str(l.dtype))
                     for l in jax.tree.leaves(tree))


# ---------------------------------------------------------------------------
# spec builders — one per registered entry point
# ---------------------------------------------------------------------------

def build_engine_step() -> EntrySpec:
    """The fused train step: collectives bound, state donated, and the step
    must not retrace across steps (same shapes -> one signature). The step
    is GSPMD-sharded (jit + shardings, no shard_map): the data-parallel
    gradient all-reduce and the ZeRO-1 sharded-optimizer gather/exchange
    are partitioner-inserted BY DESIGN — the declared expected_spmd set."""
    import jax.numpy as jnp

    engine = _tiny_engine()
    batch = _batch(engine)
    lr = jnp.asarray(1e-3, jnp.float32)
    args = (engine.state, batch, lr)
    return EntrySpec(
        name="engine-train-step", fn=engine._train_step_fn, args=args,
        donate_argnums=(0,), mesh=engine.mesh,
        jit_kwargs=_fused_step_jit_kwargs(engine),
        retrace_args=[args, args],
        expected_spmd=frozenset({"all-reduce", "all-gather", "all-to-all"}))


def _fused_step_jit_kwargs(engine) -> Dict[str, Any]:
    """The fused step's production jit arguments (engine._build_fused_jit):
    state shardings in and out, replicated scalars."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shardings = engine._state_shardings()
    rep = NamedSharding(engine.mesh, P())
    return dict(in_shardings=(shardings, None, None),
                out_shardings=(shardings, rep, rep, rep))


def _zeropp_micro_jit_kwargs(engine) -> Dict[str, Any]:
    """The explicit ZeRO++ micro's production jit arguments
    (engine._build_jits, _explicit_micro branch): only grad_acc flows
    donated; scale replicated; params/batch placed by the caller."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shardings = engine._state_shardings()
    rep = NamedSharding(engine.mesh, P())
    return dict(in_shardings=(shardings["grad_acc"], rep, None, None),
                out_shardings=(shardings["grad_acc"], rep))


def build_zero_gather_partition() -> EntrySpec:
    """ZeRO++ micro step — the whole-tree BARRIER schedule, the
    ``overlap_comm: false`` escape hatch (engine._build_zeropp_micro_barrier):
    every collective must ride the canonical dp axes and the donated grad
    accumulator must alias. Gathers/scatters are EXPLICIT shard_map
    collectives, so the compiled program may contain no collective kind
    the source jaxpr doesn't already name (psum lowers to all-reduce)."""
    engine = _tiny_engine(config_extra={"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True, "overlap_comm": False}})
    assert engine._zeropp, "config did not enable the ZeRO++ path"
    batch = _batch(engine)
    micro = engine._build_zeropp_micro()
    assert not engine._overlap_active, \
        "overlap_comm: false must select the barrier schedule"
    args = (engine.state["grad_acc"], engine.state["loss_scale"]["cur_scale"],
            engine.state["params"], batch)
    return EntrySpec(
        name="zero-gather-partition", fn=micro, args=args,
        donate_argnums=(0,), mesh=engine.mesh,
        jit_kwargs=_zeropp_micro_jit_kwargs(engine),
        param_shapes=_full_param_shapes(engine.model))


def build_zeropp_micro_overlap() -> EntrySpec:
    """The layer-granular pipelined ZeRO++ micro step (ISSUE 3 tentpole,
    engine._build_zeropp_micro_overlap + models/transformer.py
    scan_blocks_pipelined + runtime/zero/overlap.py): double-buffered
    param prefetch in the forward scan carry, backward-interleaved
    gradient reduce-scatter. The audit enforces axis binding (every
    collective in both scan bodies rides canonical dp axes), donation
    aliasing on the grad accumulator, and a stable retrace signature —
    the schedule recompiling per step would erase the win it exists for.
    ``param_shapes`` arms the remat-residual rule: the prefetch CARRY may
    hold one gathered layer (by design), stacked scan residuals may not."""
    engine = _tiny_engine(config_extra={"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True, "zero_quantized_gradients": True}})
    assert engine._zeropp, "config did not enable the ZeRO++ path"
    batch = _batch(engine)
    micro = engine._build_zeropp_micro()
    assert engine._overlap_active, (
        "overlap_comm (stage-3 default true) must select the pipelined "
        f"schedule; fell back: {engine._overlap_fallback}")
    gacc = engine.state["grad_acc"]
    scale = engine.state["loss_scale"]["cur_scale"]
    args = (gacc, scale, engine.state["params"], batch)
    return EntrySpec(
        name="zeropp-micro-overlap", fn=micro, args=args,
        donate_argnums=(0,), mesh=engine.mesh,
        jit_kwargs=_zeropp_micro_jit_kwargs(engine),
        retrace_args=[args, args],
        param_shapes=_full_param_shapes(engine.model),
        overlap_contract=True)


def build_moe_dispatch() -> EntrySpec:
    """MoE dispatch/combine: the expert exchange is expressed as sharding
    constraints over the expert axis — those specs must name canonical axes
    of the configured topology, and the partitioner materializes the
    exchange (all-to-all/permute/gather + the combine all-reduce), which is
    the declared expected_spmd set. Since ISSUE 9 the input rides the data
    axis (the production layout, where dispatch is a REAL exchange) and
    the overlap planner's scan-carry chunking pipelines that exchange
    under expert compute — the entry declares an ``overlap_contract``:
    the dispatch-side bytes must stay hidden, the combine-side epilogue
    is the budget-justified edge (tools/exposure_budgets.json)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.moe.layer import MoE
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig

    topo = topo_mod.initialize(TopologyConfig(expert=2, data=-1), force=True)
    # intermediate 64: a representative FFN-to-exchange ratio (real MoE
    # FFNs are 2-4x hidden) — the dispatch chunk must have enough expert
    # compute beside it to classify overlapped on the audit mesh
    moe = MoE(hidden_size=16, intermediate_size=64, num_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0))
    x = jax.device_put(jnp.zeros((4, 8, 16), jnp.float32),
                       NamedSharding(topo.mesh, P(DATA_AXIS)))
    args = (params, x)
    return EntrySpec(
        name="moe-dispatch", fn=lambda p, t: moe(p, t)[0], args=args,
        mesh=topo.mesh, retrace_args=[args, args], gate_cheap=True,
        overlap_contract=True,
        expected_spmd=frozenset({"all-reduce", "all-gather", "all-to-all",
                                 "collective-permute"}))


def build_ring_attention() -> EntrySpec:
    """Ring attention: the K/V rotation must ppermute over the canonical
    seq axis inside a shard_map whose mesh matches the global topology.
    All collectives are explicit (collective-permute from ppermute):
    expected_spmd is empty — a partitioner-inserted gather here means the
    sequence sharding broke."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import TopologyConfig
    from deepspeed_tpu.sequence.ring_attention import ring_attention

    topo_mod.initialize(TopologyConfig(seq=2, data=-1), force=True)
    q = jnp.zeros((4, 8, 4, 8), jnp.float32)
    args = (q, q, q)
    return EntrySpec(name="ring-attention", fn=ring_attention, args=args,
                     retrace_args=[args, args], gate_cheap=True)


def build_ulysses_attention() -> EntrySpec:
    """Ulysses: the head-scatter/seq-gather all-to-alls over the seq axis —
    explicit in the source jaxpr, so expected_spmd is empty. Since ISSUE 9
    the exchanges ride the transport planner's activation-kind bf16 wire
    (half the exposed bytes) and the entry declares an
    ``overlap_contract``: the reshard is a dependence chain, so its
    remaining exposure is budget-pinned rather than hideable — a byte
    REGRESSION (e.g. the wire silently reverting to full width) is the
    hard ``exposed-collective`` finding."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import TopologyConfig
    from deepspeed_tpu.sequence.layer import ulysses_attention

    topo_mod.initialize(TopologyConfig(seq=2, data=-1), force=True)

    def attn(q, k, v):
        import jax
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / q.shape[-1] ** 0.5
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)

    # at this toy size the exchange sits below the transport planner's
    # min_bytes floor, so the audited wire is full width by DESIGN (tiny
    # exchanges are latency-bound; narrowing buys nothing) — the bf16
    # activation wire is pinned by tests/unit/runtime/test_ulysses.py,
    # whose payloads clear the floor
    q = jnp.zeros((4, 8, 4, 8), jnp.float32)
    args = (q, q, q)
    # attn is a static callable, not a traced array — close over it.
    return EntrySpec(name="ulysses-attention",
                     fn=lambda q, k, v: ulysses_attention(attn, q, k, v),
                     args=args, retrace_args=[args, args], gate_cheap=True,
                     overlap_contract=True)


def build_flash_kernel() -> EntrySpec:
    """The in-repo Pallas flash training kernel (r6 tentpole,
    ops/transformer/pallas_flash.py): the jaxpr audit covers the wrapper's
    graph — the kernel must bind no collective and alias no donation. The
    scalar-prefetch contract (``q_offset``/``window`` are OPERANDS, not
    static config) is enforced by tracing them as ABSTRACT i32 scalars
    here: a regression that bakes either into the kernel's static
    configuration cannot concretize a tracer and surfaces as a hard
    trace-failed finding (and the numerics side is pinned by
    tests/unit/ops/test_pallas_flash.py::test_traced_q_offset_and_window,
    which feeds one jitted trace multiple values)."""
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer.pallas_flash import \
        flash_attention_kernel

    q = jnp.zeros((1, 64, 4, 16), jnp.float32)
    k = jnp.zeros((1, 64, 2, 16), jnp.float32)

    def fn(q, k, v, off, w):
        return flash_attention_kernel(q, k, v, causal=True, q_offset=off,
                                      window=w, interpret=True)

    i32 = lambda x: jnp.asarray(x, jnp.int32)
    args = (q, k, k, i32(0), i32(0))
    return EntrySpec(name="flash-attention-kernel", fn=fn, args=args,
                     retrace_args=[args, args])


def build_paged_decode() -> EntrySpec:
    """The paged-decode serving step (inference/v2 paged_attention): one
    new token per sequence against a blocked KV cache. Batch rides the
    data axis; the page pool is replicated (every rank serves its own
    requests against shared pages on the CPU audit mesh). The gather is
    per-rank local — NO collective belongs in the compiled program, so
    expected_spmd is empty: any partitioner-inserted gather/reduce means
    the serving sharding regressed (the 24-request serving wall is a
    memory/reshard problem, not a FLOPs one)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.inference.v2.kernels.paged_attention import \
        paged_decode_attention
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig

    topo = topo_mod.initialize(TopologyConfig(data=-1), force=True)
    mesh = topo.mesh
    B, H, D, kvH, pages, page = 8, 4, 16, 2, 16, 8
    put = lambda x, *spec: jax.device_put(x, NamedSharding(mesh, P(*spec)))
    q = put(jnp.zeros((B, H, D), jnp.float32), DATA_AXIS)
    k_pages = put(jnp.zeros((kvH, pages, page, D), jnp.float32))
    v_pages = put(jnp.zeros((kvH, pages, page, D), jnp.float32))
    context_lens = put(jnp.ones((B,), jnp.int32), DATA_AXIS)
    block_tables = put(jnp.zeros((B, 4), jnp.int32), DATA_AXIS)
    args = (q, k_pages, v_pages, context_lens, block_tables)
    return EntrySpec(name="paged-decode", fn=paged_decode_attention,
                     args=args, mesh=mesh, retrace_args=[args, args],
                     gate_cheap=True)


def build_ragged_paged_attention() -> EntrySpec:
    """The ragged serving wave (ISSUE 6 tentpole): ragged paged attention
    dispatched through ``shard_map`` over the data axis against a
    DATA-SHARDED page pool — the production composition
    ``engine_v2._wave_sharded_fn`` runs (each rank's sub-wave against its
    local pool slice). The zero-collective decode contract carries over
    from ``paged-decode``: everything is rank-local by construction, so
    ``expected_spmd`` is empty and ANY partitioner-inserted collective
    means the pool sharding or the local-id discipline regressed.

    The ragged wave descriptors (``cu_q_lens`` / ``kv_lens`` /
    ``page_indices``) are traced as ABSTRACT i32 arrays: a regression
    that bakes wave composition into static kernel configuration cannot
    concretize a tracer and surfaces as a hard trace-failed finding
    (numerics pinned by tests/unit/inference/test_ragged_paged_attention
    .py). The kernel path itself is traced in interpret mode, the same
    program the CPU parity suite validates."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.inference.v2.kernels.ragged_paged_attention import \
        ragged_paged_attention
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = topo_mod.initialize(TopologyConfig(data=-1), force=True)
    mesh = topo.mesh
    dp = mesh.shape[DATA_AXIS]
    # per-rank sub-wave: 16 flat tokens, 8 atoms, 4-page tables against a
    # 4-pages-per-rank pool slice (global pool dp*4 pages)
    H, D, kvH, ps = 4, 16, 2, 8
    Nr, Ar, MP = 16, 8, 4

    def wave_attn(q, k_pages, v_pages, cu_q_lens, kv_lens, page_indices):
        return ragged_paged_attention(
            q, k_pages, v_pages, kv_lens, page_indices, cu_q_lens,
            block_q=8, use_pallas=True, interpret=True)

    d = DATA_AXIS
    fn = shard_map(wave_attn, mesh=mesh,
                   in_specs=(P(d), P(None, d), P(None, d),
                             P(d), P(d), P(d, None)),
                   out_specs=P(d), check_vma=False)
    put = lambda x, *spec: jax.device_put(x, NamedSharding(mesh, P(*spec)))
    q = put(jnp.zeros((dp * Nr, H, D), jnp.float32), d)
    k_pages = put(jnp.zeros((kvH, dp * 4, ps, D), jnp.float32), None, d)
    v_pages = put(jnp.zeros((kvH, dp * 4, ps, D), jnp.float32), None, d)
    cu = put(jnp.zeros((dp * (Ar + 1),), jnp.int32), d)
    kv_lens = put(jnp.ones((dp * Ar,), jnp.int32), d)
    tables = put(jnp.zeros((dp * Ar, MP), jnp.int32), d)
    args = (q, k_pages, v_pages, cu, kv_lens, tables)
    return EntrySpec(name="ragged-paged-attention", fn=fn, args=args,
                     mesh=mesh, retrace_args=[args, args], gate_cheap=True,
                     overlap_contract=True)


def build_quantized_transport() -> EntrySpec:
    """The transport planner's quantized + hierarchical collective paths
    (ISSUE 8, comm/comm.py + ops/quantizer): an explicit shard_map region
    over the two-tier audit mesh (mics=2 intra-tier x data=4 cross-tier)
    running the planner-resolved grad reduce-scatter (int8 wire,
    hierarchical decomposition) and the EQuARX-style quantized
    all-reduce. Layer B enforces collective axis binding on the quantized
    wire legs; every collective is explicit in the source jaxpr, so
    ``expected_spmd`` is empty; Layers C/D pin the wire bytes per kind
    and the exposure budget (docs/COLLECTIVES.md)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import (DATA_AXIS, MICS_AXIS,
                                                TopologyConfig)
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = topo_mod.initialize(TopologyConfig(mics=2, data=-1), force=True)
    axes = (DATA_AXIS, MICS_AXIS)

    def local(g, a):
        rs = dist.reduce_scatter(g, axis=axes, kind="grad")
        ar = dist.all_reduce(a, axis=axes, kind="grad")
        return rs, ar

    fn = shard_map(local, mesh=topo.mesh,
                   in_specs=(P(axes), P(axes)),
                   out_specs=(P(axes), P(None)),  # rs shards; ar replicates
                   check_vma=False)
    g = jnp.zeros((2048, 16), jnp.float32)
    a = jnp.zeros((4096,), jnp.float32)
    args = (g, a)
    return EntrySpec(name="quantized-transport", fn=fn, args=args,
                     mesh=topo.mesh, retrace_args=[args, args],
                     gate_cheap=True)


def build_fused_optimizer_step() -> EntrySpec:
    """The fused Pallas optimizer step (ISSUE 10 tentpole,
    ops/adam/pallas_adam.py via ``Optimizer.update(kernel='pallas')``):
    one launch per flat bucket over a ZeRO-1-style dp-sharded state with
    bf16 SR moments and the in-pass bf16 param cast — the program every
    step path dispatches under ``DSTPU_OPT_KERNEL`` on TPU. ``step``
    (inside the donated state) and ``lr`` trace ABSTRACT, so a regression
    that bakes either into the kernel's static configuration cannot
    concretize a tracer (the flash/ragged scalar-prefetch discipline).

    DONATED MOMENT BUFFERS are the machine-checked contract: the kernel
    wrapper aliases master/moment operands in place
    (``input_output_aliases``) and the spec donates the state, so a
    layout change that breaks the aliasing chain (a pad or concat
    creeping into the single-leaf path) surfaces as a hard
    ``dead-donation`` finding — without it the fp32+bf16 moments exist
    twice at peak, exactly the copy the fused step exists to avoid.

    The step runs as a ``shard_map`` over the dp axis with LOCAL flat
    shards — the multi-chip composition the engine's mesh-aware auto
    refinement defers to (engine ``_opt_kernel_choice``; under plain
    GSPMD the flat-bucket layout makes the partitioner rematerialize the
    sharded state, which is the finding this entry would raise). The
    update is per-rank elementwise math, so NO collective belongs in the
    compiled program (``expected_spmd`` empty, zero-byte collective map
    committed — the paged-decode discipline)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.optimizers import Optimizer
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = topo_mod.initialize(TopologyConfig(data=-1), force=True)
    mesh = topo.mesh
    d = DATA_AXIS
    opt = Optimizer(name="adamw", lr=1e-3, weight_decay=0.01,
                    moment_dtype=jnp.bfloat16, moment_sq_dtype=jnp.bfloat16)
    put = lambda x, *spec: jax.device_put(x, NamedSharding(mesh, P(*spec)))
    # a dp-sharded matmul-weight leaf + a replicated bias leaf — the two
    # sharding classes a ZeRO-1 optimizer state mixes
    spec_of = {"w": P(d), "b": P()}
    tree_spec = lambda: dict(spec_of)
    params = {"w": put(jnp.zeros((2048, 128), jnp.float32), d),
              "b": put(jnp.zeros((128,), jnp.float32))}
    state = opt.init(params)
    place = lambda t: {k: put(v, *(spec_of[k] or ()))
                       for k, v in t.items()}
    state = {"step": put(state["step"]),
             "master": place(state["master"]),
             "exp_avg": place(state["exp_avg"]),
             "exp_avg_sq": place(state["exp_avg_sq"])}
    grads = {"w": put(jnp.zeros((2048, 128), jnp.bfloat16), d),
             "b": put(jnp.zeros((128,), jnp.bfloat16))}

    def local_update(g, opt_state, lr):
        # bucket_elems=1: every leaf stands alone = the alias (in-place)
        # path — the donation contract under machine check. Replicated
        # leaves step identically on every rank (the SR stream is a pure
        # function of (step, slot, bucket) x element index).
        return opt.update(g, opt_state, lr, param_dtype=jnp.bfloat16,
                          kernel="pallas", bucket_elems=1)

    state_specs = {"step": P(), "master": tree_spec(),
                   "exp_avg": tree_spec(), "exp_avg_sq": tree_spec()}
    fn = shard_map(local_update, mesh=mesh,
                   in_specs=(tree_spec(), state_specs, P()),
                   out_specs=(tree_spec(), state_specs),
                   check_vma=False)
    lr = jnp.asarray(1e-3, jnp.float32)
    args = (grads, state, lr)
    sh = lambda tree: jax.tree.map(lambda x: x.sharding, tree)
    return EntrySpec(
        name="fused-optimizer-step", fn=fn, args=args,
        donate_argnums=(1,), mesh=mesh, retrace_args=[args, args],
        jit_kwargs=dict(in_shardings=(sh(grads), sh(state), None),
                        out_shardings=(sh(grads), sh(state))),
        gate_cheap=True)


def build_fused_moe_dispatch() -> EntrySpec:
    """The fused Pallas MoE dispatch/combine kernel pair (ISSUE 11,
    ops/transformer/pallas_moe.py via ``MoE(kernel='pallas')``): route
    select + capacity scatter, the slot gather + wire cast, and the
    grouped expert-FFN + combine-scatter as hand launches, traced in
    interpret mode (the CPU parity suite's program — the flash/ragged
    discipline).

    The audited composition is a ``shard_map`` over the data axis: each
    rank runs the kernel forward on its LOCAL token slice against
    replicated expert weights — the dead-EP data-parallel regime the
    kernel serves (a live expert/pipeline axis keeps the GSPMD exchange
    path, ``moe/layer.py``). Everything is rank-local by construction,
    so NO collective belongs in the compiled program: ``expected_spmd``
    is empty and the committed collective map is zero-byte (the
    paged-decode / fused-optimizer-step discipline) — any
    partitioner-inserted gather here means the wrapper's sharding
    regressed into exactly the rematerialization the auto-gate guards
    against.

    ``n_chunks=2`` exercises the overlap planner's scan-carry placement
    on the kernel path (chunk c+1's gather+cast prefetched from the
    carry under chunk c's FFN+combine). The token/logits operands trace
    ABSTRACT — a regression that concretizes a routing tracer into the
    kernels' static configuration surfaces as a hard trace-failed
    finding. DONATED TOKEN BUFFER is the machine-checked capacity-buffer
    contract: the token-major output reuses the donated input's buffer
    (same shape/dtype/sharding) while the capacity-slot payload and the
    expert outputs stay internal to the launches — a layout change that
    breaks the alias (the output growing a pad, the payload escaping to
    HBM as a program output) surfaces as a hard ``dead-donation``
    finding."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.moe.layer import MoE
    from deepspeed_tpu.ops.transformer import pallas_moe
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = topo_mod.initialize(TopologyConfig(data=-1), force=True)
    mesh = topo.mesh
    dp = mesh.shape[DATA_AXIS]
    d = DATA_AXIS
    # intermediate 64: the representative FFN-to-dispatch ratio the
    # moe-dispatch entry uses (real MoE FFNs are 2-4x hidden)
    moe = MoE(hidden_size=16, intermediate_size=64, num_experts=4, top_k=2)
    fwd = pallas_moe.make_moe_forward(
        top_k=2, capacity=10, activation="silu_gated", mask_pad=False,
        n_chunks=2, interpret=True)
    fn = shard_map(lambda p, t: fwd(p, t)[0], mesh=mesh,
                   in_specs=(jax.tree.map(lambda _: P(), moe.specs(),
                                          is_leaf=lambda s: s is None
                                          or isinstance(s, P)), P(d)),
                   out_specs=P(d), check_vma=False)
    put = lambda x, *spec: jax.device_put(x, NamedSharding(mesh, P(*spec)))
    params = jax.tree.map(put, moe.init(jax.random.PRNGKey(0)))
    tokens = put(jnp.zeros((dp * 32, 16), jnp.float32), d)
    args = (params, tokens)
    sh = lambda tree: jax.tree.map(lambda x: x.sharding, tree)
    return EntrySpec(
        name="fused-moe-dispatch", fn=fn, args=args,
        donate_argnums=(1,), mesh=mesh, retrace_args=[args, args],
        jit_kwargs=dict(in_shardings=(sh(params), tokens.sharding),
                        out_shardings=tokens.sharding),
        gate_cheap=True)


def build_offload_step_pipeline() -> EntrySpec:
    """The per-bucket traced compute of the double-buffered offload
    pipeline (ISSUE 15, ``engine._apply_step_offload``): the D2H fetch
    side's 2-D flatten (``DeepSpeedEngine._to_flat`` — dp dim first, any
    model dim major of the second, a LOCAL transpose by design) and the
    H2D push side's unflatten (``_from_flat`` — the engine's push jit
    traces the SAME function, so the audited program cannot drift).

    Contracts under machine check:

    - **Donated swap-in buffer** (``dead-donation``): the pushed flat
      master segment is dead once the param leaf is rebuilt; for an
      identity-order dp-sharded leaf the unflatten is a pure bitcast and
      the donated buffer MUST alias the output — a pad/concat/reshard
      creeping into the push path surfaces as a hard finding (the
      fused-optimizer-step discipline).
    - **Zero-collective data path** (``expected_spmd`` empty, zero-byte
      committed map): the whole point of the 2-D flat layout is that the
      SPMD partitioner never rematerializes — a GSPMD-inserted collective
      here means the layout contract regressed. (The per-leaf sq-norm
      stat programs are scalar reductions outside this contract; they
      all-reduce ~4 bytes by construction and run once per leaf.)
    - **No host-sync prims in the traced bucket compute** (Layer B's
      callback/sync walk): every fence in the pipeline is host-side
      BETWEEN programs, never inside one."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime import topology as topo_mod
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.topology import DATA_AXIS, TopologyConfig

    topo = topo_mod.initialize(TopologyConfig(data=-1), force=True)
    mesh = topo.mesh
    d = DATA_AXIS
    # one dp-sharded matrix leaf + one replicated bias leaf — the two
    # layout classes the offload flat machinery handles (a tp-sharded
    # leaf adds an mp dim on the flat's second axis, same local-transpose
    # argument); identity flat order for the matrix, so the push-side
    # donation contract is checkable
    lay_w = (0, (d,), None, ())
    lay_b = (None, (), None, ())
    shape_w, shape_b = (2048, 128), (128,)
    wire = jnp.bfloat16

    def bucket_step(grads, push_flat):
        gw, gb = grads
        flats = [DeepSpeedEngine._to_flat(gw, lay_w),
                 DeepSpeedEngine._to_flat(gb, lay_b)]
        new_w = DeepSpeedEngine._from_flat(push_flat, lay_w, shape_w, wire)
        return flats, new_w

    put = lambda x, *spec: jax.device_put(x, NamedSharding(mesh, P(*spec)))
    grads = (put(jnp.zeros(shape_w, wire), d),
             put(jnp.zeros(shape_b, wire)))
    push_flat = put(jnp.zeros(shape_w, wire), d)
    args = (grads, push_flat)
    w_sh = NamedSharding(mesh, P(d, None))
    b_flat_sh = NamedSharding(mesh, P(None, None))
    return EntrySpec(
        name="offload-step-pipeline", fn=bucket_step, args=args,
        donate_argnums=(1,), mesh=mesh, retrace_args=[args, args],
        jit_kwargs=dict(
            in_shardings=((grads[0].sharding, grads[1].sharding),
                          push_flat.sharding),
            out_shardings=([w_sh, b_flat_sh], w_sh)),
        gate_cheap=True)


def build_telemetry_off_parity() -> EntrySpec:
    """The telemetry zero-overhead contract (docs/OBSERVABILITY.md): the
    engine step entry point's jaxpr must be IDENTICAL with telemetry off
    and on — instrumentation is host-side spans around dispatches, never
    graph edits — and neither graph may contain a host-callback primitive
    (the auditor's ``host-callback-in-graph`` rule covers that part).
    The parity diff runs at build time and lands in ``extra_findings``;
    the spec's fn is the telemetry-ON step, so the Layer-C artifact (and
    its budget) must match engine-train-step's — drift between those two
    budget lines is itself a parity smell."""
    import tempfile

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.telemetry import NULL_TELEMETRY, reset_telemetry

    from .trace_harness import TELEMETRY_GRAPH_DRIFT, JaxprAuditor

    lr = jnp.asarray(1e-3, jnp.float32)
    # ONE engine, traced twice: telemetry enabled (handle + global live),
    # then forced off — if the step graph consults either, the jaxprs
    # diverge. One build keeps the audit cheap inside the tier-1 gate.
    tmpdir = tempfile.mkdtemp(prefix="dstpu_telemetry_audit_")
    try:
        engine = _tiny_engine(config_extra={"telemetry": {
            "enabled": True, "watchdog": {"enabled": False},
            "trace": {"output_path": tmpdir}}})
        assert engine.telemetry.enabled, \
            "telemetry config block did not enable the subsystem"
        batch = _batch(engine)
        with engine.mesh:
            jaxpr_on = jax.make_jaxpr(engine._train_step_fn)(
                engine.state, batch, lr)
    finally:
        reset_telemetry()  # the audit must not leak a live recorder
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    engine.telemetry = NULL_TELEMETRY
    with engine.mesh:
        jaxpr_off = jax.make_jaxpr(engine._train_step_fn)(
            engine.state, batch, lr)
    auditor = JaxprAuditor("telemetry-off-parity")
    auditor.walk(jaxpr_on.jaxpr)
    extra = auditor.findings
    if str(jaxpr_off) != str(jaxpr_on):
        extra.append(Finding(
            rule_id=TELEMETRY_GRAPH_DRIFT.rule_id,
            path="<trace:telemetry-off-parity>", line=0,
            severity=SEVERITY_ERROR,
            message="engine train-step jaxpr differs between telemetry "
                    "disabled and enabled",
            fix_hint=TELEMETRY_GRAPH_DRIFT.fix_hint))
    return EntrySpec(
        name="telemetry-off-parity", fn=engine._train_step_fn,
        args=(engine.state, batch, lr), donate_argnums=(0,),
        mesh=engine.mesh, extra_findings=extra,
        jit_kwargs=_fused_step_jit_kwargs(engine),
        expected_spmd=frozenset({"all-reduce", "all-gather", "all-to-all"}))


def build_guardian_step_parity() -> EntrySpec:
    """The guardian zero-overhead contract (ISSUE 13, docs/RESILIENCE.md):
    a guardian-OFF engine's fused step jaxpr must be IDENTICAL to the
    pre-guardian program — the sentinels exist only behind the
    ``spike_thresh`` gate — and the guardian-ON step may add NOTHING
    beyond the packed anomaly word riding the reductions the step
    already computes. Three traces:

    1. a pristine engine (guardian never configured) — the baseline;
    2. a guardian-armed engine force-disarmed — must print the SAME
       jaxpr as (1), else ``guardian-graph-drift`` fires;
    3. the armed step (``_train_step_fn_guardian``) — the spec's fn, so
       Layers B/C/D audit the SENTINEL path: collective axis binding,
       donation, and a committed collective map that must stay
       zero-delta against engine-train-step's (the anomaly word may not
       launch new collectives; a tier-1 test diffs the two maps).

    The threshold traces as an ABSTRACT f32 scalar — the rolling-stat
    side stays on the host by construction (baking a concrete threshold
    into the program would recompile every step the stats move)."""
    import jax
    import jax.numpy as jnp

    from .trace_harness import GUARDIAN_GRAPH_DRIFT, JaxprAuditor

    lr = jnp.asarray(1e-3, jnp.float32)
    # the pre-guardian baseline: an engine that never saw the config
    base = _tiny_engine()
    base_batch = _batch(base)
    with base.mesh:
        jaxpr_base = jax.make_jaxpr(base._train_step_fn)(
            base.state, base_batch, lr)
    # the guardian-armed engine, traced ON then force-disarmed for OFF
    engine = _tiny_engine(config_extra={"guardian": {"enabled": True}})
    assert engine._guardian is not None, \
        "guardian config block did not arm the subsystem"
    batch = _batch(engine)
    thresh = jnp.asarray(float("inf"), jnp.float32)
    with engine.mesh:
        jaxpr_on = jax.make_jaxpr(engine._train_step_fn_guardian)(
            engine.state, batch, lr, thresh)
    guardian, engine._guardian = engine._guardian, None
    with engine.mesh:
        jaxpr_off = jax.make_jaxpr(engine._train_step_fn)(
            engine.state, batch, lr)
    engine._guardian = guardian
    auditor = JaxprAuditor("guardian-step-parity")
    auditor.walk(jaxpr_on.jaxpr)
    extra = auditor.findings
    if str(jaxpr_off) != str(jaxpr_base):
        extra.append(Finding(
            rule_id=GUARDIAN_GRAPH_DRIFT.rule_id,
            path="<trace:guardian-step-parity>", line=0,
            severity=SEVERITY_ERROR,
            message="engine train-step jaxpr with the guardian disabled "
                    "differs from the pre-guardian program",
            fix_hint=GUARDIAN_GRAPH_DRIFT.fix_hint))
    args = (engine.state, batch, lr, thresh)
    return EntrySpec(
        name="guardian-step-parity", fn=engine._train_step_fn_guardian,
        args=args, donate_argnums=(0,), mesh=engine.mesh,
        retrace_args=[args, args], extra_findings=extra,
        jit_kwargs=_guardian_step_jit_kwargs(engine),
        expected_spmd=frozenset({"all-reduce", "all-gather", "all-to-all"}))


def _guardian_step_jit_kwargs(engine) -> Dict[str, Any]:
    """The guardian-armed fused jit's production arguments
    (engine._build_fused_jit, guardian branch): +1 replicated scalar in,
    the anomaly word out."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shardings = engine._state_shardings()
    rep = NamedSharding(engine.mesh, P())
    return dict(in_shardings=(shardings, None, None, None),
                out_shardings=(shardings, rep, rep, rep, rep))


SPEC_BUILDERS: Dict[str, Callable[[], EntrySpec]] = {
    "engine-train-step": build_engine_step,
    "zero-gather-partition": build_zero_gather_partition,
    "zeropp-micro-overlap": build_zeropp_micro_overlap,
    "moe-dispatch": build_moe_dispatch,
    "fused-moe-dispatch": build_fused_moe_dispatch,
    "ring-attention": build_ring_attention,
    "ulysses-attention": build_ulysses_attention,
    "flash-attention-kernel": build_flash_kernel,
    "paged-decode": build_paged_decode,
    "quantized-transport": build_quantized_transport,
    "ragged-paged-attention": build_ragged_paged_attention,
    "fused-optimizer-step": build_fused_optimizer_step,
    "offload-step-pipeline": build_offload_step_pipeline,
    "telemetry-off-parity": build_telemetry_off_parity,
    "guardian-step-parity": build_guardian_step_parity,
}


def build_spec(name: str) -> EntrySpec:
    """Build one entry point's spec with a clean topology (builders that
    configure the global MeshTopology get a fresh slate)."""
    from deepspeed_tpu.runtime import topology as topo_mod

    topo_mod.reset()
    return SPEC_BUILDERS[name]()


def run_entry_audit(spec: EntrySpec) -> List[Finding]:
    """Layer B over one spec: jaxpr walk + donation + retrace + any bespoke
    findings the builder produced."""
    with spec.mesh_ctx():
        findings = trace_and_check(
            spec.fn, *spec.args, donate_argnums=spec.donate_argnums,
            name=spec.name)
    if spec.retrace_args is not None:
        findings += check_retrace(spec.name, spec.retrace_args,
                                  max_signatures=spec.max_signatures)
    return list(spec.extra_findings) + findings


def _make_audit(name: str) -> Callable[[], List[Finding]]:
    def audit() -> List[Finding]:
        return run_entry_audit(build_spec(name))
    audit.__name__ = f"audit_{name.replace('-', '_')}"
    return audit


ENTRY_POINTS: Dict[str, Callable[[], List[Finding]]] = {
    name: _make_audit(name) for name in SPEC_BUILDERS
}

#: the subset the tier-1 CI gate COMPILES (Layer C). Cheap by construction:
#: no engine build, sub-second compiles on the CPU mesh. The full set runs
#: via `dstpu lint --spmd` (docs/STATIC_ANALYSIS.md, "Tier-1 cost control").
#: Pinned rather than computed — building every spec just to read its
#: gate_cheap flag would boot engines; a test asserts the two agree.
GATE_SPMD_ENTRY_POINTS: Tuple[str, ...] = (
    "fused-moe-dispatch", "fused-optimizer-step", "moe-dispatch",
    "offload-step-pipeline", "paged-decode", "quantized-transport",
    "ragged-paged-attention", "ring-attention", "ulysses-attention")


def audit_entry_points(names=None) -> List[Finding]:
    """Run the named audits (default: all). An audit that cannot even trace
    is itself a hard finding — a broken hot path must not pass silently."""
    from deepspeed_tpu.runtime import topology as topo_mod

    if names:
        unknown = sorted(set(names) - set(ENTRY_POINTS))
        if unknown:
            raise ValueError(
                f"unknown entry point(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(ENTRY_POINTS))})")
    findings: List[Finding] = []
    for name, fn in ENTRY_POINTS.items():
        if names and name not in names:
            continue
        topo_mod.reset()
        try:
            findings.extend(fn())
        except Exception as e:  # noqa: BLE001 - any trace failure is a finding
            findings.append(Finding(
                rule_id="trace-failed", path=f"<trace:{name}>", line=0,
                severity=SEVERITY_ERROR,
                message=f"entry point failed to trace: {type(e).__name__}: {e}",
                fix_hint="run the audit under JAX_PLATFORMS=cpu with "
                         "xla_force_host_platform_device_count>=8"))
    topo_mod.reset()
    return findings
