"""Shrink-only memory & collective budgets (``tools/memory_budgets.json``).

Every Layer-C entry point has a committed byte budget: the
``memory_analysis()`` fields of its compiled artifact plus the total bytes
moved by collective instructions in the partitioned program. The contract
mirrors ``tools/lint_baseline.json``:

- current usage above a committed number -> ``memory-budget-regression``,
  a HARD finding. Raising a budget is a hand edit that must survive code
  review — the tool never does it for you.
- ``dstpu lint --update-budgets`` writes the file ONLY downward: an entry
  whose usage dropped is re-pinned at the lower number, a new entry point
  gets its first budget, and nothing is ever raised.
- a registered entry point with no committed budget is itself a finding —
  new hot paths land with their budget in the same PR.

Budgets are taken on the canonical audit environment (CPU host platform,
``--xla_force_host_platform_device_count=8``); the file records
``mesh_devices`` and comparisons are skipped when the live device count
differs (a TPU run has different partitioning and different bytes).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

#: fields a budget tracks, all bytes, all shrink-only. ``collective_bytes``
#: is the sum over collective instructions in the partitioned HLO of their
#: per-device result bytes — the auditor's estimate of bytes moved per step.
TRACKED_FIELDS: Tuple[str, ...] = (
    "argument_size_in_bytes", "output_size_in_bytes",
    "temp_size_in_bytes", "collective_bytes")

#: PER-KIND collective budgets (ISSUE 8): alongside the total, every
#: ``collective_bytes.<hlo-kind>`` key (e.g. ``collective_bytes.all-to-all``)
#: is tracked shrink-only whenever ``collective_bytes`` is among the
#: tracked fields. This is what statically pins the quantized-transport
#: byte win per kind — an entry whose reduce-scatter bytes grow back to
#: full width regresses that kind's budget even if another kind shrank.
KIND_PREFIX = "collective_bytes."


def tracks_field(field: str, fields: Tuple[str, ...]) -> bool:
    return field in fields or ("collective_bytes" in fields
                               and field.startswith(KIND_PREFIX))


def default_budgets_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "memory_budgets.json")


def load_budgets(path: str,
                 fields: Tuple[str, ...] = TRACKED_FIELDS) -> Optional[Dict]:
    """-> {"mesh_devices": int, "budgets": {entry: {field: int}}} or None
    when the file doesn't exist yet. ``fields`` selects the tracked keys —
    Layer C's memory budgets by default; Layer D passes its exposure
    fields so both shrink-only files share one loader."""
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {"mesh_devices": int(data.get("mesh_devices", 0)),
            "budgets": {k: {f: int(v) for f, v in e.items()
                            if tracks_field(f, fields)}
                        for k, e in data.get("budgets", {}).items()}}


def env_matches(budgets: Optional[Dict]) -> bool:
    """Budgets are only comparable on the mesh size they were taken on."""
    if not budgets:
        return False
    import jax
    return jax.device_count() == budgets["mesh_devices"]


DEFAULT_COMMENT = ("Per-entry-point compiled memory & collective byte "
                   "budgets (dstpu lint --spmd). Shrink, never grow: "
                   "`dstpu lint --update-budgets` only lowers; raising a "
                   "budget is a hand edit that must survive review. "
                   "collective_bytes[.kind] are OPERAND-side (input payload) "
                   "bytes per launch — the wire convention shared with "
                   "Layer D and record_collective (docs/COLLECTIVES.md).")


def write_budgets(path: str, budgets: Dict,
                  comment: Optional[str] = None) -> None:
    data = {
        "comment": comment or DEFAULT_COMMENT,
        "mesh_devices": budgets["mesh_devices"],
        "budgets": {k: dict(sorted(e.items()))
                    for k, e in sorted(budgets["budgets"].items())},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
        fh.write("\n")


def shrink_budgets(old: Optional[Dict], reports: Dict[str, Dict[str, int]],
                   mesh_devices: int,
                   fields: Tuple[str, ...] = TRACKED_FIELDS
                   ) -> Tuple[Dict, List[str]]:
    """Merge current ``reports`` into ``old`` budgets, ONLY downward.

    Returns the new budgets dict and the list of ``entry.field`` keys whose
    current usage EXCEEDS the committed budget (left untouched — those are
    regressions the caller must surface, not numbers to absorb)."""
    old_budgets = dict((old or {}).get("budgets", {}))
    exceeded: List[str] = []
    merged: Dict[str, Dict[str, int]] = {k: dict(v)
                                         for k, v in old_budgets.items()}
    for name, report in reports.items():
        entry = merged.setdefault(name, {})
        for field in report:
            if not tracks_field(field, fields):
                continue
            cur = int(report[field])
            if field not in entry:
                entry[field] = cur          # first budget for a new entry
            elif cur <= entry[field]:
                entry[field] = cur          # shrink
            else:
                exceeded.append(f"{name}.{field}")  # regression: never raise
    return {"mesh_devices": mesh_devices, "budgets": merged}, exceeded
