"""Structured lint findings.

The unit every layer of the suite speaks: Layer A (AST rules,
``ast_rules.py``) and Layer B (jaxpr audit, ``trace_harness.py``) both emit
:class:`Finding` records, the baseline (``baseline.py``) diffs them, and the
CLI (``cli.py``) renders them. A finding is keyed for baseline purposes by
``(path, rule_id, message)`` — line numbers shift on every unrelated edit,
so they are display-only and never part of the identity.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str          # repo-relative where possible
    line: int          # 1-indexed; 0 = whole-file / trace-level finding
    severity: str      # SEVERITY_ERROR | SEVERITY_WARNING
    message: str
    fix_hint: str = ""

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def baseline_key(self) -> str:
        return f"{self.path}::{self.rule_id}::{self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict) -> "Finding":
        return Finding(rule_id=d["rule_id"], path=d["path"],
                       line=int(d.get("line", 0)),
                       severity=d.get("severity", SEVERITY_WARNING),
                       message=d.get("message", ""),
                       fix_hint=d.get("fix_hint", ""))


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule_id, f.message))


def dedupe(findings: List[Finding]) -> List[Finding]:
    seen, out = set(), []
    for f in findings:
        k = (f.path, f.line, f.rule_id, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
