"""DeepSpeed-TPU: a TPU-native training & inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of DeepSpeed
(reference ``deepspeed/__init__.py``): ZeRO-style memory partitioning,
tensor/sequence/expert/pipeline parallelism, mixed precision with loss
scaling, checkpointing, monitoring and profiling, and ragged-batch inference
— expressed as sharding specs over a ``jax.sharding.Mesh`` instead of NCCL
process groups and CUDA kernels.

Front door (reference ``deepspeed/__init__.py:64``):

    engine, optimizer, dataloader, lr_scheduler = deepspeed_tpu.initialize(
        model=model, config=config_dict)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError  # noqa: F401
from .runtime.engine import DeepSpeedEngine  # noqa: F401
from .runtime.topology import MeshTopology, TopologyConfig  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401


def maybe_apply_tuned_config(config: Optional[Any]) -> Optional[Any]:
    """The ``DSTPU_TUNE`` overlay (docs/AUTOTUNING.md): when the env var
    is ``1``, deep-merge the pinned tune winner's config overrides
    (``tools/autotune/best.json``, written by ``dstpu tune --apply``)
    over the caller's config dict; any other non-empty, non-``0`` value
    is read as an explicit path to a ``best.json`` or trial ledger.

    Unset or ``0`` returns ``config`` UNCHANGED — the very same object,
    so opted-out engine construction is byte-identical to a build that
    never heard of the autotuner."""
    gate = os.environ.get("DSTPU_TUNE", "")
    if gate in ("", "0"):
        return config
    from .autotuning.cli import default_best_path
    path = default_best_path() if gate == "1" else gate
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        from .utils.logging import logger
        logger.warning(f"DSTPU_TUNE={gate}: no usable tuned config at "
                       f"{path} ({e}) — building untuned")
        return config
    best = doc.get("best") if "best" in doc else doc
    overrides = ((best or {}).get("overrides") or {}).get("config") or {}
    if not overrides or not isinstance(config, dict):
        return config
    from .runtime.config import deep_update
    from .utils.logging import log_dist
    merged = deep_update(json.loads(json.dumps(config)), overrides)
    log_dist(f"DSTPU_TUNE: overlaid tuned config "
             f"{(best or {}).get('label')} from {path}", ranks=[0])
    return merged


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port: int = 29500,
               topology: Optional[MeshTopology] = None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               config: Optional[Any] = None,
               config_params: Optional[Dict[str, Any]] = None,
               seed: int = 42):
    """Build a ready-to-train engine (reference ``deepspeed.initialize``,
    ``deepspeed/__init__.py:64``).

    ``model`` is a module object exposing ``init(rng, dtype) -> params``,
    ``specs() -> PartitionSpec tree``, ``loss(params, batch) -> scalar``
    (e.g. ``deepspeed_tpu.models.TransformerLM``). Returns the same 4-tuple
    as the reference: (engine, optimizer_descriptor, dataloader, lr_scheduler).
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    config = config if config is not None else config_params
    if isinstance(config, str):  # JSON path (reference-supported form)
        with open(config) as f:
            config = json.load(f)
    # DSTPU_TUNE overlay: off (unset/"0") this returns `config` itself —
    # engine construction stays byte-identical to an autotuner-free build
    config = maybe_apply_tuned_config(config)

    init_distributed()

    # engine selection (reference deepspeed/__init__.py:156-193: hybrid_engine
    # config -> DeepSpeedHybridEngine, else DeepSpeedEngine)
    engine_cls = DeepSpeedEngine
    if isinstance(config, dict) and config.get("hybrid_engine", {}).get("enabled"):
        from .runtime.hybrid_engine import DeepSpeedHybridEngine
        engine_cls = DeepSpeedHybridEngine

    engine = engine_cls(
        model=model,
        config_dict=config if isinstance(config, dict) else None,
        config=config if isinstance(config, DeepSpeedConfig) else None,
        topology=topology,
        seed=seed,
        init_params=model_parameters,
    )

    # elastic resume (dstpu-resilience, docs/RESILIENCE.md): a world
    # (re)started by DSElasticAgent(checkpoint_dir=...) carries the
    # checkpoint dir in DSTPU_ELASTIC — resume from the last committed
    # tag so a restart (possibly at a different dp width; the store
    # re-buckets shards on load) continues instead of re-initializing.
    # No committed tag yet → fresh start; a corrupt `latest` falls back
    # to the newest verified tag inside load_checkpoint.
    from .resilience import parse_elastic_env
    _ckpt_dir = parse_elastic_env().get("checkpoint_dir")
    if _ckpt_dir:
        tag, _ = engine.load_checkpoint(_ckpt_dir)
        from .utils.logging import log_dist
        log_dist(
            "elastic resume: "
            + (f"resumed tag {tag} at step {engine.global_steps}" if tag
               else "no committed checkpoint yet — fresh start")
            + f" (dir {_ckpt_dir})", ranks=[0])

    dataloader = None
    if training_data is not None:
        from .runtime.dataloader import DeepSpeedDataLoader
        dp = engine.topology.data_parallel_size
        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=engine.train_micro_batch_size_per_gpu * dp,
            collate_fn=collate_fn)

    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, model_path: Optional[str] = None, **kwargs):
    """Reference ``deepspeed.init_inference`` (``deepspeed/__init__.py:269``).

    ``model_path`` loads a real HF checkpoint directory (safetensors or
    torch-bin, gpt2/llama/mistral/mixtral) and places the weights sharded
    per the model's TP specs — the reference's checkpoint-loading path
    (``inference/engine.py:254`` + ``module_inject/load_checkpoint.py``).
    """
    from .inference.engine import InferenceEngine
    if model_path is not None:
        if model is not None:
            raise ValueError("init_inference: pass either model or model_path, "
                             "not both (which weights would win is ambiguous)")
        if "params" in kwargs:
            raise ValueError("init_inference: params cannot be combined with "
                             "model_path (the checkpoint provides the params)")
        from .inference.engine import InferenceConfig
        from .runtime.state_dict_factory import load_hf_model
        icfg = config if isinstance(config, InferenceConfig) else InferenceConfig(config, **kwargs)
        model, kwargs["params"] = load_hf_model(model_path, dtype=icfg.dtype)
    return InferenceEngine(model=model, config=config, **kwargs)
