"""Config-driven model compression.

Counterpart of the reference ``compression/compress.py``
(``init_compression`` :100, ``redundancy_clean`` :148,
``student_initialization`` :192). The reference rewrites torch modules in
place; here compression is a *pytree transform pipeline*: ``init_compression``
parses the ``compression_training`` config into a :class:`CompressionManager`
whose ``compress_params`` maps a param tree through fake-quant + pruning
masks (applied during training under the scheduler's gating), and
``redundancy_clean`` makes the zeros/quantization permanent for deployment.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .ops import fake_quantize_ste, head_prune_mask, magnitude_prune_mask, row_prune_mask

_MATMUL_KEYS = ("kernel", "embedding", "wi", "wo", "wi_gate", "wi_up")


def _leaf_name(path) -> str:
    return ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _group_cfg(section: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Reference config shape: {shared_parameters: {...}, different_groups:
    {name: {params: {...}, modules: [patterns]}}}. Returns merged per-group
    match list or None when disabled."""
    if not section or not section.get("shared_parameters", {}).get("enabled",
                                                                   section.get("enabled", False)):
        return None
    shared = section.get("shared_parameters", {})
    groups = []
    for name, g in section.get("different_groups", {}).items():
        groups.append({
            "name": name,
            "modules": g.get("modules", ["*"]),
            "params": g.get("params", {}),
        })
    if not groups:
        groups.append({"name": "default", "modules": ["*"], "params": {}})
    return {"shared": shared, "groups": groups}


def _matches(name: str, patterns: List[str]) -> bool:
    for p in patterns:
        if p == "*" or re.search(p.replace("*", ".*"), name):
            return True
    return False


class CompressionManager:

    def __init__(self, config):
        c = config
        self.weight_quant = _group_cfg(c.weight_quantization)
        self.act_quant = _group_cfg(c.activation_quantization)
        self.sparse = _group_cfg(c.sparse_pruning)
        self.row = _group_cfg(c.row_pruning)
        self.head = _group_cfg(c.head_pruning)
        self.layer_reduction = c.layer_reduction if c.layer_reduction.get("enabled") else None
        self._masks: Dict[str, jax.Array] = {}

    # -- weight transforms ---------------------------------------------------
    @staticmethod
    def scheduled_bits(group_params: Dict, step: Optional[int]) -> int:
        """Anneal start_bits → target_bits on the reference's doubling
        schedule (runtime/quantize.py:135-140): each time the step crosses
        the period the precision drops one bit and the period doubles, so
        an 8→4 QAT with period p drops at steps p, 2p, 4p, 8p."""
        start = int(group_params.get("start_bits", group_params.get("bits", 8)))
        target = int(group_params.get("target_bits", start))
        period = int(group_params.get("quantization_period",
                                      group_params.get("quantize_period", 0)))
        if step is None or period <= 0 or target >= start:
            return start
        bits, p = start, period
        while bits > target and step >= p:
            p <<= 1
            bits -= 1
        return bits

    def compress_params(self, params: Any, quant_enabled: bool = True,
                        prune_enabled: bool = True,
                        step: Optional[int] = None) -> Any:
        """Differentiable compression pass for QAT training (fake-quant with
        STE + mask multiply). Use inside the loss: model.loss(cm.compress_
        params(params), batch). ``step`` drives the start→target bits
        annealing; None holds at start_bits."""

        def transform(path, leaf):
            name = _leaf_name(path)
            if not any(k in name for k in _MATMUL_KEYS) or leaf.ndim < 2:
                return leaf
            x = leaf
            if prune_enabled and name in self._masks:
                x = x * self._masks[name].astype(x.dtype)
            if quant_enabled and self.weight_quant is not None:
                for g in self.weight_quant["groups"]:
                    if _matches(name, g["modules"]):
                        bits = self.scheduled_bits(g["params"], step)
                        x = fake_quantize_ste(x, num_bits=int(bits))
                        break
            return x

        return jax.tree_util.tree_map_with_path(transform, params)

    def update_masks(self, params: Any, num_heads: Optional[int] = None) -> int:
        """(Re)compute pruning masks from current magnitudes — the reference
        recomputes at schedule offsets (snip_momentum variant re-ranks)."""
        self._masks.clear()
        count = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            name = _leaf_name(path)
            if not any(k in name for k in _MATMUL_KEYS) or np.ndim(leaf) < 2:
                continue
            mask = None
            if self.sparse is not None:
                for g in self.sparse["groups"]:
                    if _matches(name, g["modules"]):
                        ratio = g["params"].get("dense_ratio", 0.5)
                        mask = magnitude_prune_mask(jnp.asarray(leaf), 1.0 - ratio)
                        break
            if self.row is not None and mask is None:
                for g in self.row["groups"]:
                    if _matches(name, g["modules"]):
                        ratio = g["params"].get("dense_ratio", 0.5)
                        mask = row_prune_mask(jnp.asarray(leaf), 1.0 - ratio)
                        break
            if (self.head is not None and mask is None and num_heads
                    and "o_proj" in name):
                for g in self.head["groups"]:
                    if _matches(name, g["modules"]):
                        ratio = g["params"].get("dense_ratio", 0.5)
                        mask = head_prune_mask(jnp.asarray(leaf), num_heads,
                                               1.0 - ratio)
                        break
            if mask is not None:
                self._masks[name] = mask
                count += 1
        return count

    # -- activation hook -----------------------------------------------------
    def quantize_activation(self, x: jax.Array) -> jax.Array:
        if self.act_quant is None:
            return x
        bits = self.act_quant["groups"][0]["params"].get("bits", 8)
        return fake_quantize_ste(x, num_bits=int(bits), symmetric=False)


def init_compression(params_or_engine, config) -> CompressionManager:
    """Reference compress.py:100. Accepts an engine (uses its config) or a
    bare CompressionConfig/dict."""
    from ..runtime.config import DeepSpeedConfig
    if hasattr(params_or_engine, "config"):
        cfg = params_or_engine.config.compression_config
    elif isinstance(config, dict):
        from ..runtime.config import CompressionConfig
        cfg = CompressionConfig(**config)
    else:
        cfg = config
    return CompressionManager(cfg)


def redundancy_clean(params: Any, manager: CompressionManager,
                     num_heads: Optional[int] = None) -> Any:
    """Make compression permanent for deployment (reference compress.py:148):
    bake masks and quantization into the weights (no STE)."""
    manager.update_masks(params, num_heads=num_heads)
    return manager.compress_params(params)


def student_initialization(student_params: Any, teacher_params: Any,
                           layer_map: List[int]) -> Any:
    """Layer-reduction distillation init (reference compress.py:192 +
    ``layer_reduction`` config): student layer i copies teacher layer
    ``layer_map[i]``; stacked-block layout means this is an index-select on
    the leading layer dim."""
    idx = jnp.asarray(layer_map)

    def pick(s_leaf, t_leaf):
        if s_leaf.ndim >= 1 and t_leaf.ndim == s_leaf.ndim \
                and s_leaf.shape[0] == len(layer_map) \
                and t_leaf.shape[1:] == s_leaf.shape[1:]:
            return jnp.take(jnp.asarray(t_leaf), idx, axis=0)
        return jnp.asarray(t_leaf) if t_leaf.shape == s_leaf.shape else s_leaf

    out = dict(student_params)
    for key in student_params:
        if key == "blocks":
            out["blocks"] = jax.tree.map(pick, student_params["blocks"],
                                         teacher_params["blocks"])
        elif key in teacher_params:
            out[key] = jax.tree.map(pick, student_params[key], teacher_params[key])
    return out
