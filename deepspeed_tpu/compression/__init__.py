from .compress import CompressionManager, init_compression, redundancy_clean, student_initialization  # noqa: F401
from .ops import (fake_quantize_ste, head_prune_mask, magnitude_prune_mask,  # noqa: F401
                  row_prune_mask)
from .scheduler import CompressionScheduler  # noqa: F401
