"""Compression primitives.

Counterpart of the reference ``compression/basic_layer.py`` +
``compression/utils.py`` (QuantAct / LinearLayer_Compress quant & prune
internals): quantization-aware-training fake-quant with a straight-through
estimator, and magnitude/structured pruning masks. Pure jnp — on TPU these
fuse into the surrounding matmuls; the STE is the standard
``x + stop_gradient(q(x) - x)`` identity-gradient trick the reference gets
from torch autograd Functions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def fake_quantize_ste(x: jax.Array, num_bits: int = 8, symmetric: bool = True,
                      per_channel_dim: Optional[int] = None) -> jax.Array:
    """QAT fake quantization with straight-through gradients.

    Forward: quantize-dequantize; backward: identity (reference
    ``SymQuantizer``/``AsymQuantizer`` autograd Functions)."""
    qmax = float((1 << (num_bits - 1)) - 1)
    if per_channel_dim is not None:
        axes = tuple(i for i in range(x.ndim) if i != per_channel_dim)
    else:
        axes = tuple(range(x.ndim))
    if symmetric:
        absmax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
        q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
    else:
        lo = jnp.min(x, axis=axes, keepdims=True)
        hi = jnp.max(x, axis=axes, keepdims=True)
        scale = jnp.where(hi == lo, 1.0, (hi - lo) / (2 * qmax + 1))
        zero = jnp.round(-lo / scale)
        q = (jnp.clip(jnp.round(x / scale + zero), 0, 2 * qmax + 1) - zero) * scale
    return x + jax.lax.stop_gradient(q - x)


def magnitude_prune_mask(w: jax.Array, sparsity: float) -> jax.Array:
    """Unstructured magnitude mask (reference sparse_pruning 'l1' method):
    zero the smallest |w| fraction."""
    if sparsity <= 0:
        return jnp.ones_like(w, dtype=jnp.bool_)
    k = int(w.size * (1.0 - sparsity))
    if k <= 0:
        return jnp.zeros_like(w, dtype=jnp.bool_)
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return jnp.abs(w) >= thresh


def row_prune_mask(w: jax.Array, sparsity: float, dim: int = -1) -> jax.Array:
    """Structured row/channel mask by L1 norm over ``dim``'s complement
    (reference row_pruning)."""
    axes = tuple(i for i in range(w.ndim) if i != (dim % w.ndim))
    norms = jnp.sum(jnp.abs(w), axis=axes, keepdims=True)
    n = norms.size
    k = max(1, int(n * (1.0 - sparsity)))
    thresh = jnp.sort(norms.reshape(-1))[-k]
    return jnp.broadcast_to(norms >= thresh, w.shape)


def head_prune_mask(w_o: jax.Array, num_heads: int, sparsity: float) -> jax.Array:
    """Attention-head mask for the output projection [H*D, out] (reference
    head_pruning: rank heads by the L1 norm of their o_proj slice)."""
    in_dim = w_o.shape[-2]
    head_dim = in_dim // num_heads
    heads = w_o.reshape(w_o.shape[:-2] + (num_heads, head_dim, w_o.shape[-1]))
    norms = jnp.sum(jnp.abs(heads), axis=(-2, -1))           # [..., H]
    k = max(1, int(num_heads * (1.0 - sparsity)))
    thresh = jnp.sort(norms.reshape(-1))[-k]
    mask = (norms >= thresh)[..., :, None, None]
    return jnp.broadcast_to(mask, heads.shape).reshape(w_o.shape)
