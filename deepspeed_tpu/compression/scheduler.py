"""Compression scheduling.

Counterpart of the reference ``compression/scheduler.py``: gates each
compression feature by schedule offset (step ranges) so quantization/pruning
ramp in during training rather than from step 0.
"""

from __future__ import annotations

from typing import Any, Dict


class CompressionScheduler:

    def __init__(self, manager, config: Dict[str, Any] = None):
        self.manager = manager
        cfg = config or {}
        self.quant_offset = cfg.get("quantize_offset", cfg.get("schedule_offset", 0))
        self.prune_offset = cfg.get("prune_offset", cfg.get("schedule_offset", 0))
        self.mask_refresh_interval = cfg.get("mask_refresh_interval", 100)
        self._last_mask_step = -1

    def quant_enabled(self, step: int) -> bool:
        return step >= self.quant_offset

    def prune_enabled(self, step: int) -> bool:
        return step >= self.prune_offset

    def step(self, params, step: int, num_heads=None) -> None:
        """Refresh pruning masks at interval boundaries past the offset."""
        if (self.prune_enabled(step)
                and (self._last_mask_step < 0
                     or step - self._last_mask_step >= self.mask_refresh_interval)):
            self.manager.update_masks(params, num_heads=num_heads)
            self._last_mask_step = step

    def compress(self, params, step: int):
        return self.manager.compress_params(
            params,
            quant_enabled=self.quant_enabled(step),
            prune_enabled=self.prune_enabled(step),
            # bits annealing counts from when quantization switches on
            # (reference qsteps, runtime/quantize.py:75)
            step=max(0, step - self.quant_offset))
