"""MoE layer with expert parallelism.

Counterpart of the reference ``deepspeed/moe/layer.py`` (``MoE`` :16) +
``experts.py`` (``Experts`` :10). Experts are a stacked parameter tensor
[num_experts, ...] sharded over the ``expert`` mesh axis; dispatched tokens
get a sharding constraint on the expert dimension so XLA emits the
all-to-all over ICI that the reference performs with ``_AllToAll``
(sharded_moe.py:95). Dispatch/combine are index-based gather/scatter
(O(tokens*k*hidden), the layout work the reference's cutlass
moe_gather/moe_scatter kernels do) rather than dense one-hot einsums
(O(tokens*experts*capacity*hidden) — quadratic in tokens); the expert FFN
itself runs as a batched einsum over the (expert-sharded) expert dim,
which IS the grouped-GEMM on the MXU (reference cutlass moe_gemm,
inference/v2/kernels/cutlass_ops).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime import topology as topo_mod
from ..runtime.topology import BATCH_AXES, DATA_AXIS, EXPERT_AXIS
from ..utils.jax_compat import with_sharding_constraint
from .sharded_moe import capacity as _capacity, top_k_gating_indices

Params = Dict[str, Any]


def _c(x, spec):
    return with_sharding_constraint(x, spec)


def moe_reference_forward(params: Params, tokens: jax.Array, *,
                          top_k: int, capacity: int, activation: str,
                          mask_pad: bool) -> Tuple[jax.Array, jax.Array]:
    """The dead-EP XLA expert path as ONE pure statement: gating ->
    capacity-slot gather -> grouped-einsum FFN -> weighted combine.
    ``tokens`` [T, H] -> (out [T, H], aux). This is the numerics
    reference the fused Pallas kernel pair (ISSUE 11,
    ``ops/transformer/pallas_moe.py``) is held to — its interpret-mode
    parity suite compares against this function, and the kernel path's
    ``custom_vjp`` backward IS this function's VJP (one statement of the
    gradient math shared with the ``DSTPU_MOE_KERNEL=xla`` hatch)."""
    n_tok, h = tokens.shape
    e = params["gate"].shape[-1]
    logits = tokens @ params["gate"].astype(tokens.dtype)
    eidx, pos, keep, weight, aux, _ = top_k_gating_indices(
        logits, top_k, capacity)
    cap = capacity
    slot = jnp.where(keep, eidx * cap + pos, e * cap).reshape(-1)
    src = jnp.zeros((e * cap + 1,), jnp.int32).at[slot].set(
        jnp.repeat(jnp.arange(n_tok, dtype=jnp.int32), top_k) + 1,
        mode="drop")[:e * cap]
    gathered = tokens[jnp.maximum(src - 1, 0)]
    if mask_pad:
        gathered = jnp.where((src > 0)[:, None], gathered,
                             jnp.zeros((), tokens.dtype))
    expert_in = gathered.reshape(e, cap, h)
    if activation == "silu_gated":
        gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in,
                                      params["wi_gate"].astype(tokens.dtype)))
        up = jnp.einsum("ech,ehf->ecf", expert_in,
                        params["wi_up"].astype(tokens.dtype))
        mid = gate * up
    else:
        mid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in,
                                     params["wi"].astype(tokens.dtype)))
    expert_out = jnp.einsum("ecf,efh->ech", mid,
                            params["wo"].astype(tokens.dtype))
    flat_out = expert_out.reshape(e * cap, h)
    picked = flat_out[jnp.where(keep, eidx * cap + pos, 0)]
    w = (weight * keep).astype(tokens.dtype)
    return jnp.sum(picked * w[:, :, None], axis=1), aux


@dataclasses.dataclass(frozen=True)
class MoE:
    hidden_size: int
    intermediate_size: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    activation: str = "silu_gated"  # 'silu_gated' | 'gelu'
    init_scale: float = 0.02
    #: fused Pallas kernel dispatch (ISSUE 11): None = the
    #: ``DSTPU_MOE_KERNEL`` env gate (auto: Pallas on single-chip TPU,
    #: XLA elsewhere); 'xla'/'pallas' pin per-layer (lint entries,
    #: parity tests). The kernel serves the dead-EP composition only —
    #: a live expert/pipeline mesh keeps the GSPMD exchange path.
    kernel: Any = None

    def init(self, rng, dtype=jnp.float32) -> Params:
        e, h, f = self.num_experts, self.hidden_size, self.intermediate_size
        ks = jax.random.split(rng, 4)
        scale = self.init_scale

        def w(r, shape):
            return (jax.random.normal(r, shape, jnp.float32) * scale).astype(dtype)

        params = {"gate": w(ks[0], (h, self.num_experts))}
        if self.activation == "silu_gated":
            params["wi_gate"] = w(ks[1], (e, h, f))
            params["wi_up"] = w(ks[2], (e, h, f))
        else:
            params["wi"] = w(ks[1], (e, h, f))
        params["wo"] = w(ks[3], (e, f, h))
        return params

    def specs(self) -> Params:
        expert_w = P(EXPERT_AXIS, None, None)
        out = {"gate": P(None, None), "wo": expert_w}
        if self.activation == "silu_gated":
            out["wi_gate"] = expert_w
            out["wi_up"] = expert_w
        else:
            out["wi"] = expert_w
        return out

    def _expert_ffn(self, params: Params, expert_in: jax.Array,
                    dtype) -> jax.Array:
        """The expert FFN as batched einsums over the (expert-sharded)
        expert dim — the grouped-GEMM on the MXU. Operates on any
        capacity extent, so the overlap planner's chunked dispatch can
        run it per capacity chunk (bitwise: each slot's row contracts
        the same operands either way)."""
        if self.activation == "silu_gated":
            gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in,
                                          params["wi_gate"].astype(dtype)))
            up = jnp.einsum("ech,ehf->ecf", expert_in,
                            params["wi_up"].astype(dtype))
            mid = gate * up
        else:
            mid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in,
                                         params["wi"].astype(dtype)))
        return jnp.einsum("ecf,efh->ech", mid, params["wo"].astype(dtype))

    def __call__(self, params: Params, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """x: [batch, seq, hidden] → (out, aux_loss)."""
        b, s, h = x.shape
        tokens = x.reshape(b * s, h)
        n_tok = b * s
        cap = _capacity(n_tok, self.num_experts, self.capacity_factor, self.min_capacity)

        # Fused Pallas kernel path (ISSUE 11, ops/transformer/pallas_moe
        # .py): route select + capacity scatter, the slot gather + wire
        # cast, and the grouped FFN + combine-scatter run as hand
        # kernels instead of the XLA op chain. DSTPU_MOE_KERNEL follows
        # the PR 10 discipline (auto = Pallas on single-chip TPU, XLA
        # elsewhere; 'xla' = bitwise hatch — this method's XLA path is
        # untouched; 'pallas' = force, interpret off-TPU). The kernel
        # serves the dead-EP/no-pipe composition: with a live expert
        # axis the exchange is GSPMD-mediated and stays XLA (the
        # multi-chip note in docs/KERNELS.md).
        from ..ops.transformer import pallas_moe
        from ..runtime import overlap_planner as op_mod
        if pallas_moe.moe_kernel_resolution(
                top_k=self.top_k, activation=self.activation,
                dtype=x.dtype, tokens=n_tok,
                num_experts=self.num_experts, hidden=h,
                kernel=self.kernel) == "pallas":
            # wired under the planner's chunked-dispatch scan: the plan's
            # scan-carry placement chunks the capacity dim so chunk c+1's
            # gather+cast launch issues from the carry under chunk c's
            # FFN+combine kernel (depth 1 — the kernel executor's clamp).
            # The carry rides the FUSED combine epilogue only: shapes
            # over the fused-combine VMEM budget run the split FFN +
            # token-major combine launches straight-line, so derive no
            # chunk count there (a derived nc the kernel cannot execute
            # would silently overstate the schedule).
            plan = op_mod.plan_for("moe-dispatch")
            nbytes = self.num_experts * cap * h * x.dtype.itemsize
            nc = (op_mod.moe_chunks_for_bytes(nbytes)
                  if (plan.placement == op_mod.PLACEMENT_SCAN_CARRY
                      and pallas_moe.moe_fused_combine_fits(n_tok, h))
                  else 1)
            fwd = pallas_moe.make_moe_forward(
                top_k=self.top_k, capacity=cap,
                activation=self.activation, mask_pad=False, n_chunks=nc)
            out2d, aux = fwd(params, tokens)
            return out2d.reshape(b, s, h), aux

        logits = tokens @ params["gate"].astype(x.dtype)
        eidx, pos, keep, weight, aux, _ = top_k_gating_indices(
            logits, self.top_k, cap)
        e = self.num_experts

        # Dispatch by GATHER, not by one-hot einsum: the reference's
        # "tec,th->ech" dispatch matmul costs O(tokens*experts*cap*hidden)
        # — quadratic in tokens (experts*cap ~ top_k*cf*tokens). Building
        # the inverse slot→token map is an O(tokens*k) integer scatter and
        # the row gather moves O(experts*cap*hidden) bytes with zero FLOPs
        # (the grouped-GEMM data layout the reference needs cutlass
        # moe_gather/moe_scatter kernels for, ragged_ops.cpp:20-47).
        slot = jnp.where(keep, eidx * cap + pos, e * cap).reshape(-1)
        src = jnp.zeros((e * cap + 1,), jnp.int32).at[slot].set(
            jnp.repeat(jnp.arange(n_tok, dtype=jnp.int32), self.top_k) + 1,
            mode="drop")[:e * cap]
        # under PIPELINE composition the dispatch/combine gathers sit inside
        # the stage vmap, where the partitioner cannot move their operands
        # from the stage-propagated sharding to the expert layout without an
        # "involuntary full rematerialization" fallback (a silent perf
        # cliff); pin the gather boundaries explicitly there. In the pure-EP
        # regime the propagated shardings are already right — and the pinned
        # replication would CHANGE the exchange pattern — so this is
        # trace-time conditional on a real pipe axis.
        pipelined = (topo_mod.is_initialized()
                     and topo_mod.get_topology().pipe_parallel_size > 1)
        if pipelined:
            tokens = _c(tokens, P(BATCH_AXES, None))
        # Unfilled capacity slots gather token 0's row UNMASKED: the
        # combine below never reads them (their combine weight is 0 and no
        # token's slot index points at them), so their contribution to
        # every output — and therefore their backward cotangent — is
        # exactly zero *as long as the pad rows' activations stay finite*.
        # Masking them with a where() would add a full [e*cap, h] select
        # plus its backward per layer for bytes that are already dead.
        # fp16 keeps the mask: a pad row routed through an expert it was
        # never assigned to can overflow fp16's range, and 0 * inf = NaN
        # would poison the expert-weight gradients (bf16/fp32 share
        # fp32's exponent range, so a pad row overflows only where a real
        # row would too). DSTPU_MOE_MASK_PAD=1 forces the masked form
        # (trace-time; for A/B).
        # Dispatch/combine transport plan (ISSUE 8, docs/COLLECTIVES.md):
        # the expert exchange is GSPMD-mediated (the constraints below make
        # the partitioner emit the all-to-all), so the wire narrows by
        # CASTING the dispatched activations — bf16 by default, exact
        # no-op when the model already computes in a <=2-byte dtype. Only
        # a live expert axis pays an exchange; without one the cast would
        # cost accuracy for zero wire bytes.
        from .. import comm as dist
        from ..runtime import overlap_planner as op_mod
        live_ep = (topo_mod.is_initialized()
                   and topo_mod.get_topology().expert_parallel_size > 1)
        wire_dtype = None
        if live_ep and x.dtype.itemsize > 2:
            tp = dist.resolve_transport(
                "activation", "all_to_all", e * cap * h * x.dtype.itemsize,
                (EXPERT_AXIS,))
            if tp.width == "bf16":
                wire_dtype = jnp.bfloat16

        def _exchange(t, spec):
            if wire_dtype is None:
                return _c(t, spec)
            return _c(t.astype(wire_dtype), spec).astype(x.dtype)

        mask_pad = (x.dtype == jnp.float16
                    or os.environ.get("DSTPU_MOE_MASK_PAD") == "1")

        # Overlap plan (ISSUE 9, runtime/overlap_planner.py): the planner's
        # scan-carry placement chunks the dispatch over the CAPACITY dim —
        # chunk c+1's token gather + expert exchange are issued from the
        # scan carry while chunk c's expert FFN computes, so the dispatch
        # wire hides under expert compute instead of fully preceding it.
        # Exact: each slot's gather row and FFN contraction are identical;
        # only launch placement changes. Since ISSUE 11 the COMBINE-side
        # exchange also rides the scan body: each chunk's expert rows
        # re-gather to tokens under a chunk mask right after that chunk's
        # FFN (every token's k slots span chunks, so the mask selects the
        # choices whose capacity slot lives in this chunk), which puts
        # nc-1 of the nc combine launches inside the body's circular
        # slack window — Layer D classifies them overlapped — leaving
        # only the LAST chunk's combine as the budget-justified epilogue
        # edge. Chunking is clamped to a divisor of the capacity and
        # skipped entirely under pipeline composition (the stage vmap
        # pins its own constraints) or a dead expert axis.
        plan = op_mod.plan_for("moe-dispatch")
        # the plan decides PLACEMENT; the chunk count scales with THIS
        # layer's actual exchange bytes (the committed n_chunks records
        # the audit entry's decision, not a production layer's). top_k>2
        # pins nc=1: the masked per-chunk combine below reassociates a
        # token's k weighted terms into chunk order, exact only while at
        # most two terms exist — beyond that the unchunked program is the
        # exactness contract.
        nc = (op_mod.moe_chunks_for_bytes(e * cap * h * x.dtype.itemsize)
              if (plan.placement == op_mod.PLACEMENT_SCAN_CARRY
                  and live_ep and not pipelined and self.top_k <= 2)
              else 1)
        while nc > 1 and cap % nc:
            nc -= 1

        if nc > 1:
            capc = cap // nc
            src_chunks = src.reshape(e, nc, capc).transpose(1, 0, 2)
            # token-side chunk membership: choice (t, k)'s capacity slot
            # lives in chunk pos // capc at local position pos % capc
            chunk_of = pos // capc
            pos_in = pos - chunk_of * capc

            def fetch(sc):
                flat = sc.reshape(-1)
                g = tokens[jnp.maximum(flat - 1, 0)]
                if mask_pad:
                    g = jnp.where((flat > 0)[:, None], g,
                                  jnp.zeros((), x.dtype))
                return _exchange(g.reshape(e, capc, h),
                                 P(EXPERT_AXIS, BATCH_AXES, None))

            def combine_chunk(y_c, c_idx):
                # masked per-chunk re-gather (ISSUE 11): the return
                # exchange materializes at this row gather, so placing it
                # here — inside the scan body / before the epilogue's
                # final adds — is what moves the combine wire off the
                # step edge. Algebraically exact vs the whole-capacity
                # epilogue gather for top-k <= 2 (each kept choice
                # contributes from exactly one chunk, masked-out choices
                # multiply by an exact 0, two-term addition commutes) —
                # and bitwise in the pinned tests/unit/moe composition;
                # across a LIVE expert exchange the partitioner may
                # reassociate the shard reduction around the weighted
                # sum, so engine-level parity with the unchunked program
                # is float-tolerance there (same class as the backward,
                # which PR 9 already pinned at tolerance).
                if wire_dtype is not None:
                    y_c = y_c.astype(wire_dtype)
                y_c = _c(y_c, P(EXPERT_AXIS, BATCH_AXES, None))
                flat_c = y_c.reshape(e * capc, h)
                in_chunk = keep & (chunk_of == c_idx)
                rows = flat_c[jnp.where(in_chunk, eidx * capc + pos_in, 0)]
                w_c = (weight * in_chunk).astype(x.dtype)
                return jnp.sum(rows.astype(x.dtype) * w_c[:, :, None],
                               axis=1)

            chunk_elems = e * capc * h
            wire = chunk_elems * (2 if wire_dtype is not None
                                  else x.dtype.itemsize)
            logical = chunk_elems * x.dtype.itemsize
            # prologue fetch is the pipeline edge (nothing to hide it);
            # the in-scan prefetches overlap the previous chunk's FFN
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=False, wire_bytes=wire)
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=True, count=nc - 1,
                                   wire_bytes=wire)
            # combine side: nc-1 masked re-gathers ride the scan body
            # (hidden in the circular slack window); the last chunk's
            # combine is the epilogue edge
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=True, count=nc - 1,
                                   wire_bytes=wire)
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=False, wire_bytes=wire)
            cur = fetch(src_chunks[0])

            def body(carry, xs_c):
                payload, acc = carry
                nxt = fetch(xs_c["src"])  # independent of the FFN below
                y_c = self._expert_ffn(params, payload, x.dtype)
                acc = acc + combine_chunk(y_c, xs_c["idx"])
                return (nxt, acc), None

            (last, acc), _ = jax.lax.scan(
                body, (cur, jnp.zeros((n_tok, h), x.dtype)),
                {"src": src_chunks[1:],
                 "idx": jnp.arange(nc - 1, dtype=jnp.int32)})
            y_last = self._expert_ffn(params, last, x.dtype)
            out = acc + combine_chunk(y_last, jnp.int32(nc - 1))
            return out.reshape(b, s, h), aux
        else:
            gathered = tokens[jnp.maximum(src - 1, 0)]
            if mask_pad:
                gathered = jnp.where((src > 0)[:, None], gathered,
                                     jnp.zeros((), x.dtype))
            if pipelined:
                gathered = _c(gathered, P(None, None))
            expert_in = gathered.reshape(e, cap, h)
            # all-to-all over ICI: expert dim sharded across the expert axis
            expert_in = _exchange(expert_in, P(EXPERT_AXIS, BATCH_AXES, None))
            # expert FFN as batched einsum over the (sharded) expert dim
            expert_out = self._expert_ffn(params, expert_in, x.dtype)

        # inverse all-to-all + combine back to tokens: per-token gather of
        # its k slots, weighted sum — O(tokens*k*hidden). The return
        # exchange materializes at the row gather below (the partitioner
        # reshards the expert-sharded rows to the token layout there), so
        # the wire cast must PERSIST through the gather — cast back only
        # on the picked rows.
        if wire_dtype is not None:
            expert_out = expert_out.astype(wire_dtype)
        expert_out = _c(expert_out, P(EXPERT_AXIS, BATCH_AXES, None))
        flat_out = expert_out.reshape(e * cap, h)
        picked = flat_out[jnp.where(keep, eidx * cap + pos, 0)]  # [t, k, h]
        picked = picked.astype(x.dtype)
        w = (weight * keep).astype(x.dtype)
        out = jnp.sum(picked * w[:, :, None], axis=1)
        return out.reshape(b, s, h), aux
