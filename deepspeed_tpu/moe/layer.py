"""MoE layer with expert parallelism.

Counterpart of the reference ``deepspeed/moe/layer.py`` (``MoE`` :16) +
``experts.py`` (``Experts`` :10). Experts are a stacked parameter tensor
[num_experts, ...] sharded over the ``expert`` mesh axis; dispatched tokens
get a sharding constraint on the expert dimension so XLA emits the
all-to-all over ICI that the reference performs with ``_AllToAll``
(sharded_moe.py:95). Dispatch/combine are index-based gather/scatter
(O(tokens*k*hidden), the layout work the reference's cutlass
moe_gather/moe_scatter kernels do) rather than dense one-hot einsums
(O(tokens*experts*capacity*hidden) — quadratic in tokens); the expert FFN
itself runs as a batched einsum over the (expert-sharded) expert dim,
which IS the grouped-GEMM on the MXU (reference cutlass moe_gemm,
inference/v2/kernels/cutlass_ops).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime import topology as topo_mod
from ..runtime.topology import BATCH_AXES, DATA_AXIS, EXPERT_AXIS
from ..utils.jax_compat import with_sharding_constraint
from .sharded_moe import capacity as _capacity, top_k_gating_indices

Params = Dict[str, Any]


def _c(x, spec):
    return with_sharding_constraint(x, spec)


@dataclasses.dataclass(frozen=True)
class MoE:
    hidden_size: int
    intermediate_size: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    activation: str = "silu_gated"  # 'silu_gated' | 'gelu'
    init_scale: float = 0.02

    def init(self, rng, dtype=jnp.float32) -> Params:
        e, h, f = self.num_experts, self.hidden_size, self.intermediate_size
        ks = jax.random.split(rng, 4)
        scale = self.init_scale

        def w(r, shape):
            return (jax.random.normal(r, shape, jnp.float32) * scale).astype(dtype)

        params = {"gate": w(ks[0], (h, self.num_experts))}
        if self.activation == "silu_gated":
            params["wi_gate"] = w(ks[1], (e, h, f))
            params["wi_up"] = w(ks[2], (e, h, f))
        else:
            params["wi"] = w(ks[1], (e, h, f))
        params["wo"] = w(ks[3], (e, f, h))
        return params

    def specs(self) -> Params:
        expert_w = P(EXPERT_AXIS, None, None)
        out = {"gate": P(None, None), "wo": expert_w}
        if self.activation == "silu_gated":
            out["wi_gate"] = expert_w
            out["wi_up"] = expert_w
        else:
            out["wi"] = expert_w
        return out

    def _expert_ffn(self, params: Params, expert_in: jax.Array,
                    dtype) -> jax.Array:
        """The expert FFN as batched einsums over the (expert-sharded)
        expert dim — the grouped-GEMM on the MXU. Operates on any
        capacity extent, so the overlap planner's chunked dispatch can
        run it per capacity chunk (bitwise: each slot's row contracts
        the same operands either way)."""
        if self.activation == "silu_gated":
            gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in,
                                          params["wi_gate"].astype(dtype)))
            up = jnp.einsum("ech,ehf->ecf", expert_in,
                            params["wi_up"].astype(dtype))
            mid = gate * up
        else:
            mid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in,
                                         params["wi"].astype(dtype)))
        return jnp.einsum("ecf,efh->ech", mid, params["wo"].astype(dtype))

    def __call__(self, params: Params, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """x: [batch, seq, hidden] → (out, aux_loss)."""
        b, s, h = x.shape
        tokens = x.reshape(b * s, h)
        n_tok = b * s
        cap = _capacity(n_tok, self.num_experts, self.capacity_factor, self.min_capacity)

        logits = tokens @ params["gate"].astype(x.dtype)
        eidx, pos, keep, weight, aux, _ = top_k_gating_indices(
            logits, self.top_k, cap)
        e = self.num_experts

        # Dispatch by GATHER, not by one-hot einsum: the reference's
        # "tec,th->ech" dispatch matmul costs O(tokens*experts*cap*hidden)
        # — quadratic in tokens (experts*cap ~ top_k*cf*tokens). Building
        # the inverse slot→token map is an O(tokens*k) integer scatter and
        # the row gather moves O(experts*cap*hidden) bytes with zero FLOPs
        # (the grouped-GEMM data layout the reference needs cutlass
        # moe_gather/moe_scatter kernels for, ragged_ops.cpp:20-47).
        slot = jnp.where(keep, eidx * cap + pos, e * cap).reshape(-1)
        src = jnp.zeros((e * cap + 1,), jnp.int32).at[slot].set(
            jnp.repeat(jnp.arange(n_tok, dtype=jnp.int32), self.top_k) + 1,
            mode="drop")[:e * cap]
        # under PIPELINE composition the dispatch/combine gathers sit inside
        # the stage vmap, where the partitioner cannot move their operands
        # from the stage-propagated sharding to the expert layout without an
        # "involuntary full rematerialization" fallback (a silent perf
        # cliff); pin the gather boundaries explicitly there. In the pure-EP
        # regime the propagated shardings are already right — and the pinned
        # replication would CHANGE the exchange pattern — so this is
        # trace-time conditional on a real pipe axis.
        pipelined = (topo_mod.is_initialized()
                     and topo_mod.get_topology().pipe_parallel_size > 1)
        if pipelined:
            tokens = _c(tokens, P(BATCH_AXES, None))
        # Unfilled capacity slots gather token 0's row UNMASKED: the
        # combine below never reads them (their combine weight is 0 and no
        # token's slot index points at them), so their contribution to
        # every output — and therefore their backward cotangent — is
        # exactly zero *as long as the pad rows' activations stay finite*.
        # Masking them with a where() would add a full [e*cap, h] select
        # plus its backward per layer for bytes that are already dead.
        # fp16 keeps the mask: a pad row routed through an expert it was
        # never assigned to can overflow fp16's range, and 0 * inf = NaN
        # would poison the expert-weight gradients (bf16/fp32 share
        # fp32's exponent range, so a pad row overflows only where a real
        # row would too). DSTPU_MOE_MASK_PAD=1 forces the masked form
        # (trace-time; for A/B).
        import os
        # Dispatch/combine transport plan (ISSUE 8, docs/COLLECTIVES.md):
        # the expert exchange is GSPMD-mediated (the constraints below make
        # the partitioner emit the all-to-all), so the wire narrows by
        # CASTING the dispatched activations — bf16 by default, exact
        # no-op when the model already computes in a <=2-byte dtype. Only
        # a live expert axis pays an exchange; without one the cast would
        # cost accuracy for zero wire bytes.
        from .. import comm as dist
        from ..runtime import overlap_planner as op_mod
        live_ep = (topo_mod.is_initialized()
                   and topo_mod.get_topology().expert_parallel_size > 1)
        wire_dtype = None
        if live_ep and x.dtype.itemsize > 2:
            tp = dist.resolve_transport(
                "activation", "all_to_all", e * cap * h * x.dtype.itemsize,
                (EXPERT_AXIS,))
            if tp.width == "bf16":
                wire_dtype = jnp.bfloat16

        def _exchange(t, spec):
            if wire_dtype is None:
                return _c(t, spec)
            return _c(t.astype(wire_dtype), spec).astype(x.dtype)

        mask_pad = (x.dtype == jnp.float16
                    or os.environ.get("DSTPU_MOE_MASK_PAD") == "1")

        # Overlap plan (ISSUE 9, runtime/overlap_planner.py): the planner's
        # scan-carry placement chunks the dispatch over the CAPACITY dim —
        # chunk c+1's token gather + expert exchange are issued from the
        # scan carry while chunk c's expert FFN computes, so the dispatch
        # wire hides under expert compute instead of fully preceding it.
        # Exact: each slot's gather row and FFN contraction are identical;
        # only launch placement changes. The combine-side exchange stays
        # at the epilogue (every token's k slots span all chunks — there
        # is no per-chunk combine without masked re-gathers), which is the
        # entry's budget-justified edge exposure. Chunking is clamped to a
        # divisor of the capacity and skipped entirely under pipeline
        # composition (the stage vmap pins its own constraints) or a dead
        # expert axis.
        plan = op_mod.plan_for("moe-dispatch")
        # the plan decides PLACEMENT; the chunk count scales with THIS
        # layer's actual exchange bytes (the committed n_chunks records
        # the audit entry's decision, not a production layer's)
        nc = (op_mod.moe_chunks_for_bytes(e * cap * h * x.dtype.itemsize)
              if (plan.placement == op_mod.PLACEMENT_SCAN_CARRY
                  and live_ep and not pipelined) else 1)
        while nc > 1 and cap % nc:
            nc -= 1

        if nc > 1:
            src_chunks = src.reshape(e, nc, cap // nc).transpose(1, 0, 2)

            def fetch(sc):
                flat = sc.reshape(-1)
                g = tokens[jnp.maximum(flat - 1, 0)]
                if mask_pad:
                    g = jnp.where((flat > 0)[:, None], g,
                                  jnp.zeros((), x.dtype))
                return _exchange(g.reshape(e, cap // nc, h),
                                 P(EXPERT_AXIS, BATCH_AXES, None))

            chunk_elems = e * (cap // nc) * h
            wire = chunk_elems * (2 if wire_dtype is not None
                                  else x.dtype.itemsize)
            logical = chunk_elems * x.dtype.itemsize
            # prologue fetch is the pipeline edge (nothing to hide it);
            # the in-scan prefetches overlap the previous chunk's FFN
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=False, wire_bytes=wire)
            dist.record_collective("all_to_all", logical, (EXPERT_AXIS,),
                                   overlapped=True, count=nc - 1,
                                   wire_bytes=wire)
            cur = fetch(src_chunks[0])

            def body(carry, sc):
                nxt = fetch(sc)  # independent of the FFN below
                return nxt, self._expert_ffn(params, carry, x.dtype)

            last, ys = jax.lax.scan(body, cur, src_chunks[1:])
            y_last = self._expert_ffn(params, last, x.dtype)
            expert_out = jnp.concatenate([ys, y_last[None]], axis=0)
            expert_out = expert_out.transpose(1, 0, 2, 3).reshape(e, cap, h)
        else:
            gathered = tokens[jnp.maximum(src - 1, 0)]
            if mask_pad:
                gathered = jnp.where((src > 0)[:, None], gathered,
                                     jnp.zeros((), x.dtype))
            if pipelined:
                gathered = _c(gathered, P(None, None))
            expert_in = gathered.reshape(e, cap, h)
            # all-to-all over ICI: expert dim sharded across the expert axis
            expert_in = _exchange(expert_in, P(EXPERT_AXIS, BATCH_AXES, None))
            # expert FFN as batched einsum over the (sharded) expert dim
            expert_out = self._expert_ffn(params, expert_in, x.dtype)

        # inverse all-to-all + combine back to tokens: per-token gather of
        # its k slots, weighted sum — O(tokens*k*hidden). The return
        # exchange materializes at the row gather below (the partitioner
        # reshards the expert-sharded rows to the token layout there), so
        # the wire cast must PERSIST through the gather — cast back only
        # on the picked rows.
        if wire_dtype is not None:
            expert_out = expert_out.astype(wire_dtype)
        expert_out = _c(expert_out, P(EXPERT_AXIS, BATCH_AXES, None))
        flat_out = expert_out.reshape(e * cap, h)
        picked = flat_out[jnp.where(keep, eidx * cap + pos, 0)]  # [t, k, h]
        picked = picked.astype(x.dtype)
        w = (weight * keep).astype(x.dtype)
        out = jnp.sum(picked * w[:, :, None], axis=1)
        return out.reshape(b, s, h), aux
