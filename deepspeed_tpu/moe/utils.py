"""MoE parameter utilities.

Counterpart of the reference ``deepspeed/moe/utils.py`` (``is_moe_param``
:23, ``split_params_into_shared_and_expert_params`` :29,
``split_params_into_different_moe_groups_for_optimizer`` :65). The
reference needs these to give expert parameters their own torch optimizer
param groups (their gradient allreduce runs over a different process
group). Under SPMD the collective routing is already carried by each
leaf's PartitionSpec — what remains useful is the SPLIT itself: per-group
optimizer hyperparameters (expert LR scaling, excluding experts from
weight decay) over a param pytree.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
from jax.sharding import PartitionSpec as P

from ..runtime.topology import EXPERT_AXIS
from ..runtime.zero.partition import flatten_spec_axes


def _spec_leaf(s) -> bool:
    # replicated leaves carry spec None (the add_axes_to_spec convention,
    # runtime/zero/partition.py:54) — they must stay LEAVES, not vanish
    # as empty subtrees
    return s is None or isinstance(s, P)


def is_moe_spec(spec) -> bool:
    """True when a leaf's PartitionSpec shards it over the expert axis —
    the SPMD analogue of the reference's ``param.allreduce = False`` mark
    (``is_moe_param``, moe/utils.py:23)."""
    if not isinstance(spec, P):
        return False
    return EXPERT_AXIS in flatten_spec_axes(spec)


def expert_param_mask(specs: Dict[str, Any]) -> Dict[str, Any]:
    """Boolean pytree (True = expert-sharded leaf): the
    ``split_params_into_different_moe_groups_for_optimizer`` equivalent —
    pass to ``optax.masked(tx, mask)`` to scope a transform to expert (or
    with ``jax.tree.map(operator.not_, mask)``, shared) parameters."""
    return jax.tree.map(is_moe_spec, specs, is_leaf=_spec_leaf)


def split_params_into_shared_and_expert_params(
        params: Dict[str, Any], specs: Dict[str, Any],
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Two same-structure trees: (shared, expert) — each leaf appears in
    exactly one of them, the other holds ``None`` (reference
    moe/utils.py:29). Same DICT shape, not the same pytree structure:
    None entries flatten to zero leaves in JAX, so don't tree.map the
    two trees against each other or against ``params``. For optax
    integration use :func:`expert_param_mask` (``optax.masked`` wants
    the boolean mask, not these trees)."""
    mask = expert_param_mask(specs)
    shared = jax.tree.map(lambda p, m: None if m else p, params, mask)
    expert = jax.tree.map(lambda p, m: p if m else None, params, mask)
    return shared, expert
