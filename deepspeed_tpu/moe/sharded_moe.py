"""Gating + expert dispatch math.

Counterpart of the reference ``deepspeed/moe/sharded_moe.py``: ``TopKGate``
(:348), ``top1gating`` (:184), ``_capacity`` (:162), ``_AllToAll`` (:95),
``MOELayer`` (:425). The reference dispatches tokens with einsum-built
one-hot masks and a ``torch.distributed`` all-to-all across the expert
group; here the same capacity-bucketed dispatch is built with static shapes
(XLA requirement) and the expert exchange is expressed through sharding:
the dispatch tensor [experts, capacity, d] carries a sharding constraint
that splits the expert dim over the ``expert`` mesh axis, so the SPMD
partitioner emits the all-to-all over ICI.

Load-balancing aux loss follows the reference (GShard l_aux = E * Σ me·ce,
sharded_moe.py:266-272).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def capacity(num_tokens: int, num_experts: int, capacity_factor: float,
             min_capacity: int) -> int:
    """Reference ``_capacity`` (sharded_moe.py:162) — tokens per expert."""
    cap = int(num_tokens * capacity_factor * 1.0 / num_experts)
    return max(cap, min_capacity)


def top_k_gating_indices(logits: jax.Array, top_k: int, capacity_: int):
    """Top-k gate with capacity, in INDEX form.

    logits: [tokens, experts]. Returns
      expert_idx [tokens, k] int32 — chosen expert per (token, choice)
      pos        [tokens, k] int32 — slot inside the expert's capacity bucket
      keep       [tokens, k] bool  — False when the bucket overflowed
      weight     [tokens, k] f32   — normalized combine weight (0 if dropped)
      aux_loss   scalar (GShard load-balancing loss, scaled by E)
      me         [experts] mean gate probability (for monitoring)

    The index form is what the dispatch actually needs: building dense
    one-hot [tokens, experts, capacity] masks and contracting them (the
    reference's einsum dispatch, sharded_moe.py:425) costs
    O(tokens*experts*capacity*hidden) FLOPs — quadratic in tokens; the
    gather/scatter dispatch built from indices is O(tokens*k*hidden).

    ROUTE-PARITY CONTRACT (ISSUE 11): the fused Pallas route kernel
    (``ops/transformer/pallas_moe.py::_route_kernel``) replicates this
    function's fp32 operation sequence EXACTLY — same softmax, same
    lowest-index tie rule (``lax.top_k`` == masked re-argmax), same
    cumsum position ranks, capacity clamps and weight normalization —
    so kernel- and XLA-path routing decisions are bit-identical. Any
    change here must be mirrored there;
    ``tests/unit/ops/test_pallas_moe.py::TestRoute`` pins the pair.
    """
    tokens, num_experts = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-k expert choice per token
    _, expert_idx = jax.lax.top_k(gates, top_k)  # [tokens, k]

    # aux loss from the top-1 assignment like the reference (top1gating :238)
    mask1 = jax.nn.one_hot(expert_idx[:, 0], num_experts, dtype=jnp.float32)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * num_experts

    # process the k choices sequentially so capacity counting is consistent
    counts = jnp.zeros((num_experts,), dtype=jnp.int32)
    gate_sum = jnp.zeros((tokens,), dtype=jnp.float32)
    idxs, poss, keeps, gatews = [], [], [], []
    for k in range(top_k):
        idx_k = expert_idx[:, k]  # [tokens]
        mask_k = jax.nn.one_hot(idx_k, num_experts, dtype=jnp.int32)
        # rank of each token within the tokens routed to the same expert
        pos_in_expert = jnp.cumsum(mask_k, axis=0) - mask_k  # [tokens, experts]
        pos_k = jnp.sum(pos_in_expert * mask_k, axis=1) + counts[idx_k]
        keep = pos_k < capacity_
        gate_k = jnp.take_along_axis(gates, idx_k[:, None], axis=1)[:, 0] * keep
        idxs.append(idx_k)
        poss.append(jnp.minimum(pos_k, capacity_ - 1))
        keeps.append(keep)
        gatews.append(gate_k)
        counts = counts + jnp.sum(mask_k * keep[:, None], axis=0)
        gate_sum = gate_sum + gate_k

    # normalize combine weights over kept choices (reference top2gating :341)
    denom = jnp.maximum(gate_sum, 1e-9)
    weight = jnp.stack(gatews, axis=1) / denom[:, None]
    return (jnp.stack(idxs, axis=1).astype(jnp.int32),
            jnp.stack(poss, axis=1).astype(jnp.int32),
            jnp.stack(keeps, axis=1),
            weight, aux_loss, me)


def top_k_gating(logits: jax.Array, top_k: int, capacity_: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-k gate with capacity, in DENSE one-hot form (API parity with the
    reference's top1gating/top2gating tensors).

    logits: [tokens, experts]. Returns
      combine   [tokens, experts, capacity]  — weights for gathering results
      dispatch  [tokens, experts, capacity]  — boolean one-hot routing
      aux_loss  scalar (GShard load-balancing loss, scaled by E)
      me        [experts] mean gate probability (for monitoring)
    """
    tokens, num_experts = logits.shape
    expert_idx, pos, keep, weight, aux_loss, me = \
        top_k_gating_indices(logits, top_k, capacity_)
    combine = jnp.zeros((tokens, num_experts, capacity_), dtype=jnp.float32)
    dispatch = jnp.zeros((tokens, num_experts, capacity_), dtype=bool)
    token_ids = jnp.arange(tokens)
    for k in range(expert_idx.shape[1]):
        combine = combine.at[token_ids, expert_idx[:, k], pos[:, k]].add(
            jnp.where(keep[:, k], weight[:, k], 0.0))
        dispatch = dispatch.at[token_ids, expert_idx[:, k], pos[:, k]].max(
            keep[:, k])
    return combine, dispatch, aux_loss, me
