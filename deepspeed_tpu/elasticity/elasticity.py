"""Elastic batch-size solver.

Counterpart of the reference ``deepspeed/elasticity/elasticity.py``
(``compute_elastic_config`` :233, ``_get_compatible_gpus_v01/v02`` :83,126):
pre-computes global batch sizes compatible with a *range* of accelerator
counts so a job restarted on a resized TPU slice keeps identical batch
semantics. The math is hardware-agnostic and ports directly; "gpus" in the
reference API means model replicas, i.e. chips/data-parallel ranks here.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    ...


class ElasticityConfigError(ElasticityError):
    ...


class ElasticityIncompatibleWorldSize(ElasticityError):
    ...


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """All multiples of each base micro-batch up to the cap (reference :35)."""
    candidates = set()
    for base in base_list:
        if base <= 0:
            continue
        value = base
        while value <= max_acceptable_batch_size:
            candidates.add(value)
            value += base
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    """Device counts that evenly divide batch into one of the micro sizes
    (reference :47)."""
    valid = set()
    for micro in micro_batches:
        if micro <= 0 or batch_size % micro:
            continue
        max_gpus = batch_size // micro
        for n in range(1, max_gpus + 1):
            if max_gpus % n == 0 and min_valid_gpus <= n <= max_valid_gpus:
                valid.add(n)
    return sorted(valid)


def _get_compatible_gpus_v01(micro_batches: List[int], max_acceptable_batch_size: int,
                             min_gpus: int = 1, max_gpus: int = 10000,
                             prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """Reference :83 — pick the batch size maximizing compatible device counts."""
    candidates = get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    best: Tuple[int, List[int]] = (0, [])
    for batch in (candidates if not prefer_larger else reversed(candidates)):
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        if len(valid) > len(best[1]):
            best = (batch, valid)
    if not best[1]:
        raise ElasticityError(
            f"No compatible batch size found for micro_batches={micro_batches} "
            f"max={max_acceptable_batch_size} gpus=[{min_gpus},{max_gpus}]")
    return best


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size, current_num_gpus,
                             min_gpus=1, max_gpus=10000, prefer_larger=True,
                             num_gpus_per_node: int = 1, model_parallel_size: int = 1):
    """Reference :126 — v0.2 accounts for model parallelism: batch applies to
    data-parallel replicas = world / mp."""
    if current_num_gpus % model_parallel_size:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} not divisible by mp {model_parallel_size}")
    dp = current_num_gpus // model_parallel_size
    batch, valid = _get_compatible_gpus_v01(
        micro_batches, max_acceptable_batch_size,
        min_gpus=max(1, min_gpus // model_parallel_size),
        max_gpus=max_gpus // model_parallel_size,
        prefer_larger=prefer_larger)
    if dp not in valid:
        raise ElasticityIncompatibleWorldSize(
            f"data-parallel size {dp} not in compatible set {valid}")
    return batch, [v * model_parallel_size for v in valid], batch // dp


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference :233 — resolve (final_batch_size, valid_gpus[, micro_batch])."""
    e = ds_config.get("elasticity", {})
    if not e.get("enabled", False):
        raise ElasticityConfigError("elasticity not enabled in config")
    micro_batches = e.get("micro_batch_sizes", [2, 4, 6])
    max_batch = e.get("max_train_batch_size", 2000)
    min_gpus, max_gpus = e.get("min_gpus", 1), e.get("max_gpus", 10000)
    prefer_larger = e.get("prefer_larger_batch", True)
    version = e.get("version", LATEST_ELASTICITY_VERSION)

    if float(version) >= 0.2 and world_size > 0:
        mp = e.get("model_parallel_size", 1)
        batch, valid, micro = _get_compatible_gpus_v02(
            micro_batches, max_batch, world_size, min_gpus, max_gpus,
            prefer_larger, model_parallel_size=mp)
        return (batch, valid, micro) if return_microbatch else (batch, valid)

    batch, valid = _get_compatible_gpus_v01(
        micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    if world_size > 0 and world_size not in valid:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not compatible: valid={valid}")
    if return_microbatch:
        dp = world_size if world_size > 0 else valid[-1]
        return batch, valid, max(1, batch // dp)
    return batch, valid


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict,
                                    frozen_elastic_config_dict: Dict) -> None:
    """Reference :208 — elastic config must not change across restarts."""
    if runtime_elastic_config_dict != frozen_elastic_config_dict:
        raise ElasticityConfigError(
            "Elastic config changed between scheduler and runtime; "
            f"frozen={frozen_elastic_config_dict} runtime={runtime_elastic_config_dict}")
