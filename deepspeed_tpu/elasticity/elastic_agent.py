"""Elastic training agent: supervise, restart, reshape.

Counterpart of the reference's ``elasticity/elastic_agent.py``
(``DSElasticAgent`` :28, extending torch-elastic's ``LocalElasticAgent``):
keep a training job alive across worker failures by restarting the world —
possibly at a DIFFERENT size — while keeping the global batch schedule valid
via the elasticity solver (``elasticity.py`` ``compute_elastic_config``).

TPU-first shape: there is no c10d rendezvous store to re-seed — a JAX world
is (coordinator address, num_processes, process_id) env vars, so a restart
is simply re-spawning per-slot processes with a fresh
``JAX_COORDINATOR_ADDRESS`` port and the re-solved world size exported as
``DSTPU_ELASTIC`` (json: world_size / train_batch / micro_batch / gas).
Workers read it before ``deepspeed_tpu.initialize`` to configure batches.

Failure policy: on any worker failure the remaining world is torn down
(collectives cannot survive a lost peer) and relaunched; with
``shrink_on_failure`` each retry drops one slot, re-solving the batch
config, until ``min_gpus`` — the reference's membership-change path.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger
from .elasticity import compute_elastic_config


class DSElasticAgent:

    def __init__(self,
                 user_script: str,
                 user_args: Optional[List[str]] = None,
                 ds_config: Optional[Dict[str, Any]] = None,
                 num_slots: int = 1,
                 max_restarts: int = 3,
                 shrink_on_failure: bool = True,
                 master_addr: str = "localhost",
                 master_port: int = 29555,
                 extra_env: Optional[Dict[str, str]] = None,
                 spawn_fn: Optional[Callable] = None):
        self.user_script = user_script
        self.user_args = list(user_args or [])
        self.ds_config = ds_config or {}
        self.num_slots = num_slots
        self.max_restarts = max_restarts
        self.shrink_on_failure = shrink_on_failure
        self.master_addr = master_addr
        self.master_port = master_port
        self.extra_env = dict(extra_env or {})
        self.restart_count = 0
        self.world_history: List[int] = []
        self._spawn = spawn_fn or self._default_spawn

    # -- world solving ------------------------------------------------------
    def _solve_world(self, slots: int) -> Dict[str, Any]:
        """Largest elasticity-valid world size <= slots plus its batch
        config; without an elastic config every size is valid."""
        el = self.ds_config.get("elasticity")
        if not el or not el.get("enabled", False):
            mb = self.ds_config.get("train_micro_batch_size_per_gpu", 1)
            return {"world_size": slots, "micro_batch": mb,
                    "train_batch": mb * slots, "gas": 1}
        final_batch, valid_gpus = compute_elastic_config(self.ds_config)
        fit = [g for g in valid_gpus if g <= slots]
        if not fit:
            raise RuntimeError(
                f"no elasticity-valid world size fits {slots} slots "
                f"(valid: {valid_gpus})")
        world = max(fit)
        per_gpu = final_batch // world
        micro = max(m for m in el.get("micro_batch_sizes", [2, 4, 6])
                    if per_gpu % m == 0)
        return {"world_size": world, "micro_batch": micro,
                "train_batch": final_batch, "gas": per_gpu // micro}

    # -- spawning -----------------------------------------------------------
    def _default_spawn(self, world: Dict[str, Any], attempt: int) -> List[subprocess.Popen]:
        procs = []
        n = world["world_size"]
        port = self.master_port + attempt  # stale coordinator never rejoins
        for rank in range(n):
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update({
                "JAX_COORDINATOR_ADDRESS": f"{self.master_addr}:{port}",
                "JAX_NUM_PROCESSES": str(n),
                "JAX_PROCESS_ID": str(rank),
                "DSTPU_ELASTIC": json.dumps({**world, "restart_count": attempt}),
            })
            cmd = [sys.executable, self.user_script] + self.user_args
            procs.append(subprocess.Popen(cmd, env=env))
        return procs

    @staticmethod
    def _reap(procs: List[subprocess.Popen], poll_s: float = 0.1) -> int:
        """First nonzero exit code (terminating peers), else 0."""
        rc = 0
        live = list(procs)
        while live:
            for p in list(live):
                code = p.poll()
                if code is None:
                    continue
                live.remove(p)
                if code and not rc:
                    rc = code
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if live:
                time.sleep(poll_s)
        return rc

    # -- main loop ----------------------------------------------------------
    def run(self) -> int:
        """Supervise until clean exit or restart budget exhausted
        (reference ``DSElasticAgent._invoke_run`` :106). SIGINT/SIGTERM to
        the agent fan out to the live workers — a scheduler killing the
        supervisor must not orphan the world."""
        live_procs: List[subprocess.Popen] = []

        def fan_out(sig, frame):
            for p in live_procs:
                if p.poll() is None:
                    p.send_signal(sig)
            raise SystemExit(128 + sig)

        old_int = signal.signal(signal.SIGINT, fan_out)
        old_term = signal.signal(signal.SIGTERM, fan_out)
        try:
            return self._run(live_procs)
        finally:
            signal.signal(signal.SIGINT, old_int)
            signal.signal(signal.SIGTERM, old_term)

    def _run(self, live_procs: List[subprocess.Popen]) -> int:
        slots = self.num_slots
        attempt = 0
        while True:
            world = self._solve_world(slots)
            self.world_history.append(world["world_size"])
            logger.info(
                f"elastic agent: attempt {attempt}, world {world['world_size']} "
                f"(batch {world['train_batch']} = {world['micro_batch']} "
                f"x {world['world_size']} x gas {world['gas']})")
            procs = self._spawn(world, attempt)
            live_procs[:] = procs
            rc = self._reap(procs)
            live_procs[:] = []
            if rc == 0:
                return 0
            self.restart_count += 1
            attempt += 1
            if self.restart_count > self.max_restarts:
                logger.error(f"elastic agent: restart budget exhausted (rc={rc})")
                return rc
            if self.shrink_on_failure and slots > 1:
                slots -= 1
            logger.warning(
                f"elastic agent: worker failed (rc={rc}); restarting with "
                f"{slots} slots ({self.restart_count}/{self.max_restarts})")
