"""Elastic training agent: supervise, restart, reshape.

Counterpart of the reference's ``elasticity/elastic_agent.py``
(``DSElasticAgent`` :28, extending torch-elastic's ``LocalElasticAgent``):
keep a training job alive across worker failures by restarting the world —
possibly at a DIFFERENT size — while keeping the global batch schedule valid
via the elasticity solver (``elasticity.py`` ``compute_elastic_config``).

TPU-first shape: there is no c10d rendezvous store to re-seed — a JAX world
is (coordinator address, num_processes, process_id) env vars, so a restart
is simply re-spawning per-slot processes with a fresh
``JAX_COORDINATOR_ADDRESS`` port and the re-solved world size exported as
``DSTPU_ELASTIC`` (json: world_size / train_batch / micro_batch / gas).
Workers read it before ``deepspeed_tpu.initialize`` to configure batches.

Failure policy: on any worker failure the remaining world is torn down
(collectives cannot survive a lost peer) and relaunched — after an
exponential backoff (``restart_backoff_s``; a crash-looping script must
not burn its restart budget in milliseconds); with ``shrink_on_failure``
each retry drops one slot, re-solving the batch config, until
``min_gpus`` — the reference's membership-change path.

Elastic resume (dstpu-resilience): pass ``checkpoint_dir`` and the agent
threads it through ``DSTPU_ELASTIC`` — ``deepspeed_tpu.initialize``
resumes every (re)started world from the last *committed* tag there, at
whatever dp width the restart solved (the checkpoint store's span
assembly re-buckets ZeRO shards on load). See docs/RESILIENCE.md.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger
from .elasticity import compute_elastic_config


class DSElasticAgent:

    def __init__(self,
                 user_script: str,
                 user_args: Optional[List[str]] = None,
                 ds_config: Optional[Dict[str, Any]] = None,
                 num_slots: int = 1,
                 max_restarts: int = 3,
                 shrink_on_failure: bool = True,
                 master_addr: str = "localhost",
                 master_port: int = 29555,
                 extra_env: Optional[Dict[str, str]] = None,
                 spawn_fn: Optional[Callable] = None,
                 checkpoint_dir: Optional[str] = None,
                 restart_backoff_s: float = 1.0,
                 max_backoff_s: float = 30.0):
        self.user_script = user_script
        self.user_args = list(user_args or [])
        self.ds_config = ds_config or {}
        self.num_slots = num_slots
        self.max_restarts = max_restarts
        self.shrink_on_failure = shrink_on_failure
        self.master_addr = master_addr
        self.master_port = master_port
        self.extra_env = dict(extra_env or {})
        self.checkpoint_dir = checkpoint_dir
        self.restart_backoff_s = float(restart_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.restart_count = 0
        self.world_history: List[int] = []
        self._spawn = spawn_fn or self._default_spawn

    # -- world solving ------------------------------------------------------
    def _solve_world(self, slots: int) -> Dict[str, Any]:
        """Largest elasticity-valid world size <= slots plus its batch
        config; without an elastic config every size is valid."""
        el = self.ds_config.get("elasticity")
        if not el or not el.get("enabled", False):
            mb = self.ds_config.get("train_micro_batch_size_per_gpu", 1)
            return {"world_size": slots, "micro_batch": mb,
                    "train_batch": mb * slots, "gas": 1}
        final_batch, valid_gpus = compute_elastic_config(self.ds_config)
        fit = [g for g in valid_gpus if g <= slots]
        if not fit:
            raise RuntimeError(
                f"no elasticity-valid world size fits {slots} slots "
                f"(valid: {valid_gpus})")
        world = max(fit)
        per_gpu = final_batch // world
        sizes = el.get("micro_batch_sizes", [2, 4, 6])
        divisible = [m for m in sizes if m >= 1 and per_gpu % m == 0]
        if divisible:
            micro = max(divisible)
        else:
            # no configured micro size divides per-gpu batch (e.g. prime
            # per_gpu after a shrink): micro=1 always divides — degrade
            # with a loud note instead of a bare max() ValueError
            micro = 1
            logger.warning(
                f"elasticity: no micro_batch_sizes entry of {sizes} "
                f"divides per-gpu batch {per_gpu} (train_batch "
                f"{final_batch} over world {world}); falling back to "
                f"micro_batch=1 x gas={per_gpu} — add a divisor of "
                f"{per_gpu} to micro_batch_sizes to silence this")
        return {"world_size": world, "micro_batch": micro,
                "train_batch": final_batch, "gas": per_gpu // micro}

    # -- spawning -----------------------------------------------------------
    def _probe_port(self, base: int, tries: int = 64) -> int:
        """First bindable coordinator port at or above ``base``. A fixed
        ``master_port + attempt`` can collide with a lingering listener
        (an unreaped coordinator from the PREVIOUS attempt, another job)
        — and a world that dies on bind burns a restart credit for a
        failure that is the agent's to dodge, not the script's."""
        for port in range(base, base + tries):
            try:
                with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((self.master_addr, port))
                return port
            except OSError:
                continue
        raise RuntimeError(
            f"no free coordinator port in [{base}, {base + tries}) on "
            f"{self.master_addr} — set master_port to a free range")

    def _world_env(self, world: Dict[str, Any], attempt: int) -> Dict[str, Any]:
        payload = {**world, "restart_count": attempt}
        if self.checkpoint_dir is not None:
            # the elastic-resume thread: workers (deepspeed_tpu.initialize)
            # resume from the last committed tag here
            payload["checkpoint_dir"] = self.checkpoint_dir
        return payload

    def _default_spawn(self, world: Dict[str, Any], attempt: int) -> List[subprocess.Popen]:
        procs = []
        n = world["world_size"]
        # advancing base per attempt keeps a stale coordinator from
        # rejoining; probing dodges ports something else already holds
        port = self._probe_port(self.master_port + attempt)
        for rank in range(n):
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update({
                "JAX_COORDINATOR_ADDRESS": f"{self.master_addr}:{port}",
                "JAX_NUM_PROCESSES": str(n),
                "JAX_PROCESS_ID": str(rank),
                "DSTPU_ELASTIC": json.dumps(self._world_env(world, attempt)),
            })
            cmd = [sys.executable, self.user_script] + self.user_args
            procs.append(subprocess.Popen(cmd, env=env))
        return procs

    @staticmethod
    def _reap(procs: List[subprocess.Popen], poll_s: float = 0.1) -> int:
        """First nonzero exit code (terminating peers), else 0."""
        rc = 0
        live = list(procs)
        while live:
            for p in list(live):
                code = p.poll()
                if code is None:
                    continue
                live.remove(p)
                if code and not rc:
                    rc = code
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if live:
                time.sleep(poll_s)
        return rc

    # -- main loop ----------------------------------------------------------
    def run(self) -> int:
        """Supervise until clean exit or restart budget exhausted
        (reference ``DSElasticAgent._invoke_run`` :106). SIGINT/SIGTERM to
        the agent fan out to the live workers — a scheduler killing the
        supervisor must not orphan the world."""
        live_procs: List[subprocess.Popen] = []

        def fan_out(sig, frame):
            for p in live_procs:
                if p.poll() is None:
                    p.send_signal(sig)
            raise SystemExit(128 + sig)

        old_int = signal.signal(signal.SIGINT, fan_out)
        old_term = signal.signal(signal.SIGTERM, fan_out)
        try:
            return self._run(live_procs)
        finally:
            signal.signal(signal.SIGINT, old_int)
            signal.signal(signal.SIGTERM, old_term)

    def _run(self, live_procs: List[subprocess.Popen]) -> int:
        slots = self.num_slots
        attempt = 0
        while True:
            world = self._solve_world(slots)
            self.world_history.append(world["world_size"])
            # a (re)solved world is a resize event: the tune controller
            # re-searches the batch-geometry knobs for the new dp width
            from ..resilience.events import announce_resize
            announce_resize(world, attempt=attempt)
            logger.info(
                f"elastic agent: attempt {attempt}, world {world['world_size']} "
                f"(batch {world['train_batch']} = {world['micro_batch']} "
                f"x {world['world_size']} x gas {world['gas']})")
            procs = self._spawn(world, attempt)
            live_procs[:] = procs
            rc = self._reap(procs)
            live_procs[:] = []
            if rc == 0:
                return 0
            self.restart_count += 1
            attempt += 1
            if self.restart_count > self.max_restarts:
                logger.error(f"elastic agent: restart budget exhausted (rc={rc})")
                return rc
            if self.shrink_on_failure and slots > 1:
                slots -= 1
            backoff = min(self.restart_backoff_s * (2 ** (self.restart_count - 1)),
                          self.max_backoff_s) if self.restart_backoff_s > 0 else 0.0
            logger.warning(
                f"elastic agent: worker failed (rc={rc}); restarting with "
                f"{slots} slots in {backoff:.1f}s "
                f"({self.restart_count}/{self.max_restarts})")
            if backoff > 0:
                # a crash-looping script must not burn its whole restart
                # budget in milliseconds; also gives the dead world's
                # sockets/fds time to drain before the next rendezvous
                time.sleep(backoff)
