from .elasticity import (compute_elastic_config, ensure_immutable_elastic_config,  # noqa: F401
                         ElasticityConfigError, ElasticityError,
                         ElasticityIncompatibleWorldSize)
