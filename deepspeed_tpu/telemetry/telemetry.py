"""The telemetry facade: one object the engines talk to.

Composes the recorder (trace.py), derived metrics (metrics.py), memory
tracker (memory.py) and stall watchdog (watchdog.py) behind a small hook
API, and fans derived metrics out to *sinks* — ``MonitorMaster``
(TensorBoard/W&B/CSV) is one sink among several; a JSONL sink writes the
same events for offline tooling (``tools/trace_view.py``).

The zero-overhead-when-off contract lives here: a disabled engine holds
:data:`NULL_TELEMETRY`, whose every hook is a constant no-op — no
buffers, no locks, no threads, and (enforced by lint + the Layer-B
``telemetry-off-parity`` audit) nothing injected into traced step code.
Telemetry is HOST-side either way; enabling it must never change a jaxpr.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import log_dist, logger
from . import clock
from .config import TelemetryConfig, telemetry_enabled
from .memory import MemoryTracker
from .metrics import MetricsEngine, peak_flops_per_device
from .trace import (NULL_SPAN, PHASE_CHECKPOINT, PHASE_SERVING, PHASE_STEP,
                    TraceRecorder)
from .watchdog import StallWatchdog


class JsonlMetricsSink:
    """Append derived-metric events to ``metrics.jsonl`` (rank 0)."""

    enabled = True

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def write_events(self, event_list) -> None:
        import json
        with self._lock, open(self.path, "a") as f:
            for tag, value, step in event_list:
                f.write(json.dumps({"tag": tag, "value": float(value),
                                    "step": int(step)}) + "\n")


class Telemetry:

    enabled = True

    def __init__(self, config: Optional[TelemetryConfig] = None,
                 sinks: Optional[List[Any]] = None,
                 rank: int = 0, n_devices: int = 1):
        self.config = config or TelemetryConfig(enabled=True)
        self.rank = rank
        self.flush_every = max(1, self.config.flush_interval or 1)
        self.output_dir = self.config.trace.output_path or "./dstpu_telemetry"
        self.trace = TraceRecorder(max_events=self.config.trace.max_events)
        self.metrics = MetricsEngine(window=self.config.metrics.window)
        self.metrics.peak_flops_total = peak_flops_per_device() * n_devices
        self.memory = MemoryTracker() if self.config.memory.enabled else None
        wd = self.config.watchdog
        self.watchdog = StallWatchdog(
            deadline_factor=wd.deadline_factor,
            min_deadline_s=wd.min_deadline_s, poll_s=wd.poll_s,
            dump_fns=[self._dump_spans], on_stall=self._on_stall,
            escalate_after_s=getattr(wd, "escalate_after_s", 0.0),
            on_escalate=self._on_escalate,
        ) if wd.enabled else None
        # set by the engine (_build_telemetry): the checkpoint-and-exit
        # hard-deadline path (docs/RESILIENCE.md); None → log-only
        self.escalation_handler: Optional[Callable[[int, float], None]] = None
        self.sinks: List[Any] = [s for s in (sinks or [])
                                 if getattr(s, "enabled", True)]
        self._step_span = None
        self._flops_fn: Optional[Callable[[], float]] = None
        self._flops_attempts = 0
        self._closed = False
        # flush-summary subscribers (the tune controller): host-side
        # callbacks fed off the flush fence, never from traced code
        self._subscribers: List[Callable[[int, Dict[str, float]], None]] = []

    # -- flush subscription (dstpu-tune, docs/AUTOTUNING.md) -------------
    def subscribe(self, callback: Callable[[int, Dict[str, float]], None]
                  ) -> Callable[[], None]:
        """Register ``callback(step, summary)`` to run at every flush,
        after the sinks. Returns an unsubscribe callable. Callbacks run
        on the flushing thread and must be cheap; a raising callback is
        logged and kept (parity with the sink contract)."""
        self._subscribers.append(callback)

        def unsubscribe() -> None:
            try:
                self._subscribers.remove(callback)
            except ValueError:
                pass
        return unsubscribe

    # -- spans -----------------------------------------------------------
    def phase(self, name: str, phase: Optional[str] = None,
              step: Optional[int] = None, **args):
        return self.trace.span(name, phase=phase or name, step=step, **args)

    # -- train-step lifecycle -------------------------------------------
    def step_begin(self, step: int) -> None:
        if self._step_span is not None:
            if self._step_span.step == step:  # split fwd/bwd path re-enters
                return
            # a rejected batch / raised step abandoned its span — close it
            # so it neither leaks in the live stacks nor skews this step
            self.trace.end(self._step_span)
        self._step_span = self.trace.span("train_step", phase=PHASE_STEP,
                                          step=step)
        if self.watchdog is not None:
            self.watchdog.step_begin(step)

    def step_end(self, step: int, tokens: int = 0) -> None:
        span = self._step_span
        if span is None:
            return
        self._step_span = None
        self.trace.end(span)
        dur = span.t1 - span.t0
        excess = (self.watchdog.step_end(step, dur)
                  if self.watchdog is not None else 0.0)
        self.metrics.record_step(dur, tokens=tokens, stall_excess_s=excess)

    def checkpoint_span(self, name: str = "checkpoint", **args):
        """Checkpoint phases pause the watchdog (a long save is a pause,
        not a stall) and charge goodput's checkpoint account on exit."""
        tele = self

        class _CkptSpan:
            def __enter__(self):
                if tele.watchdog is not None:
                    tele.watchdog.pause()
                self._span = tele.trace.span(name, phase=PHASE_CHECKPOINT,
                                             **args)
                return self._span

            def __exit__(self, *exc):
                tele.trace.end(self._span)
                tele.metrics.record_checkpoint_pause(
                    self._span.t1 - self._span.t0)

        return _CkptSpan()

    # -- comm records (dist.record_collective feed) ----------------------
    def record_collective(self, op: str, nbytes: int, axes,
                          overlapped: Optional[bool] = None,
                          count: int = 1,
                          wire_bytes: Optional[int] = None) -> None:
        self.trace.comm(op, nbytes, axes, overlapped, count,
                        wire_bytes=wire_bytes)
        self.metrics.record_comm(nbytes, overlapped, count,
                                 wire_bytes=wire_bytes)

    # -- numerics guardian (resilience/guardian.py, ISSUE 13) ------------
    def record_numerics(self, step: int, loss, gnorm) -> None:
        """Per-step loss/gnorm into the anomaly reservoirs — host scalars
        the step fetched anyway; nothing here touches the device."""
        self.metrics.record_numerics(loss, gnorm)

    def record_anomaly(self, step: int, word: int, kinds) -> None:
        """A guardian sentinel fired: trace instant + anomaly counter
        (the watchdog-stall convention — instants mark the autopsy
        timeline, counters feed the flush summary)."""
        self.trace.instant("guardian:anomaly", phase=PHASE_STEP, step=step,
                           word=int(word), kinds=list(kinds))
        self.metrics.record_anomaly(word)

    def record_rollback(self, step: int, tag) -> None:
        """Guardian escalation: the run is rolling back to ``tag``."""
        self.trace.instant("guardian:rollback", phase=PHASE_STEP, step=step,
                           tag=tag)
        self.metrics.record_guardian_rollback()

    # -- out-of-core offload pipeline (ISSUE 15) -------------------------
    def record_offload_phases(self, step: int,
                              phases: Dict[str, float]) -> None:
        """One offload optimizer boundary's phase decomposition
        (h2d_prefetch / bucket_compute / d2h_writeback / nvme_io seconds,
        accumulated host-side — nothing here touches the device). Each
        phase lands as a completed span under the ``offload`` phase track
        plus a summary accumulator (``offload_*_s`` / the derived
        ``offload_stall_frac``)."""
        from .trace import PHASE_OFFLOAD
        for name, dur in phases.items():
            if dur > 0.0:
                self.trace.complete_span(f"offload/{name}", PHASE_OFFLOAD,
                                         dur, step=step)
        self.metrics.record_offload_phases(phases)

    # -- serving ---------------------------------------------------------
    def record_wave(self, kind: str, tokens: int, duration_s: float,
                    queue_depth: int = 0, running: int = 0,
                    occupancy: float = 0.0, admitted: int = 0,
                    queue_wait_s: float = 0.0) -> None:
        """``duration_s`` is EXECUTE time only (compose + dispatch + fetch
        of this wave); ``queue_wait_s`` is the longest submit->schedule
        wait among the ``admitted`` requests this wave first scheduled —
        kept separate so deep queues cannot masquerade as slow forwards."""
        self.trace.instant(f"wave:{kind}", phase=PHASE_SERVING,
                           tokens=tokens, queue_depth=queue_depth,
                           running=running, occupancy=round(occupancy, 4),
                           dur_ms=round(duration_s * 1e3, 3),
                           admitted=admitted,
                           queue_wait_ms=round(queue_wait_s * 1e3, 3))
        self.metrics.wave_latency.record(duration_s)
        if tokens > 0:
            self.metrics.token_latency.record(duration_s / tokens)

    def record_request(self, queue_wait_s: float, ttft_s: float) -> None:
        """Per-request TTFT attribution at first token: total TTFT, the
        queue-wait component, and the execute remainder each land in
        their own reservoir (the serving SLA scoreboard the scheduler's
        admission policy and the bench lines read)."""
        self.metrics.ttft_latency.record(ttft_s)
        self.metrics.queue_wait.record(queue_wait_s)
        self.metrics.ttft_execute.record(max(0.0, ttft_s - queue_wait_s))

    # -- MFU plumbing ----------------------------------------------------
    def set_flops_fn(self, fn: Callable[[], float]) -> None:
        """Lazy model-FLOPs source (the engine's cost-analysis helper) —
        evaluated once, at the first flush, off the hot path."""
        self._flops_fn = fn

    _FLOPS_MAX_ATTEMPTS = 3

    def _resolve_flops(self) -> None:
        if (self.metrics.model_flops_per_step > 0 or self._flops_fn is None
                or self._flops_attempts >= self._FLOPS_MAX_ATTEMPTS):
            return
        self._flops_attempts += 1
        try:
            self.metrics.model_flops_per_step = float(self._flops_fn())
        except Exception as e:  # noqa: BLE001 - MFU is best-effort; a
            # transient failure (compile under memory pressure) retries at
            # the next flushes before giving up for good
            last = self._flops_attempts >= self._FLOPS_MAX_ATTEMPTS
            logger.warning(
                f"telemetry: model-FLOPs resolution failed ({e}); "
                + ("MFU unavailable" if last
                   else f"retrying at the next flush "
                        f"({self._flops_attempts}/{self._FLOPS_MAX_ATTEMPTS})"))

    # -- flush / export --------------------------------------------------
    def flush(self, step: int) -> List:
        """Fence point: re-anchor the clock, sample memory, compute the
        derived metrics, and write them to every sink. Returns the event
        list (also recorded as trace counter tracks)."""
        clock.fence("telemetry-flush")
        self._resolve_flops()
        summary = self.metrics.summary()
        events = [(f"Telemetry/{k}", v, step) for k, v in summary.items()]
        if self.memory is not None:
            sample = self.memory.sample(tag=f"step{step}")
            events += [(f"Telemetry/memory/{k}", float(v), step)
                       for k, v in sample.items() if k != "tag"]
        for tag, value, s in events:
            self.trace.metric(tag, value, step=s)
        for sink in self.sinks:
            try:
                sink.write_events(events)
            except Exception as e:  # noqa: BLE001 - a broken sink must not
                logger.warning(f"telemetry sink {type(sink).__name__} "
                               f"failed: {e}")          # kill the training loop
        for cb in list(self._subscribers):
            try:
                cb(step, summary)
            except Exception as e:  # noqa: BLE001 - subscriber parity with
                logger.warning(f"telemetry subscriber failed: {e}")  # sinks
        return events

    def export(self) -> Dict[str, str]:
        """Write the trace exports; returns {kind: path}."""
        os.makedirs(self.output_dir, exist_ok=True)
        chrome = os.path.join(self.output_dir,
                              f"trace.rank{self.rank}.chrome.json")
        jsonl = os.path.join(self.output_dir, f"trace.rank{self.rank}.jsonl")
        self.trace.export_chrome_trace(chrome)
        self.trace.export_jsonl(jsonl)
        return {"chrome": chrome, "jsonl": jsonl}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        try:
            # final flush so serving-only processes (which never hit the
            # training engine's per-step flush) still land their derived
            # metrics — latency percentiles included — in the exports
            if self.metrics.steps or len(self.metrics.wave_latency):
                self.flush(self.metrics.steps)
            paths = self.export()
            log_dist(f"telemetry: trace exported to {paths['chrome']}",
                     ranks=[0])
        except Exception as e:  # noqa: BLE001 - exit paths must not raise
            logger.warning(f"telemetry export failed: {e}")

    # -- watchdog plumbing ----------------------------------------------
    def _dump_spans(self) -> str:
        lines = []
        for tid, stack in self.trace.active_stacks().items():
            chain = " > ".join(f"{name}({open_s:.1f}s)"
                               for name, open_s in stack)
            lines.append(f"  thread {tid}: {chain}")
        return ("live span stacks:\n" + "\n".join(lines)) if lines \
            else "live span stacks: <none>"

    def _on_stall(self, step: int, elapsed: float) -> None:
        self.trace.instant("stall", phase=PHASE_STEP, step=step,
                           elapsed_s=round(elapsed, 3))

    def _on_escalate(self, step: int, elapsed: float) -> None:
        """Hard-deadline escalation: record the event (the trace is about
        to be exported by the handler's exit path), then hand off to the
        engine's checkpoint-and-exit handler."""
        self.trace.instant("stall_escalation", phase=PHASE_STEP, step=step,
                           elapsed_s=round(elapsed, 3))
        if self.escalation_handler is not None:
            self.escalation_handler(step, elapsed)


class NullTelemetry:
    """The disabled path: every hook is a constant no-op. No state, no
    threads, no syncs — and nothing for traced code to capture."""

    enabled = False
    watchdog = None
    memory = None

    def phase(self, name, phase=None, step=None, **args):
        return NULL_SPAN

    def checkpoint_span(self, name="checkpoint", **args):
        return NULL_SPAN

    def step_begin(self, step):
        pass

    def step_end(self, step, tokens=0):
        pass

    def record_collective(self, op, nbytes, axes, overlapped=None, count=1,
                          wire_bytes=None):
        pass

    def record_wave(self, *a, **k):
        pass

    def record_request(self, *a, **k):
        pass

    def record_numerics(self, *a, **k):
        pass

    def record_anomaly(self, *a, **k):
        pass

    def record_rollback(self, *a, **k):
        pass

    def record_offload_phases(self, *a, **k):
        pass

    def set_flops_fn(self, fn):
        pass

    def subscribe(self, callback):
        return lambda: None

    def flush(self, step):
        return []

    def export(self):
        return {}

    def close(self):
        pass


NULL_TELEMETRY = NullTelemetry()

_GLOBAL: Optional[Telemetry] = None


def get_telemetry():
    """The process-global telemetry (NULL when none configured) — how
    code without an engine handle (comm frontend, inference scheduler)
    reaches the active recorder."""
    return _GLOBAL if _GLOBAL is not None else NULL_TELEMETRY


def set_telemetry(tele: Optional[Telemetry]) -> None:
    global _GLOBAL
    if _GLOBAL is not None and tele is not _GLOBAL:
        _GLOBAL.close()
    _GLOBAL = tele


def reset_telemetry() -> None:
    """Drop the global WITHOUT the close-time export — the test harness's
    between-test cleanup (a closing export would litter the cwd)."""
    global _GLOBAL
    if _GLOBAL is not None:
        if _GLOBAL.watchdog is not None:
            _GLOBAL.watchdog.stop()
        _GLOBAL._closed = True
        _GLOBAL = None


def build_telemetry(config: Optional[TelemetryConfig],
                    sinks: Optional[List[Any]] = None,
                    make_global: bool = True):
    """Engine front door: NULL when disabled (config + DSTPU_TELEMETRY
    env), else a live Telemetry registered as the process global."""
    if not telemetry_enabled(config):
        return NULL_TELEMETRY
    try:
        import jax
        rank, n_dev = jax.process_index(), jax.device_count()
    except Exception:  # pragma: no cover - no backend
        rank, n_dev = 0, 1
    tele = Telemetry(config=config, sinks=sinks if rank == 0 else [],
                     rank=rank, n_devices=n_dev)
    if make_global:
        set_telemetry(tele)
        _register_atexit_once()
    return tele


_ATEXIT_REGISTERED = False


def _register_atexit_once() -> None:
    """One process-wide hook closing whatever the CURRENT global is at
    exit — per-instance registration would pin every Telemetry (and its
    event deque) ever built for the process lifetime."""
    global _ATEXIT_REGISTERED
    if _ATEXIT_REGISTERED:
        return
    _ATEXIT_REGISTERED = True
    import atexit
    atexit.register(lambda: _GLOBAL is not None and _GLOBAL.close())


def maybe_enable_from_env() -> None:
    """Serving entry points call this: DSTPU_TELEMETRY=1 with no engine
    in the process still gets a default recorder."""
    if _GLOBAL is None and telemetry_enabled(None):
        build_telemetry(TelemetryConfig(enabled=True))
