"""Memory telemetry: compiled-HLO analysis + live-buffer watermarks.

Two complementary views, both host-side:

1. **Static** — :func:`compiled_memory_report` asks XLA what a compiled
   entry point *will* use (``Compiled.memory_analysis()``: argument /
   output / temp / alias bytes). This is exact, per-program, and free of
   timing: the right tool for "does this step fit" before a 3B run OOMs
   forty minutes in.
2. **Dynamic** — :meth:`MemoryTracker.sample` sums the process's live
   ``jax.Array`` buffers (per-shard addressable bytes, so replication is
   counted the way HBM pays for it) and, where the runtime exposes it,
   the allocator's ``memory_stats()`` (``bytes_in_use`` /
   ``peak_bytes_in_use``). Sampling walks host-side bookkeeping only — no
   device sync — but it IS O(live arrays), so the telemetry facade calls
   it at fence points (flush/checkpoint boundaries) only, per the
   ``telemetry-hot-path-sync`` contract.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax


def compiled_memory_report(compiled) -> Optional[Dict[str, float]]:
    """Byte sizes from an XLA ``Compiled``'s ``memory_analysis()``;
    None when the backend doesn't expose it. Thin delegate to
    :mod:`deepspeed_tpu.analysis.lowering` — telemetry and the Layer-C
    SPMD auditor share ONE lower-and-inspect path, so the bytes reported
    at runtime are the bytes the lint budgets gate on."""
    from ..analysis.lowering import memory_report
    return memory_report(compiled)


def lower_and_report(jitfn, *abstract_args) -> Optional[Dict[str, float]]:
    """Lower+compile ``jitfn`` on abstract avals and report its memory
    analysis. Compilation is cached by signature, so calling this for a
    shape the step already ran is near-free; a NEW shape pays one compile
    — call it per entry point, not per step. (Delegates to
    ``analysis.lowering.lower_and_report`` — the shared path.)"""
    from ..analysis.lowering import lower_and_report as _lar
    return _lar(jitfn, *abstract_args)


class MemoryTracker:
    """Live-buffer watermark sampling at fence points."""

    def __init__(self):
        self.peak_live_bytes = 0
        self.last_live_bytes = 0
        self.last_allocator: Dict[str, int] = {}
        self.samples = 0

    @staticmethod
    def _live_bytes() -> int:
        total = 0
        for arr in jax.live_arrays():
            shards = getattr(arr, "addressable_shards", None)
            if shards:
                try:
                    total += sum(s.data.nbytes for s in shards)
                    continue
                except Exception:  # deleted/donated mid-walk
                    continue
            total += getattr(arr, "nbytes", 0)
        return total

    @staticmethod
    def _allocator_stats() -> Dict[str, int]:
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            return {}
        if not stats:
            return {}
        return {k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")}

    def sample(self, tag: str = "") -> Dict[str, Any]:
        """Take one watermark sample. Fence-point use only (O(live
        arrays) host walk; never a device sync)."""
        live = self._live_bytes()
        self.samples += 1
        self.last_live_bytes = live
        self.peak_live_bytes = max(self.peak_live_bytes, live)
        self.last_allocator = self._allocator_stats()
        out = {"tag": tag, "live_bytes": live,
               "peak_live_bytes": self.peak_live_bytes}
        out.update(self.last_allocator)
        return out
