"""Stall watchdog: flag a step that blows its rolling deadline BEFORE the
hang becomes a silent loss of a pod-slice.

A background daemon thread polls the currently-open step. The deadline is
``deadline_factor x`` the rolling median step time, floored at
``min_deadline_s`` — and the dog stays silent until at least one step has
COMPLETED, because the very first step carries the whole XLA compile
(routinely minutes at scale) and no deadline is meaningful without a
baseline. On first overrun of a step it dumps, once:

- the live span stacks from the trace recorder (which phase is stuck —
  data loader? checkpoint commit? the dispatch itself?),
- the comms-log tail (the last collectives recorded — a wedged collective
  on a lost host shows up here),

and records the stall so goodput accounting charges the overrun. The
watchdog never touches the device: it reads host timestamps and host
bookkeeping only, so a truly wedged XLA runtime cannot wedge the dog too.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional

from . import clock
from ..utils.logging import logger


class StallWatchdog:

    def __init__(self,
                 deadline_factor: float = 3.0,
                 min_deadline_s: float = 60.0,
                 poll_s: float = 1.0,
                 dump_fns: Optional[List[Callable[[], str]]] = None,
                 on_stall: Optional[Callable[[int, float], None]] = None,
                 escalate_after_s: float = 0.0,
                 on_escalate: Optional[Callable[[int, float], None]] = None):
        self.deadline_factor = float(deadline_factor)
        self.min_deadline_s = float(min_deadline_s)
        self.poll_s = max(0.01, float(poll_s))
        self.dump_fns = list(dump_fns or [])
        self.on_stall = on_stall
        # hard deadline: a step open this long past its start escalates
        # (checkpoint-and-exit, docs/RESILIENCE.md); 0 disables. Like the
        # soft deadline it arms only after a first completed step — the
        # compile-carrying first step has no meaningful budget.
        self.escalate_after_s = float(escalate_after_s)
        self.on_escalate = on_escalate
        self._durations: deque = deque(maxlen=64)
        self._lock = threading.Lock()
        self._cur_step: Optional[int] = None
        self._cur_start = 0.0
        self._fired_step: Optional[int] = None
        self._escalated_step: Optional[int] = None
        self.stall_count = 0
        self.last_stall_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- engine hooks ----------------------------------------------------
    def step_begin(self, step: int) -> None:
        with self._lock:
            self._cur_step = step
            self._cur_start = clock.now()
        self._ensure_thread()

    def step_end(self, step: int, duration_s: float) -> float:
        """Close the step; returns the stall overrun in seconds (0 when
        the step met its deadline) for goodput accounting."""
        with self._lock:
            self._cur_step = None
            deadline = self._deadline_locked()
            self._durations.append(float(duration_s))
        if self._fired_step == step:
            return max(0.0, duration_s - deadline)
        return 0.0

    def pause(self) -> None:
        """Suspend deadline checks (checkpoint pauses are accounted as
        checkpoint time, not stalls)."""
        with self._lock:
            self._cur_step = None

    # -- internals -------------------------------------------------------
    def _deadline_locked(self) -> float:
        if not self._durations:
            return self.min_deadline_s
        vals = sorted(self._durations)
        median = vals[len(vals) // 2]
        return max(self.min_deadline_s, self.deadline_factor * median)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dstpu-telemetry-watchdog", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            fire_stall = fire_escalate = None
            with self._lock:
                step = self._cur_step
                if step is None or not self._durations:
                    # no completed step yet: the first step carries the
                    # whole XLA compile, routinely minutes at scale — a
                    # deadline is only meaningful once a baseline exists
                    continue
                elapsed = clock.now() - self._cur_start
                deadline = self._deadline_locked()
                if self._fired_step != step and elapsed > deadline:
                    self._fired_step = step
                    self.stall_count += 1
                    self.last_stall_step = step
                    fire_stall = (step, elapsed, deadline)
                if (self.escalate_after_s > 0
                        and self._escalated_step != step
                        and elapsed > self.escalate_after_s):
                    self._escalated_step = step
                    fire_escalate = (step, elapsed)
            if fire_stall is not None:
                self._fire(*fire_stall)
            if fire_escalate is not None:
                self._escalate(*fire_escalate)

    def _fire(self, step: int, elapsed: float, deadline: float) -> None:
        lines = [f"STALL: step {step} running {elapsed:.1f}s "
                 f"(deadline {deadline:.1f}s = max({self.min_deadline_s}, "
                 f"{self.deadline_factor} x rolling median))"]
        for fn in self.dump_fns:
            try:
                dump = fn()
            except Exception as e:  # noqa: BLE001 - dump must never raise
                dump = f"<dump failed: {type(e).__name__}: {e}>"
            if dump:
                lines.append(dump)
        logger.error("\n".join(lines))
        if self.on_stall is not None:
            try:
                self.on_stall(step, elapsed)
            except Exception:  # noqa: BLE001
                pass

    def _escalate(self, step: int, elapsed: float) -> None:
        logger.error(
            f"STALL ESCALATION: step {step} running {elapsed:.1f}s, past "
            f"the hard deadline of {self.escalate_after_s:.1f}s — handing "
            "off to the escalation callback (checkpoint-and-exit)")
        if self.on_escalate is not None:
            try:
                self.on_escalate(step, elapsed)
            except Exception as e:  # noqa: BLE001 - the dog must survive
                logger.error(f"stall escalation callback failed: {e}")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        # the escalation path closes telemetry FROM the watchdog thread —
        # joining ourselves would raise and abort the trace export
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5 * self.poll_s)
        self._thread = None
