"""Derived-metrics engine: step percentiles, tokens/sec, MFU, goodput.

Definitions (documented in docs/OBSERVABILITY.md):

- **step time p50/p90/p99** — host wall time per optimizer step over a
  rolling window.
- **tokens/sec** — tokens consumed by the window's steps / window wall
  time.
- **MFU** — ``model_flops_per_step / (step_time * peak_flops_total)``.
  The numerator is the SAME number the flops profiler reports (XLA's
  ``cost_analysis()`` of the compiled micro step × accumulation steps), so
  the two surfaces can never disagree about the model's arithmetic; the
  denominator comes from the per-platform peak table below
  (``DSTPU_PEAK_FLOPS`` overrides, e.g. for a downclocked pod).
- **goodput** — productive fraction of wall time:
  ``productive / (productive + lost)`` where *lost* is stall overrun
  (time beyond the watchdog deadline on flagged steps), checkpoint pauses,
  and any other explicitly-reported non-productive time. A healthy run
  sits near 1.0; goodput diverging from 1.0 while step p50 stays flat
  means the loss is BETWEEN steps, not in them.
- **overlap efficiency** — overlapped / (overlapped + exposed) traced
  collective bytes from ``dist.record_collective`` (see
  docs/ZERO_OVERLAP.md: under XLA the honest unit is bytes by schedule
  class, not per-op wall time).
"""

from __future__ import annotations

import os
from collections import deque
from typing import Dict, List, Optional

# Peak dense bf16/fp16 FLOPs per chip (marketing peaks; MFU is a ratio
# against the roofline, so the convention just has to be stated). Keyed by
# substrings of ``jax.devices()[0].device_kind`` lowercased.
PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),         # also matches "tpu v5 lite"
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 1e12),           # nominal: keeps MFU finite on host-mesh runs
)


def peak_flops_per_device(device_kind: Optional[str] = None) -> float:
    """Per-device peak from the table; ``DSTPU_PEAK_FLOPS`` (per-device,
    in FLOPs) overrides for platforms the table mislabels."""
    env = os.environ.get("DSTPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - no backend
            return 1e12
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return 1e12


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class LatencyHistogram:
    """Bounded sample reservoir for serving latencies (per-token /
    per-wave). Keeps the newest ``cap`` samples — serving percentiles are
    about the current regime, not the whole run."""

    def __init__(self, cap: int = 4096):
        self._samples: deque = deque(maxlen=cap)

    def record(self, seconds: float) -> None:
        self._samples.append(float(seconds))

    def __len__(self) -> int:
        return len(self._samples)

    def percentiles(self, ps=(50, 90, 99)) -> Dict[str, float]:
        vals = sorted(self._samples)
        return {f"p{p}": percentile(vals, p) for p in ps}


class MetricsEngine:

    def __init__(self, window: int = 128):
        self.window = max(2, int(window))
        self._durations: deque = deque(maxlen=self.window)
        self._tokens: deque = deque(maxlen=self.window)
        self.steps = 0
        self.total_tokens = 0
        # goodput accounting (seconds)
        self.productive_s = 0.0
        self.stall_lost_s = 0.0
        self.checkpoint_lost_s = 0.0
        self.stalled_steps = 0
        # comm schedule-class byte totals (trace-time records)
        self.comm_overlapped_bytes = 0
        self.comm_exposed_bytes = 0
        # transport accounting: logical vs wire bytes across ALL records
        # (untagged included) — the quantized-transport scoreboard
        self.comm_logical_bytes = 0
        self.comm_wire_bytes = 0
        # model arithmetic for MFU — set once by the engine from the flops
        # profiler's cost-analysis machinery
        self.model_flops_per_step: float = 0.0
        self.peak_flops_total: float = 0.0
        # serving
        self.token_latency = LatencyHistogram()
        self.wave_latency = LatencyHistogram()
        # per-REQUEST serving reservoirs (ISSUE 6): TTFT decomposed into
        # queue wait (submit -> first scheduled) and execute (first
        # scheduled -> first token), so deep queues attribute latency to
        # admission rather than to the forward pass
        self.ttft_latency = LatencyHistogram()
        self.queue_wait = LatencyHistogram()
        self.ttft_execute = LatencyHistogram()
        # numerics anomaly reservoirs (dstpu-guardian, ISSUE 13): rolling
        # loss/gnorm samples — the observability twin of the guardian's
        # own spike-threshold stats — plus escalation counters
        self.loss_values = LatencyHistogram()
        self.gnorm_values = LatencyHistogram()
        self.anomaly_steps = 0
        self.anomaly_word_union = 0
        self.guardian_rollbacks = 0
        # out-of-core offload phase accounting (ISSUE 15): cumulative
        # seconds per pipeline phase — the decomposition of the old
        # scalar offload_stall_frac (docs/OBSERVABILITY.md)
        self.offload_phase_s: Dict[str, float] = {}

    # -- feeding ---------------------------------------------------------
    def record_step(self, duration_s: float, tokens: int = 0,
                    stall_excess_s: float = 0.0) -> None:
        self.steps += 1
        self._durations.append(float(duration_s))
        self._tokens.append(int(tokens))
        self.total_tokens += int(tokens)
        self.productive_s += max(0.0, duration_s - stall_excess_s)
        if stall_excess_s > 0.0:
            self.stall_lost_s += stall_excess_s
            self.stalled_steps += 1

    def record_checkpoint_pause(self, seconds: float) -> None:
        self.checkpoint_lost_s += max(0.0, float(seconds))

    def record_numerics(self, loss: Optional[float],
                        gnorm: Optional[float]) -> None:
        """Per-step loss/gnorm samples into the anomaly reservoirs (only
        finite values — the reservoirs describe the healthy regime the
        spike thresholds are judged against)."""
        import math
        if loss is not None and math.isfinite(loss):
            self.loss_values.record(abs(float(loss)))
        if gnorm is not None and math.isfinite(gnorm) and gnorm > 0.0:
            self.gnorm_values.record(float(gnorm))

    def record_anomaly(self, word: int) -> None:
        self.anomaly_steps += 1
        self.anomaly_word_union |= int(word)

    def record_guardian_rollback(self) -> None:
        self.guardian_rollbacks += 1

    def record_offload_phases(self, phases: Dict[str, float]) -> None:
        """Per-step offload pipeline phase seconds (h2d_prefetch /
        bucket_compute / d2h_writeback / nvme_io)."""
        for k, v in phases.items():
            self.offload_phase_s[k] = \
                self.offload_phase_s.get(k, 0.0) + max(0.0, float(v))

    def record_comm(self, nbytes: int, overlapped: Optional[bool],
                    count: int = 1,
                    wire_bytes: Optional[int] = None) -> None:
        if overlapped is True:
            self.comm_overlapped_bytes += int(nbytes) * int(count)
        elif overlapped is False:
            self.comm_exposed_bytes += int(nbytes) * int(count)
        self.comm_logical_bytes += int(nbytes) * int(count)
        self.comm_wire_bytes += int(nbytes if wire_bytes is None
                                    else wire_bytes) * int(count)

    def wire_ratio(self) -> Optional[float]:
        """wire / logical collective bytes (1.0 = full width everywhere;
        the transport planner's byte win, docs/COLLECTIVES.md)."""
        if self.comm_logical_bytes == 0:
            return None
        return self.comm_wire_bytes / self.comm_logical_bytes

    # -- derived ---------------------------------------------------------
    def step_percentiles(self, ps=(50, 90, 99)) -> Dict[str, float]:
        vals = sorted(self._durations)
        return {f"p{p}": percentile(vals, p) for p in ps}

    def mean_step_s(self) -> float:
        if not self._durations:
            return 0.0
        return sum(self._durations) / len(self._durations)

    def tokens_per_sec(self) -> float:
        wall = sum(self._durations)
        return (sum(self._tokens) / wall) if wall > 0 else 0.0

    def mfu(self) -> float:
        step = self.mean_step_s()
        if step <= 0 or self.model_flops_per_step <= 0 \
                or self.peak_flops_total <= 0:
            return 0.0
        return self.model_flops_per_step / (step * self.peak_flops_total)

    def feasibility_cross_check(self, entry: str,
                                plans_dir: Optional[str] = None,
                                rel_tol: float = 0.5) -> Optional[Dict]:
        """Cross-check the MFU numerator against Layer E's committed
        static prediction (``tools/feasibility/<entry>.json``,
        ``dstpu plan --update-artifacts``).

        ``model_flops_per_step`` is what the engine measured through the
        flops profiler; ``predicted_step_flops`` is what the feasibility
        oracle derived from the compiled HLO without running a step. A
        ratio drifting outside ``[1 - rel_tol, 1 / (1 - rel_tol)]`` means
        the committed verdict no longer describes the program that is
        actually running (stale artifact, diverged config) — the same
        drift the tier-1 freshness gate catches at commit time, caught
        here at run time. Advisory only: never called on the hot path,
        returns None when either side is missing."""
        if self.model_flops_per_step <= 0:
            return None
        from ..analysis.feasibility import (default_plans_dir,
                                            load_verdict_artifact)
        artifact = load_verdict_artifact(plans_dir or default_plans_dir(),
                                         entry)
        if artifact is None:
            return None
        predicted = float(artifact.get("predicted_step_flops") or 0.0)
        if predicted <= 0.0:
            return None
        ratio = self.model_flops_per_step / predicted
        lo = max(0.0, 1.0 - rel_tol)
        hi = 1.0 / lo if lo > 0 else float("inf")
        return {"entry": entry,
                "predicted_step_flops": predicted,
                "model_flops_per_step": self.model_flops_per_step,
                "ratio": ratio,
                "consistent": lo <= ratio <= hi}

    def goodput(self) -> float:
        lost = self.stall_lost_s + self.checkpoint_lost_s
        total = self.productive_s + lost
        return (self.productive_s / total) if total > 0 else 1.0

    def tuning_objective(self) -> float:
        """The autotuner's composite score: ``mfu() * goodput()`` —
        hardware efficiency discounted by the fraction of wall time the
        run actually trained (docs/AUTOTUNING.md). 0.0 until MFU is
        resolvable (no model-FLOPs source, or no steps yet), so a
        candidate that never produced a measurable step never wins."""
        return self.mfu() * self.goodput()

    def overlap_efficiency(self) -> Optional[float]:
        total = self.comm_overlapped_bytes + self.comm_exposed_bytes
        if total == 0:
            return None
        return self.comm_overlapped_bytes / total

    def summary(self) -> Dict[str, float]:
        out = {
            "steps": float(self.steps),
            "step_time_mean_s": self.mean_step_s(),
            "tokens_per_sec": self.tokens_per_sec(),
            "goodput": self.goodput(),
            # always present (0.0 while MFU is unresolved) — the
            # controller and trial runner key on it unconditionally
            "tuning_objective": self.tuning_objective(),
            "stalled_steps": float(self.stalled_steps),
        }
        out.update({f"step_time_{k}_s": v
                    for k, v in self.step_percentiles().items()})
        if self.model_flops_per_step > 0:
            out["mfu"] = self.mfu()
            out["model_flops_per_step"] = self.model_flops_per_step
        ov = self.overlap_efficiency()
        if ov is not None:
            out["comm_overlap_efficiency"] = ov
        wr = self.wire_ratio()
        if wr is not None:
            out["comm_wire_ratio"] = wr
            out["comm_wire_bytes"] = float(self.comm_wire_bytes)
            out["comm_logical_bytes"] = float(self.comm_logical_bytes)
        if len(self.token_latency):
            out.update({f"token_latency_{k}_s": v for k, v in
                        self.token_latency.percentiles().items()})
        if len(self.ttft_latency):
            out.update({f"ttft_{k}_s": v for k, v in
                        self.ttft_latency.percentiles().items()})
            out.update({f"queue_wait_{k}_s": v for k, v in
                        self.queue_wait.percentiles().items()})
        if self.offload_phase_s:
            # the stall-decomposition keys (ISSUE 15): per-phase seconds
            # plus the blocked fraction of the offload boundary — what
            # the double-buffered pipeline exists to shrink
            for k, v in self.offload_phase_s.items():
                out[f"offload_{k}_s"] = v
            compute = self.offload_phase_s.get("bucket_compute", 0.0)
            blocked = sum(v for k, v in self.offload_phase_s.items()
                          if k != "bucket_compute")
            if compute + blocked > 0:
                out["offload_stall_frac"] = blocked / (compute + blocked)
        if self.anomaly_steps or self.guardian_rollbacks:
            out["anomaly_steps"] = float(self.anomaly_steps)
            out["guardian_rollbacks"] = float(self.guardian_rollbacks)
        if len(self.gnorm_values):
            out.update({f"gnorm_{k}": v for k, v in
                        self.gnorm_values.percentiles().items()})
        if len(self.loss_values):
            out.update({f"loss_{k}": v for k, v in
                        self.loss_values.percentiles().items()})
        return out
