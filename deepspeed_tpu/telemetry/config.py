"""Telemetry config block.

One ``telemetry`` JSON block gates the whole subsystem (see
``docs/OBSERVABILITY.md``). The hard contract: ``enabled: false`` (the
default) injects **nothing** — no host callbacks, no device syncs, no
allocations on the step path; the engine holds a ``NullTelemetry`` whose
every hook is a no-op. ``DSTPU_TELEMETRY=0|1`` overrides the config either
way, so a hung production run can be re-launched with tracing on (or a
noisy one silenced) without editing configs.
"""

from __future__ import annotations

import os
from typing import Optional

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class TraceConfig(DeepSpeedConfigModel):
    enabled: bool = True
    # directory for trace/metric exports; empty → ./dstpu_telemetry
    output_path: str = ""
    # bounded span buffer — the recorder drops the oldest events past this
    # (and counts the drops) instead of growing without bound in a long run
    max_events: int = 100_000


class MetricsConfig(DeepSpeedConfigModel):
    # rolling window (steps) for percentiles / MFU / goodput
    window: int = 128


class MemoryConfig(DeepSpeedConfigModel):
    enabled: bool = True


class WatchdogConfig(DeepSpeedConfigModel):
    enabled: bool = True
    # a step is stalled when it exceeds deadline_factor x rolling median
    # step time (never less than min_deadline_s — warmup/compile steps are
    # legitimately slow)
    deadline_factor: float = 3.0
    min_deadline_s: float = 60.0
    poll_s: float = 1.0
    # HARD deadline (seconds) past which a stalled step escalates:
    # checkpoint-and-exit so a supervising elastic agent restarts the
    # world instead of a hung job burning its allocation (see
    # docs/RESILIENCE.md). 0 (the default) disables escalation. Like the
    # soft deadline, armed only once a first step has completed.
    escalate_after_s: float = 0.0


class TelemetryConfig(DeepSpeedConfigModel):
    enabled: bool = False
    # flush derived metrics to the sinks every N optimizer steps
    # (0 → follow the engine's steps_per_print)
    flush_interval: int = 0
    trace: TraceConfig = Field(default_factory=TraceConfig)
    metrics: MetricsConfig = Field(default_factory=MetricsConfig)
    memory: MemoryConfig = Field(default_factory=MemoryConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)


def telemetry_enabled(config: Optional[TelemetryConfig]) -> bool:
    """Resolve the on/off gate: DSTPU_TELEMETRY env wins over the config."""
    env = os.environ.get("DSTPU_TELEMETRY", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    return bool(config is not None and config.enabled)
