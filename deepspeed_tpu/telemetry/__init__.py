"""dstpu-telemetry: unified runtime telemetry.

One subsystem replacing four disconnected fragments (utils/timer,
utils/comms_logging, profiling/flops_profiler, monitor) with a coherent
observability layer: span/trace recording (trace.py), derived metrics —
step percentiles, tokens/sec, MFU, goodput, overlap efficiency
(metrics.py), memory watermarks + compiled-HLO analysis (memory.py), and
a stall watchdog (watchdog.py), behind the facade in telemetry.py.

Hard contract: **zero overhead when off** — the disabled path is
:data:`NULL_TELEMETRY` (constant no-ops) and nothing is ever injected
into traced code (no host callbacks, no syncs in span hooks); enforced by
the ``telemetry-hot-path-sync`` lint rule and the ``telemetry-off-parity``
Layer-B audit. See docs/OBSERVABILITY.md.
"""

from .config import TelemetryConfig, telemetry_enabled  # noqa: F401
from .telemetry import (NULL_TELEMETRY, JsonlMetricsSink, NullTelemetry,  # noqa: F401
                        Telemetry, build_telemetry, get_telemetry,
                        maybe_enable_from_env, reset_telemetry, set_telemetry)
from .trace import (PHASE_BWD, PHASE_CHECKPOINT, PHASE_DATA,  # noqa: F401
                    PHASE_FWD, PHASE_GATHER, PHASE_OPTIMIZER, PHASE_OTHER,
                    PHASE_SCATTER, PHASE_SERVING, PHASE_STEP, TraceRecorder)
