"""The telemetry clock: host timestamps plus *fence-point* sampling.

Under XLA every dispatch is asynchronous; a host timestamp taken mid-step
measures dispatch, not compute. The old timers resolved this by calling
``jax.effects_barrier()`` on every start/stop — a device sync **per phase
per step**, serializing the very pipeline the schedules exist to fill.

The telemetry contract inverts that: the hot path only ever calls
:func:`now` (a ``perf_counter`` read), and device synchronization is
confined to :func:`fence` — called at *declared* fence points (metric
flushes, report boundaries, checkpoint edges), never inside span hooks or
per-step code. Because the XLA dispatch queue backpressures, host
timestamps drift-bounded by at most one queue depth between fences; the
fence re-anchors them. The ``telemetry-hot-path-sync`` lint rule enforces
that this module's :func:`fence` stays the only sanctioned sync.
"""

from __future__ import annotations

import time

# observability of the observability: how many fences ran and where the
# last one came from — a fence count growing per-step means somebody is
# syncing on the hot path.
_FENCE_COUNT = 0
_LAST_FENCE_REASON = ""


def now() -> float:
    """Monotonic host timestamp in seconds. Never syncs."""
    return time.perf_counter()


def fence(reason: str) -> float:
    """Drain outstanding device work, then return :func:`now`.

    The ONLY sanctioned device sync in the telemetry subsystem. Call it at
    fence points (flush/report/checkpoint boundaries) to re-anchor host
    timestamps to device completion; never per phase or per step.
    """
    global _FENCE_COUNT, _LAST_FENCE_REASON
    try:
        import jax
        jax.effects_barrier()
    except Exception:  # pragma: no cover - jax not importable / no backend
        pass
    _FENCE_COUNT += 1
    _LAST_FENCE_REASON = reason
    return now()


def fence_count() -> int:
    return _FENCE_COUNT


def last_fence_reason() -> str:
    return _LAST_FENCE_REASON
