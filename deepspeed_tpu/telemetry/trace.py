"""Span/trace recorder: host-side phase spans + comm/metric events.

The step is decomposed into the phases a TPU training loop actually has
(data, gather, fwd, bwd, scatter, optimizer, checkpoint — plus serving
phases for the inference engine). Spans are HOST-side intervals around
dispatches: they measure what the host observes (dispatch + any
backpressure), which is the honest measurement under XLA's async runtime —
device-internal attribution belongs to the XLA profiler, and collective
attribution comes from the comm records (:meth:`TraceRecorder.comm`) fed
by ``dist.record_collective`` at trace time.

Exports: Chrome-trace JSON (``chrome://tracing`` / Perfetto — spans as
``X`` duration events, comm records as instant events, metrics as counter
tracks) and JSONL (one record per line; ``tools/trace_view.py``
summarizes it).

Thread safety: spans may begin/end on any thread (async checkpoint writes
record their spans from the worker); the recorder keeps a per-thread span
stack under one lock. The watchdog reads a *snapshot* of the live stacks
when it fires, so a stalled step dumps exactly which phase it is stuck in.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import clock

# -- canonical phases --------------------------------------------------------
PHASE_DATA = "data"              # host batch pipeline (validate/curriculum/H2D)
PHASE_GATHER = "gather"          # param all-gather (comm records)
PHASE_FWD = "fwd"                # forward/micro-step dispatch
PHASE_BWD = "bwd"                # backward boundary
PHASE_SCATTER = "scatter"        # grad reduce-scatter/all-reduce (comm records)
PHASE_STEP = "step"              # fused train-step dispatch
PHASE_OPTIMIZER = "optimizer"    # apply/optimizer dispatch
PHASE_CHECKPOINT = "checkpoint"  # save/load, incl. async write-behind
PHASE_SERVING = "serving"        # inference wave/dispatch
PHASE_OFFLOAD = "offload"        # out-of-core optimizer step pipeline
PHASE_OTHER = "other"

# collective op -> phase attribution for comm records
_COMM_PHASE = {
    "all_gather": PHASE_GATHER,
    "broadcast": PHASE_GATHER,
    "reduce_scatter": PHASE_SCATTER,
    "all_reduce": PHASE_SCATTER,
    "all_to_all": PHASE_SCATTER,
}


class Span:
    """One open interval. Closed via the context-manager protocol or
    :meth:`TraceRecorder.end`."""

    __slots__ = ("name", "phase", "t0", "t1", "step", "args", "_rec", "_tid")

    def __init__(self, rec: "TraceRecorder", name: str, phase: str,
                 step: Optional[int], args: Optional[Dict[str, Any]]):
        self._rec = rec
        self._tid = threading.get_ident()
        self.name = name
        self.phase = phase
        self.step = step
        self.args = args
        self.t0 = clock.now()
        self.t1 = 0.0

    @property
    def duration(self) -> float:
        return (self.t1 or clock.now()) - self.t0

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self._rec.end(self)


class _NullSpan:
    """Reusable zero-work span for the disabled path."""

    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class TraceRecorder:

    def __init__(self, max_events: int = 100_000):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(max_events, 1))
        self.dropped = 0
        self._epoch = clock.now()
        # live span stacks by thread id — the watchdog's dump source
        self._active: Dict[int, List[Span]] = {}

    # -- recording -------------------------------------------------------
    def span(self, name: str, phase: str = PHASE_OTHER,
             step: Optional[int] = None, **args) -> Span:
        s = Span(self, name, phase, step, args or None)
        with self._lock:
            self._active.setdefault(s._tid, []).append(s)
        return s

    def end(self, span: Span) -> None:
        span.t1 = clock.now()
        with self._lock:
            stack = self._active.get(span._tid, [])
            if span in stack:
                stack.remove(span)
            if not stack:
                self._active.pop(span._tid, None)
            self._push({
                "kind": "span", "name": span.name, "phase": span.phase,
                "ts": span.t0 - self._epoch, "dur": span.t1 - span.t0,
                "step": span.step, "tid": span._tid,
                **({"args": span.args} if span.args else {}),
            })

    def complete_span(self, name: str, phase: str, dur: float,
                      step: Optional[int] = None, **args) -> None:
        """Record an already-measured interval as a span (duration events
        accumulated across a step — the offload pipeline's per-phase
        seconds land here post-hoc rather than as hundreds of per-bucket
        live spans). ``ts`` is backdated so the span ends 'now'."""
        t = clock.now()
        with self._lock:
            self._push({
                "kind": "span", "name": name, "phase": phase,
                "ts": max(0.0, t - self._epoch - dur), "dur": float(dur),
                "step": step, "tid": threading.get_ident(),
                **({"args": args} if args else {}),
            })

    def instant(self, name: str, phase: str = PHASE_OTHER,
                step: Optional[int] = None, **args) -> None:
        with self._lock:
            self._push({"kind": "instant", "name": name, "phase": phase,
                        "ts": clock.now() - self._epoch, "step": step,
                        **({"args": args} if args else {})})

    def comm(self, op: str, nbytes: int, axes, overlapped: Optional[bool],
             count: int = 1, wire_bytes: Optional[int] = None) -> None:
        """One ``record_collective`` record (trace-time: sizes/schedule
        class, not wall time — see utils/comms_logging.py). ``wire``
        carries the on-link bytes when the transport plan narrows the
        width (docs/COLLECTIVES.md)."""
        with self._lock:
            self._push({"kind": "comm", "op": op,
                        "phase": _COMM_PHASE.get(op, PHASE_OTHER),
                        "bytes": int(nbytes),
                        "wire": int(nbytes if wire_bytes is None
                                    else wire_bytes),
                        "axes": str(axes),
                        "overlapped": overlapped, "count": int(count),
                        "ts": clock.now() - self._epoch})

    def metric(self, name: str, value: float,
               step: Optional[int] = None) -> None:
        with self._lock:
            self._push({"kind": "metric", "name": name, "value": float(value),
                        "step": step, "ts": clock.now() - self._epoch})

    def _push(self, rec: Dict[str, Any]) -> None:
        if len(self._events) == self._events.maxlen:
            self.dropped += 1
        self._events.append(rec)

    # -- introspection ---------------------------------------------------
    def active_stacks(self) -> Dict[int, List[Tuple[str, float]]]:
        """Snapshot of live spans: {thread_id: [(name, open-for-seconds)]}
        — what the watchdog dumps when a step blows its deadline."""
        t = clock.now()
        with self._lock:
            return {tid: [(s.name, t - s.t0) for s in stack]
                    for tid, stack in self._active.items() if stack}

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # -- export ----------------------------------------------------------
    def export_jsonl(self, path: str) -> int:
        """One record per line; returns the record count."""
        events = self.events()
        with open(path, "w") as f:
            for rec in events:
                f.write(json.dumps(rec) + "\n")
        return len(events)

    def export_chrome_trace(self, path: str, pid: int = 0) -> int:
        """Chrome-trace/Perfetto JSON (``{"traceEvents": [...]}``):
        spans → ``X`` complete events, instants/comm → ``i`` instants,
        metrics → ``C`` counter tracks. Timestamps in microseconds."""
        out = []
        for rec in self.events():
            base = {"pid": pid, "ts": rec["ts"] * 1e6}
            if rec["kind"] == "span":
                out.append({**base, "ph": "X", "name": rec["name"],
                            "cat": rec["phase"], "dur": rec["dur"] * 1e6,
                            "tid": rec["tid"] % (1 << 31),
                            "args": {**rec.get("args", {}),
                                     "step": rec.get("step")}})
            elif rec["kind"] == "instant":
                out.append({**base, "ph": "i", "s": "t", "tid": 0,
                            "name": rec["name"], "cat": rec["phase"],
                            "args": rec.get("args", {})})
            elif rec["kind"] == "comm":
                out.append({**base, "ph": "i", "s": "t", "tid": 0,
                            "name": f"comm:{rec['op']}", "cat": rec["phase"],
                            "args": {"bytes": rec["bytes"],
                                     "axes": rec["axes"],
                                     "overlapped": rec["overlapped"],
                                     "count": rec["count"]}})
            elif rec["kind"] == "metric":
                out.append({**base, "ph": "C", "tid": 0, "name": rec["name"],
                            "args": {"value": rec["value"]}})
        with open(path, "w") as f:
            json.dump({"traceEvents": out,
                       "displayTimeUnit": "ms",
                       "otherData": {"dropped_events": self.dropped}}, f)
        return len(out)
