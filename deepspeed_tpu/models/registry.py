"""Model-architecture registry.

Counterpart of the reference's per-architecture model implementations and
their registration (``inference/v2/model_implementations/*`` registered via
``inference/v2/engine_factory.py``, and the kernel-injection policy map in
``module_inject/replace_policy.py``): one table mapping an HF
``model_type`` to the pair of functions that adapt it onto the shared
:class:`~deepspeed_tpu.models.transformer.TransformerLM` —

- ``config_fn(hf_config_dict) -> kwargs for TransformerConfig``
- ``params_fn(cfg, state_dict) -> TransformerLM param pytree``

``runtime/state_dict_factory.py`` registers the built-in sixteen
(gpt2/llama/mistral/mixtral/internlm/qwen2/opt/phi/falcon/bloom/gpt_neo/
gpt_neox/gptj and the bert/roberta/distilbert encoders) at import; user code can register
additional families without touching the loader.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict


@dataclasses.dataclass(frozen=True)
class ArchitectureSpec:
    model_type: str
    config_fn: Callable[[Dict[str, Any]], Dict[str, Any]]
    params_fn: Callable[[Any, Dict[str, Any]], Dict[str, Any]]


_ARCHITECTURES: Dict[str, ArchitectureSpec] = {}


def register_architecture(model_type: str,
                          config_fn: Callable,
                          params_fn: Callable) -> ArchitectureSpec:
    spec = ArchitectureSpec(model_type, config_fn, params_fn)
    _ARCHITECTURES[model_type] = spec
    return spec


def get_architecture(model_type: str) -> ArchitectureSpec:
    # the built-ins register when the loader module imports
    from ..runtime import state_dict_factory  # noqa: F401
    if model_type not in _ARCHITECTURES:
        raise ValueError(f"unsupported model_type {model_type!r} "
                         f"(supported: {supported_architectures()})")
    return _ARCHITECTURES[model_type]


def supported_architectures() -> list:
    from ..runtime import state_dict_factory  # noqa: F401
    return sorted(_ARCHITECTURES)
