from .transformer import MoEConfig, TransformerConfig, TransformerLM  # noqa: F401
from .gpt2 import gpt2_config, gpt2_model  # noqa: F401
from .llama import llama_config, llama_model  # noqa: F401
from .mixtral import mixtral_config, mixtral_model  # noqa: F401
from .opt_phi_falcon import (falcon_config, falcon_model, opt_config,  # noqa: F401
                             opt_model, phi_config, phi_model)
from .bloom_neox_gptj import (bloom_config, bloom_model, gpt_neo_config,  # noqa: F401
                              gpt_neo_model, gpt_neox_config, gpt_neox_model,
                              gptj_config, gptj_model)
from .bert import (bert_config, bert_model, roberta_config,  # noqa: F401
                   roberta_model)
