from .transformer import MoEConfig, TransformerConfig, TransformerLM  # noqa: F401
from .gpt2 import gpt2_config, gpt2_model  # noqa: F401
from .llama import llama_config, llama_model  # noqa: F401
from .mixtral import mixtral_config, mixtral_model  # noqa: F401
