"""Llama-2 / Mistral presets (reference: inference/v2/model_implementations/
llama_v2, mistral)."""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig, TransformerLM

_PRESETS = {
    "llama2-tiny": dict(num_layers=2, num_heads=4, num_kv_heads=2, hidden_size=128,
                        intermediate_size=352, max_seq_len=256, vocab_size=1024),
    # TinyLlama-1.1B: the largest published llama-family model whose full
    # AdamW train state fits one 16 GB chip (bf16 params/grads + fp32
    # master + bf16 moments = ~13.2 GiB) — the full-depth training bench
    "tinyllama-1.1b": dict(num_layers=22, num_heads=32, num_kv_heads=4,
                           hidden_size=2048, intermediate_size=5632,
                           max_seq_len=2048),
    # OpenLLaMA-3B: largest full-depth llama whose params+grads fit one
    # chip (13.3 GiB bf16); training it needs the host-offloaded optimizer
    "open-llama-3b": dict(num_layers=26, num_heads=32, hidden_size=3200,
                          intermediate_size=8640, max_seq_len=2048),
    "llama2-7b": dict(num_layers=32, num_heads=32, hidden_size=4096,
                      intermediate_size=11008, max_seq_len=4096),
    "llama2-13b": dict(num_layers=40, num_heads=40, hidden_size=5120,
                       intermediate_size=13824, max_seq_len=4096),
    "llama2-70b": dict(num_layers=80, num_heads=64, num_kv_heads=8, hidden_size=8192,
                       intermediate_size=28672, max_seq_len=4096),
    "mistral-7b": dict(num_layers=32, num_heads=32, num_kv_heads=8, hidden_size=4096,
                       intermediate_size=14336, max_seq_len=8192, vocab_size=32000),
}


def llama_config(preset: str = "llama2-7b", dtype=jnp.bfloat16, **overrides) -> TransformerConfig:
    base = dict(
        vocab_size=32000,
        activation="silu_gated",
        norm="rmsnorm",
        position="rope",
        tie_embeddings=False,
        dtype=dtype,
    )
    base.update(_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def llama_model(preset: str = "llama2-7b", **overrides) -> TransformerLM:
    return TransformerLM(llama_config(preset, **overrides))
