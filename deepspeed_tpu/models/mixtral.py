"""Mixtral presets (reference: inference/v2/model_implementations/mixtral)."""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import MoEConfig, TransformerConfig, TransformerLM

_PRESETS = {
    "mixtral-tiny": dict(num_layers=2, num_heads=4, num_kv_heads=2, hidden_size=128,
                         intermediate_size=256, max_seq_len=256, vocab_size=1024,
                         moe=MoEConfig(num_experts=4, top_k=2)),
    "mixtral-8x7b": dict(num_layers=32, num_heads=32, num_kv_heads=8, hidden_size=4096,
                         intermediate_size=14336, max_seq_len=8192, vocab_size=32000,
                         moe=MoEConfig(num_experts=8, top_k=2)),
}


def mixtral_config(preset: str = "mixtral-8x7b", dtype=jnp.bfloat16, **overrides) -> TransformerConfig:
    base = dict(
        vocab_size=32000,
        activation="silu_gated",
        norm="rmsnorm",
        position="rope",
        tie_embeddings=False,
        dtype=dtype,
    )
    base.update(_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def mixtral_model(preset: str = "mixtral-8x7b", **overrides) -> TransformerLM:
    return TransformerLM(mixtral_config(preset, **overrides))
