"""Task heads over the shared encoder body.

Counterpart of the reference's HF-pipeline coverage: its kernel-injection
inference tests drive bert/roberta through fill-mask, text-classification,
token-classification, and question-answering pipelines
(``tests/unit/inference/test_inference.py:62`` task×model matrix; the
injected ``BertLayerPolicy`` accelerates whatever head the HF model
carries). Here the heads are explicit modules over ``TransformerLM``'s
``return_hidden`` output, loading the matching ``*For*`` HF checkpoints.

Head shapes follow the HF architectures exactly:
- bert sequence classification: pooler (dense→tanh on [CLS]) → classifier
- roberta sequence classification: classifier.dense→tanh→out_proj on [CLS]
- distilbert sequence classification: pre_classifier→relu → classifier
- token classification: per-token classifier (all archs)
- question answering: per-token ``qa_outputs`` → (start, end) logits
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import layers as nn
from .transformer import Params, TransformerLM, masked_cross_entropy

TASKS = ("sequence_classification", "token_classification",
         "question_answering")


class EncoderTaskModel:
    """An encoder body + one task head.

    ``head_params`` layouts:
    - sequence_classification: optional ``pooler`` (bert) or ``dense``
      (roberta two-layer head), then ``classifier``
    - token_classification: ``classifier``
    - question_answering: ``qa_outputs`` (out_features=2)
    """

    def __init__(self, lm: TransformerLM, task: str, num_labels: int = 2,
                 head_style: str = "bert"):
        if task not in TASKS:
            raise ValueError(f"unknown task {task!r} (one of {TASKS})")
        if lm.config.causal:
            raise ValueError("task heads expect a bidirectional encoder body")
        self.lm = lm
        self.config = lm.config
        self.task = task
        self.num_labels = 2 if task == "question_answering" else num_labels
        self.head_style = head_style
        H = lm.config.hidden_size
        self._mid = nn.Linear(H, H)        # pooler / dense / pre_classifier
        self._cls = nn.Linear(H, self.num_labels)

    # -- params --------------------------------------------------------------
    def init(self, rng: jax.Array, dtype=jnp.float32) -> Params:
        body = self.lm.init(rng, dtype)
        r = jax.random.fold_in(rng, 11)
        head: Params = {"classifier": self._cls.init(r, dtype)}
        if self.task == "sequence_classification":
            head["mid"] = self._mid.init(jax.random.fold_in(r, 1), dtype)
        body["head"] = head
        return body

    def specs(self) -> Params:
        specs = self.lm.specs()
        head = {"classifier": self._cls.specs()}
        if self.task == "sequence_classification":
            head["mid"] = self._mid.specs()
        specs["head"] = head
        return specs

    # -- forward -------------------------------------------------------------
    def apply(self, params: Params, input_ids: jax.Array,
              token_type_ids: Optional[jax.Array] = None,
              attention_mask: Optional[jax.Array] = None) -> jax.Array:
        """sequence_classification -> [B, num_labels];
        token_classification -> [B, S, num_labels];
        question_answering -> (start [B, S], end [B, S])."""
        hidden, _ = self.lm.apply(params, input_ids,
                                  token_type_ids=token_type_ids,
                                  attention_mask=attention_mask,
                                  return_hidden=True)
        head = params["head"]
        if self.task == "sequence_classification":
            x = hidden[:, 0]                     # [CLS]
            x = self._mid(head["mid"], x)
            # bert's pooler and roberta's classifier.dense both tanh;
            # distilbert's pre_classifier uses relu
            x = jax.nn.relu(x) if self.head_style == "distilbert" else jnp.tanh(x)
            return self._cls(head["classifier"], x).astype(jnp.float32)
        logits = self._cls(head["classifier"], hidden).astype(jnp.float32)
        if self.task == "question_answering":
            return logits[..., 0], logits[..., 1]
        return logits

    def loss(self, params: Params, batch: Dict[str, jax.Array]) -> jax.Array:
        """Cross-entropy per task; QA averages start+end position losses
        with HF's ignore convention (positions clamped to [0, S]; S =
        ignored — truncated/impossible answer spans contribute no loss)."""
        out = self.apply(params, batch["input_ids"],
                         token_type_ids=batch.get("token_type_ids"),
                         attention_mask=batch.get("attention_mask"))
        if self.task == "question_answering":
            start, end = out
            S = start.shape[-1]

            def qa_labels(pos):
                clamped = jnp.clip(pos, 0, S)
                return jnp.where(clamped == S, -100, clamped)

            return 0.5 * (masked_cross_entropy(start, qa_labels(batch["start_positions"]))
                          + masked_cross_entropy(end, qa_labels(batch["end_positions"])))
        return masked_cross_entropy(out, batch["labels"])


# ---------------------------------------------------------------------------
# HF checkpoint ingestion for task models
# ---------------------------------------------------------------------------

_SEQ_CLS_HEADS = {
    # arch -> (mid-layer key or None, classifier key)
    "bert": ("bert.pooler.dense", "classifier"),
    "roberta": ("classifier.dense", "classifier.out_proj"),
    "distilbert": ("pre_classifier", "classifier"),
}


def load_hf_task_model(model_path: str, task: str, dtype=None,
                       **config_overrides) -> Tuple[EncoderTaskModel, Params]:
    """HF ``*ForSequenceClassification`` / ``*ForTokenClassification`` /
    ``*ForQuestionAnswering`` checkpoint directory → (EncoderTaskModel,
    host param pytree). Counterpart of serving those models through the
    reference's injected-BERT path."""
    from ..runtime.state_dict_factory import (SDLoaderFactory,
                                              hf_state_dict_to_params,
                                              hf_to_transformer_config)

    loader = SDLoaderFactory.get_sd_loader(model_path)
    mt = loader.config.get("model_type", "bert")
    if mt not in _SEQ_CLS_HEADS:
        raise ValueError(f"task heads support bert/roberta/distilbert, "
                         f"not {mt!r}")
    cfg = hf_to_transformer_config(loader.config, dtype=dtype,
                                   mlm_head=False, **config_overrides)
    sd = loader.load_state_dict()

    num_labels = loader.config.get("num_labels") or (
        len(loader.config.get("id2label") or {}) or 2)
    lm = TransformerLM(cfg)
    model = EncoderTaskModel(lm, task, num_labels=num_labels, head_style=mt)
    params = hf_state_dict_to_params(cfg, mt, {
        k: v for k, v in sd.items()
        if not _is_head_key(k)})
    T = np.transpose

    def lin(key):
        return {"kernel": T(sd[key + ".weight"]), "bias": sd[key + ".bias"]}

    if task == "sequence_classification":
        mid_key, cls_key = _SEQ_CLS_HEADS[mt]
        params["head"] = {"mid": lin(mid_key), "classifier": lin(cls_key)}
    elif task == "token_classification":
        params["head"] = {"classifier": lin("classifier")}
    else:  # question_answering
        params["head"] = {"classifier": lin("qa_outputs")}
    return model, params


def _is_head_key(k: str) -> bool:
    return k.startswith(("classifier", "pre_classifier", "qa_outputs",
                         "bert.pooler", "roberta.pooler", "cls.seq_relationship"))
