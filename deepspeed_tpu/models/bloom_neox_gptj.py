"""BLOOM / GPT-NeoX / GPT-J presets.

Counterpart of the reference's kernel-injection policies for these
architectures (``module_inject/containers/{bloom,gptneox,gptj}.py``), which
the v2 model_implementations never covered — expressed through
``TransformerConfig`` knobs:

- **BLOOM** (containers/bloom.py): ALiBi attention bias instead of position
  embeddings, LayerNorm directly after the word embeddings
  (``word_embeddings_layernorm``), sequential residual blocks, tied head.
- **GPT-NeoX** (containers/gptneox.py): parallel attention+MLP fed by TWO
  norms (``use_parallel_residual``), partial rotary (``rotary_pct``),
  untied ``embed_out`` head.
- **GPT-J** (containers/gptj.py): parallel block from ONE norm, partial
  INTERLEAVED rotary (rotate-every-two over ``rotary_dim``), bias-free
  attention with biased MLP, untied biased lm_head.
"""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig, TransformerLM

_BLOOM_PRESETS = {
    "bloom-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                       max_seq_len=64, vocab_size=256),
    "bloom-560m": dict(num_layers=24, num_heads=16, hidden_size=1024),
    "bloom-7b1": dict(num_layers=30, num_heads=32, hidden_size=4096),
    "bloom-176b": dict(num_layers=70, num_heads=112, hidden_size=14336),
}

_NEOX_PRESETS = {
    "gpt-neox-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                          intermediate_size=256, max_seq_len=64,
                          vocab_size=256, rope_dim=4),
    "pythia-1b": dict(num_layers=16, num_heads=8, hidden_size=2048,
                      intermediate_size=8192, max_seq_len=2048,
                      vocab_size=50304, rope_dim=64),
    "gpt-neox-20b": dict(num_layers=44, num_heads=64, hidden_size=6144,
                         intermediate_size=24576, max_seq_len=2048,
                         vocab_size=50432, rope_dim=24),
}

_GPTJ_PRESETS = {
    "gptj-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                      intermediate_size=256, max_seq_len=64, vocab_size=256,
                      rope_dim=8),
    "gpt-j-6b": dict(num_layers=28, num_heads=16, hidden_size=4096,
                     intermediate_size=16384, max_seq_len=2048,
                     vocab_size=50400, rope_dim=64),
}


def bloom_config(preset: str = "bloom-7b1", dtype=jnp.bfloat16,
                 **overrides) -> TransformerConfig:
    base = dict(vocab_size=250880, max_seq_len=2048, activation="gelu",
                norm="layernorm", position="alibi", embedding_norm=True,
                tie_embeddings=True, dtype=dtype)
    base.update(_BLOOM_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def bloom_model(preset: str = "bloom-7b1", **overrides) -> TransformerLM:
    return TransformerLM(bloom_config(preset, **overrides))


def gpt_neox_config(preset: str = "gpt-neox-20b", dtype=jnp.bfloat16,
                    **overrides) -> TransformerConfig:
    # HF default hidden_act "gelu" is the exact erf form (ACT2FN), not the
    # gpt2 tanh approximation
    base = dict(activation="gelu_exact", norm="layernorm", position="rope",
                parallel_block=True, parallel_norms=True,
                tie_embeddings=False, dtype=dtype)
    base.update(_NEOX_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def gpt_neox_model(preset: str = "gpt-neox-20b", **overrides) -> TransformerLM:
    return TransformerLM(gpt_neox_config(preset, **overrides))


_GPT_NEO_PRESETS = {
    "gpt-neo-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                         intermediate_size=256, max_seq_len=64,
                         vocab_size=256, attn_windows=(0, 8)),
    "gpt-neo-1.3b": dict(num_layers=24, num_heads=16, hidden_size=2048,
                         intermediate_size=8192, max_seq_len=2048,
                         attn_windows=tuple(0 if i % 2 == 0 else 256
                                            for i in range(24))),
    "gpt-neo-2.7b": dict(num_layers=32, num_heads=20, hidden_size=2560,
                         intermediate_size=10240, max_seq_len=2048,
                         attn_windows=tuple(0 if i % 2 == 0 else 256
                                            for i in range(32))),
}


def gpt_neo_config(preset: str = "gpt-neo-1.3b", dtype=jnp.bfloat16,
                   **overrides) -> TransformerConfig:
    """GPT-Neo: alternating global/local (windowed) attention layers,
    UNSCALED attention logits, bias-free q/k/v with biased out_proj."""
    base = dict(vocab_size=50257, activation="gelu", norm="layernorm",
                position="learned", attn_scale=1.0, attn_bias=False,
                attn_out_bias=True, tie_embeddings=True, dtype=dtype)
    base.update(_GPT_NEO_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def gpt_neo_model(preset: str = "gpt-neo-1.3b", **overrides) -> TransformerLM:
    return TransformerLM(gpt_neo_config(preset, **overrides))


def gptj_config(preset: str = "gpt-j-6b", dtype=jnp.bfloat16,
                **overrides) -> TransformerConfig:
    base = dict(activation="gelu", norm="layernorm", position="rope",
                rope_style="interleaved", parallel_block=True,
                attn_bias=False, tie_embeddings=False, lm_head_bias=True,
                dtype=dtype)
    base.update(_GPTJ_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def gptj_model(preset: str = "gpt-j-6b", **overrides) -> TransformerLM:
    return TransformerLM(gptj_config(preset, **overrides))
