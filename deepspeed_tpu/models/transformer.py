"""Decoder-only transformer family (GPT-2 / Llama / Mistral / Mixtral / OPT /
Phi / Falcon / BLOOM / GPT-NeoX / GPT-J).

The reference ships models two ways — HF models patched by kernel injection
(``module_inject/replace_module.py``) and per-arch inference impls
(``inference/v2/model_implementations``). Here one TPU-first implementation
covers the family via config: pre-norm blocks, learned or rotary positions,
LayerNorm or RMSNorm, GELU MLP or gated-SiLU MLP, MHA or GQA, optional MoE.

TPU-first structure:
- **scan over layers**: block parameters are stacked with a leading layer
  dimension and the stack is executed with ``lax.scan`` — one trace/compile of
  the block regardless of depth, XLA-friendly.
- **remat**: each block is wrapped in ``jax.checkpoint`` with a configurable
  policy (counterpart of ``runtime/activation_checkpointing/checkpointing.py``).
- **sharding**: params carry PartitionSpecs (TP over ``model``); activations
  are constrained to ``[data, seq, -]``; Ulysses resharding happens inside
  attention (see ``sequence/layer.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import layers as nn
from ..ops.transformer.attention import flash_attention
from ..runtime.topology import BATCH_AXES, DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from ..sequence.layer import ulysses_attention

Params = Dict[str, Any]

ACT_SPEC = P(BATCH_AXES, SEQ_AXIS, None)  # [batch, seq, hidden]

# MLP activations by config name. HF's "gelu_new"/"gelu_pytorch_tanh"
# (gpt2, phi) is the tanh approximation; HF's "gelu" (falcon, galactica)
# is the exact erf form — they differ by up to ~5e-4 per neuron, which
# compounds across layers, so checkpoint ingestion must distinguish them.
ACTIVATIONS = {
    "gelu": nn.gelu,  # tanh approximation
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
}


def _c(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, TypeError, RuntimeError):
        return x


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    aux_loss_coef: float = 0.01


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # None => MHA
    hidden_size: int = 768
    intermediate_size: Optional[int] = None  # None => 4*hidden
    activation: str = "gelu"        # 'gelu' | 'gelu_exact' | 'relu' | 'silu_gated'
    norm: str = "layernorm"          # 'layernorm' | 'rmsnorm'
    norm_eps: float = 1e-5           # HF config layer_norm_epsilon / rms_norm_eps
    position: str = "learned"        # 'learned' | 'rope' | 'alibi'
    position_offset: int = 0         # OPT pads learned positions by 2
    rope_theta: float = 10000.0
    rope_dim: Optional[int] = None   # partial rotary (phi/neox/gpt-j); None => head_dim
    rope_style: str = "half"         # 'half' (llama/neox) | 'interleaved' (gpt-j)
    embedding_norm: bool = False     # bloom: LayerNorm right after wte
    parallel_block: bool = False     # falcon/phi: x + attn(ln(x)) + mlp(ln(x))
    parallel_norms: bool = False     # falcon-40b/neox: separate ln per parallel branch
    linear_bias: Optional[bool] = None  # None => biases iff layernorm
    attn_bias: Optional[bool] = None    # gpt-j: bias-free attn, biased MLP
    lm_head_bias: bool = False       # phi/gpt-j lm_head carries a bias
    tie_embeddings: bool = True
    seq_parallel: str = "ulysses"    # 'ulysses' | 'ring' (long-context SP)
    dtype: Any = jnp.float32         # compute dtype (params kept by engine policy)
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    moe: Optional[MoEConfig] = None
    moe_layer_freq: int = 1          # every k-th layer is MoE when moe is set

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    def num_parameters(self) -> int:
        h, v, L = self.hidden_size, self.vocab_size, self.num_layers
        ffn = self.ffn_size
        kv = self.kv_heads * self.head_dim
        attn = h * (h + 2 * kv) + h * h
        if self.activation == "silu_gated":
            mlp = 3 * h * ffn
        else:
            mlp = 2 * h * ffn
        if self.moe is not None:
            mlp = mlp * self.moe.num_experts + h * self.moe.num_experts
        embed = v * h + ((self.max_seq_len + self.position_offset) * h
                         if self.position == "learned" else 0)
        head = 0 if self.tie_embeddings else v * h
        return embed + head + L * (attn + mlp)


class TransformerLM:

    def __init__(self, config: TransformerConfig):
        self.config = config
        c = config
        self._wte = nn.Embedding(c.vocab_size, c.hidden_size, shard=True)
        self._wpe = (nn.Embedding(c.max_seq_len + c.position_offset, c.hidden_size)
                     if c.position == "learned" else None)
        base_cls = nn.LayerNorm if c.norm == "layernorm" else nn.RMSNorm
        norm_cls = lambda features: base_cls(features, eps=c.norm_eps)
        self._norm = norm_cls
        self._ln_f = norm_cls(c.hidden_size)
        # bloom normalizes embeddings before the first block
        self._ln_emb = norm_cls(c.hidden_size) if c.embedding_norm else None
        if c.position == "alibi":
            if c.seq_parallel == "ring":
                raise ValueError("alibi positions are not supported with "
                                 "ring sequence parallelism (K/V rotation "
                                 "loses absolute key positions)")
            from ..ops.transformer.attention import alibi_slopes
            self._alibi_slopes = alibi_slopes(c.num_heads)
        else:
            self._alibi_slopes = None
        if not c.tie_embeddings:
            self._lm_head = nn.Linear(c.hidden_size, c.vocab_size,
                                      use_bias=c.lm_head_bias, shard="column")

        # gpt2-style models use biases; falcon keeps layernorm but bias-free
        # linears (linear_bias overrides the norm-derived default)
        use_bias = (c.linear_bias if c.linear_bias is not None
                    else c.norm == "layernorm")
        # gpt-j: attention projections are bias-free while the MLP keeps
        # biases — attn_bias overrides the block-wide default for attn only
        attn_bias = c.attn_bias if c.attn_bias is not None else use_bias
        kv_out = c.kv_heads * c.head_dim
        self._block_layers = {
            "ln_1": norm_cls(c.hidden_size),
            "q_proj": nn.Linear(c.hidden_size, c.hidden_size, use_bias=attn_bias, shard="column"),
            "k_proj": nn.Linear(c.hidden_size, kv_out, use_bias=attn_bias, shard="column"),
            "v_proj": nn.Linear(c.hidden_size, kv_out, use_bias=attn_bias, shard="column"),
            "o_proj": nn.Linear(c.hidden_size, c.hidden_size, use_bias=attn_bias, shard="row"),
        }
        if not c.parallel_block or c.parallel_norms:
            # parallel blocks (falcon-7b/phi) feed attention and MLP from the
            # SAME normed input — no second norm exists in the checkpoint;
            # falcon-40b's "new decoder" norms each parallel branch separately
            self._block_layers["ln_2"] = norm_cls(c.hidden_size)
        if c.moe is not None:
            from ..moe.layer import MoE
            self._moe = MoE(
                hidden_size=c.hidden_size,
                intermediate_size=c.ffn_size,
                num_experts=c.moe.num_experts,
                top_k=c.moe.top_k,
                capacity_factor=c.moe.capacity_factor,
                min_capacity=c.moe.min_capacity,
                activation=c.activation,
            )
        elif c.activation == "silu_gated":
            self._block_layers.update({
                "gate_proj": nn.Linear(c.hidden_size, c.ffn_size, use_bias=False, shard="column"),
                "up_proj": nn.Linear(c.hidden_size, c.ffn_size, use_bias=False, shard="column"),
                "down_proj": nn.Linear(c.ffn_size, c.hidden_size, use_bias=False, shard="row"),
            })
        else:
            self._block_layers.update({
                "fc_in": nn.Linear(c.hidden_size, c.ffn_size, use_bias=use_bias, shard="column"),
                "fc_out": nn.Linear(c.ffn_size, c.hidden_size, use_bias=use_bias, shard="row"),
            })

    # -- init / specs --------------------------------------------------------
    def init(self, rng: jax.Array, dtype=jnp.float32) -> Params:
        c = self.config
        rng_embed, rng_blocks, rng_head = jax.random.split(rng, 3)
        params: Params = {"wte": self._wte.init(rng_embed, dtype)}
        if self._wpe is not None:
            params["wpe"] = self._wpe.init(jax.random.fold_in(rng_embed, 1), dtype)
        if self._ln_emb is not None:
            params["ln_emb"] = self._ln_emb.init(jax.random.fold_in(rng_embed, 2), dtype)
        params["ln_f"] = self._ln_f.init(rng_head, dtype)
        if not c.tie_embeddings:
            params["lm_head"] = self._lm_head.init(rng_head, dtype)

        def init_block(r):
            block, _ = nn.init_tree(self._block_layers, r, dtype)
            if c.moe is not None:
                block["moe"] = self._moe.init(jax.random.fold_in(r, 7), dtype)
            return block

        params["blocks"] = jax.vmap(init_block)(jax.random.split(rng_blocks, c.num_layers))
        return params

    def specs(self) -> Params:
        c = self.config
        specs: Params = {"wte": self._wte.specs()}
        if self._wpe is not None:
            specs["wpe"] = self._wpe.specs()
        if self._ln_emb is not None:
            specs["ln_emb"] = self._ln_emb.specs()
        specs["ln_f"] = self._ln_f.specs()
        if not c.tie_embeddings:
            specs["lm_head"] = self._lm_head.specs()
        block_specs = {name: layer.specs() for name, layer in self._block_layers.items()}
        if c.moe is not None:
            block_specs["moe"] = self._moe.specs()
        # stacked over layers: prepend None for the layer dim
        block_specs = jax.tree.map(
            lambda s: P(None, *s), block_specs,
            is_leaf=lambda s: isinstance(s, P))
        specs["blocks"] = block_specs
        return specs

    # -- forward -------------------------------------------------------------
    def _rotate(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        """Rotary embedding, possibly PARTIAL (phi applies rope to only the
        first rope_dim of each head, passing the rest through)."""
        c = self.config
        rd = c.rope_dim or c.head_dim
        if rd >= c.head_dim:
            return nn.rotary_embedding(x, positions, c.rope_theta, c.rope_style)
        rot = nn.rotary_embedding(x[..., :rd], positions, c.rope_theta, c.rope_style)
        return jnp.concatenate([rot, x[..., rd:]], axis=-1)

    def _attn(self, block: Params, h: jax.Array, positions: jax.Array) -> jax.Array:
        """Attention over the PRE-NORMED input h."""
        c = self.config
        B, S, _ = h.shape
        q = self._block_layers["q_proj"](block["q_proj"], h).reshape(B, S, c.num_heads, c.head_dim)
        k = self._block_layers["k_proj"](block["k_proj"], h).reshape(B, S, c.kv_heads, c.head_dim)
        v = self._block_layers["v_proj"](block["v_proj"], h).reshape(B, S, c.kv_heads, c.head_dim)
        if c.position == "rope":
            q = self._rotate(q, positions)
            k = self._rotate(k, positions)
        if c.seq_parallel == "ring":
            from ..sequence.ring_attention import ring_attention
            out = ring_attention(q, k, v, causal=True)
        elif self._alibi_slopes is not None:
            out = ulysses_attention(flash_attention, q, k, v, causal=True,
                                    alibi_slopes=jnp.asarray(self._alibi_slopes))
        else:
            out = ulysses_attention(flash_attention, q, k, v, causal=True)
        out = out.reshape(B, S, c.num_heads * c.head_dim)
        return self._block_layers["o_proj"](block["o_proj"], out)

    def _mlp(self, block: Params, h: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """MLP over the PRE-NORMED input h."""
        c = self.config
        aux = jnp.zeros((), dtype=jnp.float32)
        if c.moe is not None:
            out, aux = self._moe(block["moe"], h)
        elif c.activation == "silu_gated":
            gate = nn.silu(self._block_layers["gate_proj"](block["gate_proj"], h))
            up = self._block_layers["up_proj"](block["up_proj"], h)
            out = self._block_layers["down_proj"](block["down_proj"], gate * up)
        else:
            h2 = ACTIVATIONS[c.activation](self._block_layers["fc_in"](block["fc_in"], h))
            out = self._block_layers["fc_out"](block["fc_out"], h2)
        return out, aux

    def _block_fn(self, carry, block_and_keep):
        block, keep = block_and_keep
        x, positions, aux_acc = carry
        c = self.config
        # keep: per-layer stochastic-depth gate (progressive layer drop,
        # reference runtime/progressive_layer_drop.py); 1.0 = layer active
        h1 = self._block_layers["ln_1"](block["ln_1"], x)
        if c.parallel_block:
            # falcon/phi residual form: both branches read the block INPUT —
            # through one shared norm (phi/falcon-7b) or per-branch norms
            # (falcon-40b new decoder)
            attn_out = self._attn(block, h1, positions)
            hm = (self._block_layers["ln_2"](block["ln_2"], x)
                  if c.parallel_norms else h1)
            mlp_out, aux = self._mlp(block, hm)
            x = _c(x + keep * (attn_out + mlp_out), ACT_SPEC)
        else:
            x = x + keep * self._attn(block, h1, positions)
            h2 = self._block_layers["ln_2"](block["ln_2"], x)
            mlp_out, aux = self._mlp(block, h2)
            x = _c(x + keep * mlp_out, ACT_SPEC)
        return (x, positions, aux_acc + keep * aux), None

    def apply(self, params: Params, input_ids: jax.Array,
              layer_mask: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
        """Return (logits [B,S,V] in fp32, moe_aux_loss scalar).

        ``layer_mask`` [num_layers] gates each block (PLD stochastic depth).
        """
        c = self.config
        positions = jnp.arange(input_ids.shape[1])[None, :]
        x = self._wte(params["wte"], input_ids)
        if self._wpe is not None:
            x = x + self._wpe(params["wpe"], positions + c.position_offset)
        if self._ln_emb is not None:
            x = self._ln_emb(params["ln_emb"], x)
        x = _c(x.astype(c.dtype), ACT_SPEC)

        block_fn = self._block_fn
        if c.remat:
            policy = None
            if c.remat_policy and c.remat_policy not in ("full", "nothing_saveable"):
                policy = getattr(jax.checkpoint_policies, c.remat_policy)
            block_fn = jax.checkpoint(block_fn, policy=policy)

        if layer_mask is None:
            keep = jnp.ones((c.num_layers,), c.dtype)
        else:
            keep = layer_mask.astype(c.dtype)
        (x, _, aux), _ = jax.lax.scan(block_fn, (x, positions, jnp.zeros((), jnp.float32)),
                                      (params["blocks"], keep))
        x = self._ln_f(params["ln_f"], x)
        if c.tie_embeddings:
            logits = self._wte.attend(params["wte"], x)
        else:
            logits = self._lm_head(params["lm_head"], x)
        return logits.astype(jnp.float32), aux

    def loss(self, params: Params, batch: Dict[str, jax.Array]) -> jax.Array:
        """Next-token cross-entropy. batch: input_ids [B,S], optional labels,
        optional loss_mask."""
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.pad(input_ids[:, 1:], ((0, 0), (0, 1)), constant_values=-100)
        logits, aux = self.apply(params, input_ids,
                                 layer_mask=batch.get("layer_mask"))
        valid = labels >= 0
        safe_labels = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        # one-hot contraction instead of take_along_axis: its transpose is a
        # dense broadcast-multiply that GSPMD reshards freely, where the
        # scatter-add transpose of a gather forces a full rematerialization
        # when logits are vocab-sharded (TP lm_head). XLA fuses the one-hot
        # into the reduction, so no [B,S,V] buffer is materialized.
        onehot = jax.nn.one_hot(safe_labels, logits.shape[-1], dtype=logp.dtype)
        token_loss = -jnp.sum(logp * onehot, axis=-1)
        mask = valid.astype(jnp.float32)
        if "loss_mask" in batch:
            mask = mask * batch["loss_mask"].astype(jnp.float32)
        loss = jnp.sum(token_loss * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        if self.config.moe is not None:
            loss = loss + self.config.moe.aux_loss_coef * aux / self.config.num_layers
        return loss
