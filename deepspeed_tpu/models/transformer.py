"""Decoder-only transformer family (GPT-2 / Llama / Mistral / Mixtral / OPT /
Phi / Falcon / BLOOM / GPT-NeoX / GPT-J).

The reference ships models two ways — HF models patched by kernel injection
(``module_inject/replace_module.py``) and per-arch inference impls
(``inference/v2/model_implementations``). Here one TPU-first implementation
covers the family via config: pre-norm blocks, learned or rotary positions,
LayerNorm or RMSNorm, GELU MLP or gated-SiLU MLP, MHA or GQA, optional MoE.

TPU-first structure:
- **scan over layers**: block parameters are stacked with a leading layer
  dimension and the stack is executed with ``lax.scan`` — one trace/compile of
  the block regardless of depth, XLA-friendly.
- **remat**: each block is wrapped in ``jax.checkpoint`` with a configurable
  policy (counterpart of ``runtime/activation_checkpointing/checkpointing.py``).
- **sharding**: params carry PartitionSpecs (TP over ``model``); activations
  are constrained to ``[data, seq, -]``; Ulysses resharding happens inside
  attention (see ``sequence/layer.py``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import layers as nn
from ..ops.transformer.attention import flash_attention
from ..runtime.topology import BATCH_AXES, DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from ..utils.jax_compat import with_sharding_constraint
from ..sequence.layer import ulysses_attention

Params = Dict[str, Any]

ACT_SPEC = P(BATCH_AXES, SEQ_AXIS, None)  # [batch, seq, hidden]

# MLP activations by config name. HF's "gelu_new"/"gelu_pytorch_tanh"
# (gpt2, phi) is the tanh approximation; HF's "gelu" (falcon, galactica)
# is the exact erf form — they differ by up to ~5e-4 per neuron, which
# compounds across layers, so checkpoint ingestion must distinguish them.
ACTIVATIONS = {
    "gelu": nn.gelu,  # tanh approximation
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
}


def _c(x, spec):
    return with_sharding_constraint(x, spec)


def masked_cross_entropy(logits: jax.Array, labels: jax.Array,
                         extra_mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean cross-entropy over positions where ``labels >= 0`` (−100 = HF
    ignore). One-hot contraction instead of take_along_axis: its transpose
    is a dense broadcast-multiply that GSPMD reshards freely, where the
    scatter-add transpose of a gather forces a full rematerialization when
    logits are vocab-sharded (TP lm_head). XLA fuses the one-hot into the
    reduction, so no [..., V] buffer is materialized."""
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(safe, logits.shape[-1], dtype=logp.dtype)
    nll = -jnp.sum(logp * onehot, axis=-1)
    mask = valid.astype(jnp.float32)
    if extra_mask is not None:
        mask = mask * extra_mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    aux_loss_coef: float = 0.01


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # None => MHA
    hidden_size: int = 768
    intermediate_size: Optional[int] = None  # None => 4*hidden
    activation: str = "gelu"        # 'gelu' | 'gelu_exact' | 'relu' | 'silu_gated'
    norm: str = "layernorm"          # 'layernorm' | 'rmsnorm'
    norm_eps: float = 1e-5           # HF config layer_norm_epsilon / rms_norm_eps
    position: str = "learned"        # 'learned' | 'rope' | 'alibi'
    position_offset: int = 0         # OPT pads learned positions by 2
    rope_theta: float = 10000.0
    rope_dim: Optional[int] = None   # partial rotary (phi/neox/gpt-j); None => head_dim
    rope_style: str = "half"         # 'half' (llama/neox) | 'interleaved' (gpt-j)
    # per-layer causal attention windows (mistral sliding_window; gpt-neo
    # alternating global/local): 0 = global, w > 0 = attend the last w keys.
    # A single int applies to every layer.
    attn_windows: Any = None         # Optional[int | Tuple[int, ...]]
    attn_scale: Optional[float] = None  # gpt-neo: 1.0 (unscaled); None => 1/sqrt(hd)
    embedding_norm: bool = False     # bloom: LayerNorm right after wte
    parallel_block: bool = False     # falcon/phi: x + attn(ln(x)) + mlp(ln(x))
    parallel_norms: bool = False     # falcon-40b/neox: separate ln per parallel branch
    linear_bias: Optional[bool] = None  # None => biases iff layernorm
    attn_bias: Optional[bool] = None    # gpt-j: bias-free attn, biased MLP
    attn_out_bias: Optional[bool] = None  # gpt-neo: bias-free qkv, biased out_proj
    lm_head_bias: bool = False       # phi/gpt-j lm_head carries a bias
    tie_embeddings: bool = True
    causal: bool = True              # False: bidirectional encoder (bert)
    norm_style: str = "pre"          # 'pre' | 'post' (bert-era encoders)
    type_vocab_size: int = 0         # bert segment (token-type) embeddings
    mlm_head: bool = False           # bert cls.predictions transform + bias
    # roberta: position ids are a cumsum over non-pad tokens offset by
    # padding_idx (HF create_position_ids_from_input_ids) — pads land on
    # the padding_idx row, real tokens on padding_idx+1..; requires
    # pad_token_id. position_offset still sizes the table (+2 rows).
    pad_based_positions: bool = False
    pad_token_id: Optional[int] = None
    seq_parallel: str = "ulysses"    # 'ulysses' | 'ring' (long-context SP)
    dtype: Any = jnp.float32         # compute dtype (params kept by engine policy)
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    moe: Optional[MoEConfig] = None
    moe_layer_freq: int = 1          # every k-th layer is MoE when moe is set

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    def num_parameters(self) -> int:
        h, v, L = self.hidden_size, self.vocab_size, self.num_layers
        ffn = self.ffn_size
        kv = self.kv_heads * self.head_dim
        attn = h * (h + 2 * kv) + h * h
        if self.activation == "silu_gated":
            mlp = 3 * h * ffn
        else:
            mlp = 2 * h * ffn
        if self.moe is not None:
            mlp = mlp * self.moe.num_experts + h * self.moe.num_experts
        embed = v * h + ((self.max_seq_len + self.position_offset) * h
                         if self.position == "learned" else 0)
        embed += self.type_vocab_size * h
        head = 0 if self.tie_embeddings else v * h
        if self.mlm_head:
            head += h * h + v  # prediction transform + decoder bias
        return embed + head + L * (attn + mlp)


class TransformerLM:

    #: top-level param keys :meth:`embed` reads — the overlap planner's
    #: edge-split schedule (engine._build_zeropp_micro_overlap) keeps
    #: exactly these leaves at the exposed step edges and hoists every
    #: other rest leaf across the block scans. MUST stay in sync with
    #: embed(): a leaf embed reads but this tuple omits would be
    #: classified head-side and its embed-path gradient silently dropped
    #: (the split differentiates embed only w.r.t. these leaves).
    embed_param_keys = ("wte", "wpe", "ln_emb", "wtt")

    def __init__(self, config: TransformerConfig):
        self.config = config
        c = config
        self._wte = nn.Embedding(c.vocab_size, c.hidden_size, shard=True)
        self._wpe = (nn.Embedding(c.max_seq_len + c.position_offset, c.hidden_size)
                     if c.position == "learned" else None)
        base_cls = nn.LayerNorm if c.norm == "layernorm" else nn.RMSNorm
        norm_cls = lambda features: base_cls(features, eps=c.norm_eps)
        self._norm = norm_cls
        # post-LN (bert): the last block's output LN already normalizes the
        # final hidden states — there is no separate final norm
        self._ln_f = norm_cls(c.hidden_size) if c.norm_style == "pre" else None
        # bloom normalizes embeddings before the first block; bert-era
        # encoders do the same (embeddings.LayerNorm)
        self._ln_emb = norm_cls(c.hidden_size) if c.embedding_norm else None
        # bert segment embeddings + MLM prediction head (dense→act→LN, then
        # the tied decoder with its own bias)
        self._wtt = (nn.Embedding(c.type_vocab_size, c.hidden_size)
                     if c.type_vocab_size else None)
        if c.mlm_head:
            self._mlm_dense = nn.Linear(c.hidden_size, c.hidden_size)
            self._mlm_ln = norm_cls(c.hidden_size)
        if not c.causal and c.position not in ("learned",):
            raise ValueError("bidirectional encoders use learned positions")
        if not c.causal and c.seq_parallel == "ring":
            raise ValueError("ring attention is causal-only")
        if c.pad_based_positions and c.pad_token_id is None:
            raise ValueError("pad_based_positions requires pad_token_id")
        if c.attn_windows is not None:
            if not c.causal:
                raise ValueError("attention windows are causal-only")
            if c.seq_parallel == "ring":
                raise ValueError("attention windows are not supported with "
                                 "ring sequence parallelism")
            w = c.attn_windows
            self._windows = tuple([int(w)] * c.num_layers
                                  if isinstance(w, int) else map(int, w))
            if len(self._windows) != c.num_layers:
                raise ValueError(f"attn_windows has {len(self._windows)} "
                                 f"entries for {c.num_layers} layers")
            # windows that can never bind (>= max_seq_len, e.g. mistral's
            # 4096 under a 4096 context) normalize to global, and all-global
            # patterns to None, so PP and the Pallas gate stay open for
            # effectively-windowless models
            self._windows = tuple(0 if wi >= c.max_seq_len else wi
                                  for wi in self._windows)
            if not any(self._windows):
                self._windows = None
        else:
            self._windows = None
        if c.position == "alibi":
            if c.seq_parallel == "ring":
                raise ValueError("alibi positions are not supported with "
                                 "ring sequence parallelism (K/V rotation "
                                 "loses absolute key positions)")
            from ..ops.transformer.attention import alibi_slopes
            self._alibi_slopes = alibi_slopes(c.num_heads)
        else:
            self._alibi_slopes = None
        if not c.tie_embeddings:
            self._lm_head = nn.Linear(c.hidden_size, c.vocab_size,
                                      use_bias=c.lm_head_bias, shard="column")

        # gpt2-style models use biases; falcon keeps layernorm but bias-free
        # linears (linear_bias overrides the norm-derived default)
        use_bias = (c.linear_bias if c.linear_bias is not None
                    else c.norm == "layernorm")
        # gpt-j: attention projections are bias-free while the MLP keeps
        # biases — attn_bias overrides the block-wide default for attn only
        attn_bias = c.attn_bias if c.attn_bias is not None else use_bias
        attn_out_bias = (c.attn_out_bias if c.attn_out_bias is not None
                         else attn_bias)
        kv_out = c.kv_heads * c.head_dim
        self._block_layers = {
            "ln_1": norm_cls(c.hidden_size),
            "q_proj": nn.Linear(c.hidden_size, c.hidden_size, use_bias=attn_bias, shard="column"),
            "k_proj": nn.Linear(c.hidden_size, kv_out, use_bias=attn_bias, shard="column"),
            "v_proj": nn.Linear(c.hidden_size, kv_out, use_bias=attn_bias, shard="column"),
            "o_proj": nn.Linear(c.hidden_size, c.hidden_size, use_bias=attn_out_bias, shard="row"),
        }
        if not c.parallel_block or c.parallel_norms:
            # parallel blocks (falcon-7b/phi) feed attention and MLP from the
            # SAME normed input — no second norm exists in the checkpoint;
            # falcon-40b's "new decoder" norms each parallel branch separately
            self._block_layers["ln_2"] = norm_cls(c.hidden_size)
        if c.moe is not None:
            from ..moe.layer import MoE
            self._moe = MoE(
                hidden_size=c.hidden_size,
                intermediate_size=c.ffn_size,
                num_experts=c.moe.num_experts,
                top_k=c.moe.top_k,
                capacity_factor=c.moe.capacity_factor,
                min_capacity=c.moe.min_capacity,
                activation=c.activation,
            )
        elif c.activation == "silu_gated":
            self._block_layers.update({
                "gate_proj": nn.Linear(c.hidden_size, c.ffn_size, use_bias=False, shard="column"),
                "up_proj": nn.Linear(c.hidden_size, c.ffn_size, use_bias=False, shard="column"),
                "down_proj": nn.Linear(c.ffn_size, c.hidden_size, use_bias=False, shard="row"),
            })
        else:
            self._block_layers.update({
                "fc_in": nn.Linear(c.hidden_size, c.ffn_size, use_bias=use_bias, shard="column"),
                "fc_out": nn.Linear(c.ffn_size, c.hidden_size, use_bias=use_bias, shard="row"),
            })

    # -- init / specs --------------------------------------------------------
    def init(self, rng: jax.Array, dtype=jnp.float32) -> Params:
        c = self.config
        rng_embed, rng_blocks, rng_head = jax.random.split(rng, 3)
        params: Params = {"wte": self._wte.init(rng_embed, dtype)}
        if self._wpe is not None:
            params["wpe"] = self._wpe.init(jax.random.fold_in(rng_embed, 1), dtype)
        if self._ln_emb is not None:
            params["ln_emb"] = self._ln_emb.init(jax.random.fold_in(rng_embed, 2), dtype)
        if self._wtt is not None:
            params["wtt"] = self._wtt.init(jax.random.fold_in(rng_embed, 3), dtype)
        if self._ln_f is not None:
            params["ln_f"] = self._ln_f.init(rng_head, dtype)
        if not c.tie_embeddings:
            params["lm_head"] = self._lm_head.init(rng_head, dtype)
        if c.mlm_head:
            r = jax.random.fold_in(rng_head, 4)
            params["mlm"] = {
                "dense": self._mlm_dense.init(r, dtype),
                "ln": self._mlm_ln.init(jax.random.fold_in(r, 1), dtype),
                "bias": jnp.zeros((c.vocab_size,), dtype),
            }

        def init_block(r):
            block, _ = nn.init_tree(self._block_layers, r, dtype)
            if c.moe is not None:
                block["moe"] = self._moe.init(jax.random.fold_in(r, 7), dtype)
            return block

        params["blocks"] = jax.vmap(init_block)(jax.random.split(rng_blocks, c.num_layers))
        return params

    def specs(self) -> Params:
        c = self.config
        specs: Params = {"wte": self._wte.specs()}
        if self._wpe is not None:
            specs["wpe"] = self._wpe.specs()
        if self._ln_emb is not None:
            specs["ln_emb"] = self._ln_emb.specs()
        if self._wtt is not None:
            specs["wtt"] = self._wtt.specs()
        if self._ln_f is not None:
            specs["ln_f"] = self._ln_f.specs()
        if not c.tie_embeddings:
            specs["lm_head"] = self._lm_head.specs()
        if c.mlm_head:
            specs["mlm"] = {"dense": self._mlm_dense.specs(),
                            "ln": self._mlm_ln.specs(),
                            "bias": P(None)}
        block_specs = {name: layer.specs() for name, layer in self._block_layers.items()}
        if c.moe is not None:
            block_specs["moe"] = self._moe.specs()
        # stacked over layers: prepend None for the layer dim
        block_specs = jax.tree.map(
            lambda s: P(None, *s), block_specs,
            is_leaf=lambda s: isinstance(s, P))
        specs["blocks"] = block_specs
        return specs

    # -- forward -------------------------------------------------------------
    def _rotate(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        """Rotary embedding, possibly PARTIAL (phi applies rope to only the
        first rope_dim of each head, passing the rest through)."""
        c = self.config
        rd = c.rope_dim or c.head_dim
        if rd >= c.head_dim:
            return nn.rotary_embedding(x, positions, c.rope_theta, c.rope_style)
        rot = nn.rotary_embedding(x[..., :rd], positions, c.rope_theta, c.rope_style)
        return jnp.concatenate([rot, x[..., rd:]], axis=-1)

    def _attn(self, block: Params, h: jax.Array, positions: jax.Array,
              attn_mask: Optional[jax.Array] = None,
              window: Optional[jax.Array] = None) -> jax.Array:
        """Attention over the (pre-normed, or raw for post-LN) input h.
        ``attn_mask`` [B, S] (1 = real token) masks padding bidirectionally
        via the segment-ids mechanism (encoders). ``window`` (traced scalar,
        0 = global) restricts each query to the last ``window`` keys
        (mistral sliding window / gpt-neo local layers)."""
        c = self.config
        B, S, _ = h.shape
        q = self._block_layers["q_proj"](block["q_proj"], h).reshape(B, S, c.num_heads, c.head_dim)
        k = self._block_layers["k_proj"](block["k_proj"], h).reshape(B, S, c.kv_heads, c.head_dim)
        v = self._block_layers["v_proj"](block["v_proj"], h).reshape(B, S, c.kv_heads, c.head_dim)
        if c.position == "rope":
            q = self._rotate(q, positions)
            k = self._rotate(k, positions)
        seg = attn_mask.astype(jnp.int32) if attn_mask is not None else None
        kw = {}
        if c.attn_scale is not None:
            kw["scale"] = c.attn_scale
        if window is not None:
            kw["window"] = window
        if c.seq_parallel == "ring":
            if seg is not None:
                raise ValueError("ring attention does not support padding "
                                 "masks (attention_mask)")
            from ..sequence.ring_attention import ring_attention
            out = ring_attention(q, k, v, causal=True, scale=c.attn_scale)
        elif self._alibi_slopes is not None:
            out = ulysses_attention(flash_attention, q, k, v, causal=c.causal,
                                    segment_ids=seg,
                                    alibi_slopes=jnp.asarray(self._alibi_slopes),
                                    **kw)
        else:
            out = ulysses_attention(flash_attention, q, k, v, causal=c.causal,
                                    segment_ids=seg, **kw)
        out = out.reshape(B, S, c.num_heads * c.head_dim)
        return self._block_layers["o_proj"](block["o_proj"], out)

    def _mlp(self, block: Params, h: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """MLP over the PRE-NORMED input h."""
        c = self.config
        aux = jnp.zeros((), dtype=jnp.float32)
        if c.moe is not None:
            out, aux = self._moe(block["moe"], h)
        elif c.activation == "silu_gated":
            gate = nn.silu(self._block_layers["gate_proj"](block["gate_proj"], h))
            up = self._block_layers["up_proj"](block["up_proj"], h)
            out = self._block_layers["down_proj"](block["down_proj"], gate * up)
        else:
            h2 = ACTIVATIONS[c.activation](self._block_layers["fc_in"](block["fc_in"], h))
            out = self._block_layers["fc_out"](block["fc_out"], h2)
        return out, aux

    def _block_fn(self, attn_mask, carry, block_and_keep):
        if len(block_and_keep) == 3:
            block, keep, window = block_and_keep
        else:  # pipeline stage path: global attention only
            block, keep = block_and_keep
            window = None
        x, positions, aux_acc = carry
        c = self.config
        # keep: per-layer stochastic-depth gate (progressive layer drop,
        # reference runtime/progressive_layer_drop.py); 1.0 = layer active
        if c.norm_style == "post":
            # bert-era encoder block: LN AFTER each residual add. The PLD
            # gate mixes OUTSIDE the norms (keep*block(x) + (1-keep)*x) so a
            # dropped layer (keep=0, gates are binary draws) is a true
            # identity — gating inside would still double-normalize x.
            h = self._block_layers["ln_1"](
                block["ln_1"], x + self._attn(block, x, positions, attn_mask))
            mlp_out, aux = self._mlp(block, h)
            y = self._block_layers["ln_2"](block["ln_2"], h + mlp_out)
            x = _c(keep * y + (1 - keep) * x, ACT_SPEC)
            return (x, positions, aux_acc + keep * aux), None
        h1 = self._block_layers["ln_1"](block["ln_1"], x)
        if c.parallel_block:
            # falcon/phi residual form: both branches read the block INPUT —
            # through one shared norm (phi/falcon-7b) or per-branch norms
            # (falcon-40b new decoder)
            attn_out = self._attn(block, h1, positions, attn_mask, window)
            hm = (self._block_layers["ln_2"](block["ln_2"], x)
                  if c.parallel_norms else h1)
            mlp_out, aux = self._mlp(block, hm)
            x = _c(x + keep * (attn_out + mlp_out), ACT_SPEC)
        else:
            x = x + keep * self._attn(block, h1, positions, attn_mask, window)
            h2 = self._block_layers["ln_2"](block["ln_2"], x)
            mlp_out, aux = self._mlp(block, h2)
            x = _c(x + keep * mlp_out, ACT_SPEC)
        return (x, positions, aux_acc + keep * aux), None

    def embed(self, params: Params, input_ids: jax.Array,
              token_type_ids: Optional[jax.Array] = None
              ) -> Tuple[jax.Array, jax.Array]:
        """Front of the network: token + position (+ segment) embeddings,
        embedding norm, cast to compute dtype. Returns (x [B,S,H],
        positions [1,S]). Split out of ``apply`` so the param-streaming
        trainer (zero/param_stream.py) can run it as its own program with
        only the embedding leaves resident."""
        c = self.config
        positions = jnp.arange(input_ids.shape[1])[None, :]
        x = self._wte(params["wte"], input_ids)
        if self._wpe is not None:
            if c.pad_based_positions:
                pad = c.pad_token_id  # __init__ rejects None
                real = (input_ids != pad).astype(jnp.int32)
                pos_ids = jnp.cumsum(real, axis=1) * real + pad
                x = x + self._wpe(params["wpe"], pos_ids)
            else:
                x = x + self._wpe(params["wpe"], positions + c.position_offset)
        if self._wtt is not None:
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros_like(input_ids))
            x = x + self._wtt(params["wtt"], tt)
        if self._ln_emb is not None:
            x = self._ln_emb(params["ln_emb"], x)
        return _c(x.astype(c.dtype), ACT_SPEC), positions

    def head(self, params: Params, x: jax.Array) -> jax.Array:
        """Back of the network: final norm (pre-LN), MLM transform, LM/MLM
        head. Input is the last block's output; returns fp32 logits. The
        tied-embedding head reads ``params['wte']`` — the param-streaming
        trainer keeps the embedding leaves resident for this reason."""
        c = self.config
        if self._ln_f is not None:
            x = self._ln_f(params["ln_f"], x)
        if c.mlm_head:
            # bert cls.predictions: dense → act → LN → tied decoder + bias
            x = ACTIVATIONS[c.activation](
                self._mlm_dense(params["mlm"]["dense"], x))
            x = self._mlm_ln(params["mlm"]["ln"], x)
        if c.tie_embeddings:
            logits = self._wte.attend(params["wte"], x)
        else:
            logits = self._lm_head(params["lm_head"], x)
        if c.mlm_head:
            logits = logits + params["mlm"]["bias"].astype(logits.dtype)
        return logits.astype(jnp.float32)

    def block_apply(self, block: Params, x: jax.Array, positions: jax.Array,
                    keep=1.0, attn_mask: Optional[jax.Array] = None,
                    window: Optional[jax.Array] = None
                    ) -> Tuple[jax.Array, jax.Array]:
        """ONE transformer block over UNSTACKED per-layer params — the
        param-streaming trainer's unit of compute (reference fetches one
        module's partitions at a time, partitioned_param_coordinator.py:280).
        Returns (x', moe_aux)."""
        carry = (x, positions, jnp.zeros((), jnp.float32))
        keep = jnp.asarray(keep, self.config.dtype)
        packed = (block, keep) if window is None else (block, keep, window)
        (x2, _, aux), _ = self._block_fn(attn_mask, carry, packed)
        return x2, aux

    def scan_blocks_pipelined(self, blocks: Params, x: jax.Array,
                              positions: jax.Array, *, gather, scatter,
                              keep: Optional[jax.Array] = None,
                              attn_mask: Optional[jax.Array] = None,
                              layers_per_step: int = 1,
                              prefetch_depth: int = 1,
                              comm_scope=None, comm_edge=None,
                              scatter_err=None):
        """Layer-granular ZeRO overlap schedule over SHARDED stacked block
        params (the engine's pipelined ZeRO++/stage-3 micro step; see
        runtime/zero/overlap.py for the comm half).

        Forward: a scan whose carry holds the NEXT layer's gathered (full)
        params — iteration *l* issues the all-gather of layer *l+1*'s shard
        via ``gather`` while computing layer *l* with the already-gathered
        buffer (double-buffered prefetch; the buffer is dead after use, so
        at most two layers' full params are live). Per-layer inputs are
        saved as the only activation residuals.

        Backward (returned ``pullback(dx, daux)``): a hand-written reverse
        scan that re-gathers each layer's params (prefetched one iteration
        ahead, like ZeRO-3's backward re-fetch), recomputes the block from
        its saved input (layer-granular remat — the only memory-sane choice
        when saved residuals must not contain full params), and carries the
        just-computed full layer gradients so ``scatter`` (reduce-scatter)
        of layer *l*'s grads is issued during layer *l−1*'s backward
        compute. Gradients come back dp-sharded, fp32, dp-averaged.

        ``layers_per_step=2`` is the half-remat ('alternating') variant's
        shape: the schedule pipelines two-layer bundles — half the
        collective launches (bigger buckets) and half the saved boundary
        activations, at the same per-layer recompute.

        ``prefetch_depth=2`` (ISSUE 11; the overlap planner derives it
        when the committed map still shows exposed in-scan bytes at
        depth 1) TRIPLE-buffers the gather prefetch: the carry holds TWO
        gathered layers and iteration *l* issues layer *l+2*'s gather,
        giving each all-gather two layers of compute to hide under — at
        the cost of one more layer's full params live. Applies to the
        forward prefetch and the backward re-gather; the grad
        reduce-scatter stays one-behind (grads exist only after their
        layer's backward — there is nothing to deepen). Clamped to 1
        when fewer than 3 steps (a deeper carry would only re-gather the
        final step). Depth 1 is byte-identical to the pre-ISSUE-11
        schedule.

        ``comm_scope(k)`` (optional) is entered around each scan so the
        comm layer can account its in-body collectives as executing ``k``
        times per step (a scan body traces once but launches per
        iteration) — the engine passes the TreeComm's ``trace_executions``.
        ``comm_edge(overlapped)`` (optional) is entered around the
        pipeline-EDGE launches — the forward prologue gather and the
        epilogue grad flush, which have no compute to hide under — so
        they are recorded exposed rather than inheriting the tree's
        blanket class; the engine passes ``TreeComm.schedule_class``.

        ``scatter_err`` (optional; the overlap planner's error-feedback
        carry, runtime/overlap_planner.py) is a pytree whose leaves have
        a leading ``n_steps`` dim: per-step quantization residual state
        for ``scatter``. When provided, ``scatter(tree, err=slice)``
        must return ``(tree, new_err)``; step *s*'s slice rides the
        backward scan's xs/ys (the launch at reverse iteration *s*
        scatters step *s+1*'s grads, so xs carry ``scatter_err[1:]`` and
        the epilogue flush consumes slot 0) and ``pullback`` returns the
        updated stack as a THIRD element — the engine threads it through
        the micro-step carry so residuals telescope across accumulation
        steps (docs/COLLECTIVES.md "Error feedback").

        Returns ``(x_out, moe_aux_sum, pullback)``.
        """
        import contextlib
        scope = comm_scope or (lambda k: contextlib.nullcontext())
        edge = comm_edge or (lambda overlapped: contextlib.nullcontext())
        c = self.config
        L = c.num_layers
        lps = int(layers_per_step)
        if lps < 1 or L % lps:
            raise ValueError(f"layers_per_step={lps} must divide "
                             f"num_layers={L}")
        n_steps = L // lps
        keep = (jnp.ones((L,), c.dtype) if keep is None
                else keep.astype(c.dtype))
        windows = (jnp.asarray(self._windows, jnp.int32)
                   if self._windows is not None else None)
        bundle = lambda a: a.reshape((n_steps, lps) + a.shape[1:])
        blocksb = jax.tree.map(bundle, blocks)
        keepb = bundle(keep)
        winb = bundle(windows) if windows is not None else None
        take = lambda t, i: jax.tree.map(lambda a: a[i], t)

        def unit_call(bp, xx, kb, wb):
            aux = jnp.zeros((), jnp.float32)
            for j in range(lps):
                blk = jax.tree.map(lambda a: a[j], bp)
                w = None if wb is None else wb[j]
                xx, a = self.block_apply(blk, xx, positions, keep=kb[j],
                                         attn_mask=attn_mask, window=w)
                aux = aux + a
            return xx, aux

        depth = int(prefetch_depth)
        if depth < 1:
            raise ValueError(f"prefetch_depth={depth} must be >= 1")
        # a deeper carry needs >= 3 steps (at 2 every deep slot would
        # just re-gather the final step); the executor implements 1 and 2
        depth = 1 if n_steps <= 2 else min(depth, 2)

        if depth == 1:
            # xs slot s prefetches step s+1's shard; the last slot
            # re-gathers the final step, seeding the backward's first
            # full buffer for free
            nxt = jax.tree.map(
                lambda a: jnp.concatenate([a[1:], a[-1:]], axis=0), blocksb)
        else:
            # depth 2: xs slot s prefetches step s+2's shard (the last
            # two slots re-gather the final step — same seeding)
            nxt = jax.tree.map(
                lambda a: jnp.concatenate([a[2:], a[-1:], a[-1:]], axis=0),
                blocksb)
        xs = {"shard": nxt, "keep": keepb}
        if winb is not None:
            xs["win"] = winb
        with edge(False):  # prologue: nothing runs yet to hide it
            pf0 = gather(take(blocksb, 0))
            pf1 = gather(take(blocksb, 1)) if depth == 2 else None

        if depth == 1:
            def fwd_body(carry, xs_s):
                xx, pf, aux_acc = carry
                nf = gather(xs_s["shard"])  # independent of compute below
                y, aux = unit_call(pf, xx, xs_s["keep"], xs_s.get("win"))
                return (y, nf, aux_acc + aux), xx

            with scope(n_steps):
                (x_out, pf_last, aux_sum), acts = jax.lax.scan(
                    fwd_body, (x, pf0, jnp.zeros((), jnp.float32)), xs)
        else:
            def fwd_body(carry, xs_s):
                xx, pf_a, pf_b, aux_acc = carry
                nf = gather(xs_s["shard"])  # two steps ahead
                y, aux = unit_call(pf_a, xx, xs_s["keep"], xs_s.get("win"))
                return (y, pf_b, nf, aux_acc + aux), xx

            with scope(n_steps):
                (x_out, pf_last, _, aux_sum), acts = jax.lax.scan(
                    fwd_body, (x, pf0, pf1, jnp.zeros((), jnp.float32)),
                    xs)

        # error-feedback carry plumbing: without scatter_err the scatter
        # call and the return arity are EXACTLY the pre-planner form
        if scatter_err is None:
            scat = lambda t, e: (scatter(t), None)
            take_err = lambda i: None
        else:
            scat = lambda t, e: scatter(t, err=e)
            take_err = lambda i: jax.tree.map(lambda a: a[i], scatter_err)

        def pullback(dx_out, daux):
            daux_ = jnp.asarray(daux, jnp.float32)
            wb_last = None if winb is None else winb[-1]
            # peel the last step: its full params came out of the forward
            # scan's final carry, so no zero-valued first scatter and no
            # branch inside the reverse scan
            _, vjp_last = jax.vjp(
                lambda p, xx: unit_call(p, xx, keepb[-1], wb_last),
                pf_last, acts[-1])
            dp, dx = vjp_last((dx_out, daux_))
            unbundle = lambda t: jax.tree.map(
                lambda a: a.reshape((L,) + a.shape[2:]), t)
            if n_steps == 1:
                with edge(False):  # epilogue flush: step's last launch
                    ds0, ne0 = scat(dp, take_err(0))
                dblocks = unbundle(jax.tree.map(lambda a: a[None], ds0))
                if scatter_err is None:
                    return dblocks, dx
                return dblocks, dx, jax.tree.map(lambda a: a[None], ne0)
            pb0 = gather(take(blocksb, n_steps - 2))
            if depth == 1:
                # reverse prefetch: slot s carries step s-1's shard (slot
                # 0 a dead self-gather — the price of one scan body shape)
                prv = jax.tree.map(
                    lambda a: jnp.concatenate([a[:1], a[:-1]],
                                              axis=0)[:n_steps - 1],
                    blocksb)
            else:
                # depth 2: slot s carries step s-2's shard (slots 0/1
                # dead clamp-gathers; depth >= 2 implies n_steps >= 3)
                prv = jax.tree.map(
                    lambda a: jnp.concatenate([a[:1], a[:1], a[:-2]],
                                              axis=0)[:n_steps - 1],
                    blocksb)
            pb1 = (gather(take(blocksb, n_steps - 3))
                   if depth == 2 else None)
            xs_b = {"shard": prv, "act": acts[:n_steps - 1],
                    "keep": keepb[:n_steps - 1]}
            if winb is not None:
                xs_b["win"] = winb[:n_steps - 1]
            if scatter_err is not None:
                # reverse iteration s scatters step s+1's grads, so its
                # xs slot carries residual stack slice [1:]; slot 0 is
                # the epilogue flush's
                xs_b["err"] = jax.tree.map(lambda a: a[1:], scatter_err)

            if depth == 1:
                def bwd_body(carry, xs_s):
                    dxx, pb, pending = carry
                    # layer l+1's grads reduce-scatter while layer l
                    # computes
                    ds_prev, ne = scat(pending, xs_s.get("err"))
                    nb = gather(xs_s["shard"])
                    _, vjp_f = jax.vjp(
                        lambda p, xx: unit_call(p, xx, xs_s["keep"],
                                                xs_s.get("win")),
                        pb, xs_s["act"])
                    dp_s, dxx_new = vjp_f((dxx, daux_))
                    return (dxx_new, nb, dp_s), (ds_prev, ne)

                with scope(n_steps - 1):
                    (dx0, _, pending0), (ds_stack, ne_stack) = jax.lax.scan(
                        bwd_body, (dx, pb0, dp), xs_b, reverse=True)
            else:
                def bwd_body(carry, xs_s):
                    dxx, pb_a, pb_b, pending = carry
                    ds_prev, ne = scat(pending, xs_s.get("err"))
                    nb = gather(xs_s["shard"])  # two steps behind
                    _, vjp_f = jax.vjp(
                        lambda p, xx: unit_call(p, xx, xs_s["keep"],
                                                xs_s.get("win")),
                        pb_a, xs_s["act"])
                    dp_s, dxx_new = vjp_f((dxx, daux_))
                    return (dxx_new, pb_b, nb, dp_s), (ds_prev, ne)

                with scope(n_steps - 1):
                    (dx0, _, _, pending0), (ds_stack, ne_stack) = \
                        jax.lax.scan(bwd_body, (dx, pb0, pb1, dp), xs_b,
                                     reverse=True)
            with edge(False):  # epilogue: flush step 0's grads, exposed
                ds0, ne0 = scat(pending0, take_err(0))
            # ds_stack[s] holds step s+1's sharded grads; step 0 is ds0
            dblocksb = jax.tree.map(
                lambda h, t: jnp.concatenate([h[None], t], axis=0),
                ds0, ds_stack)
            if scatter_err is None:
                return unbundle(dblocksb), dx0
            new_err = jax.tree.map(
                lambda h, t: jnp.concatenate([h[None], t], axis=0),
                ne0, ne_stack)
            return unbundle(dblocksb), dx0, new_err

        return x_out, aux_sum, pullback

    def apply(self, params: Params, input_ids: jax.Array,
              layer_mask: Optional[jax.Array] = None,
              token_type_ids: Optional[jax.Array] = None,
              attention_mask: Optional[jax.Array] = None,
              return_hidden: bool = False) -> Tuple[jax.Array, jax.Array]:
        """Return (logits [B,S,V] in fp32, moe_aux_loss scalar).

        ``layer_mask`` [num_layers] gates each block (PLD stochastic depth).
        ``token_type_ids`` [B,S] selects bert segment embeddings;
        ``attention_mask`` [B,S] (1 = real) masks padding in encoders.
        ``return_hidden`` short-circuits before the LM/MLM head, returning
        the final hidden states [B,S,H] (post final-norm) — the hook task
        heads (models/heads.py) build on.
        """
        c = self.config
        x, positions = self.embed(params, input_ids, token_type_ids)

        block_fn = functools.partial(self._block_fn, attention_mask)
        alternating = c.remat and c.remat_policy == "alternating"
        if c.remat and not alternating:
            policy = None
            if c.remat_policy == "attention_only":
                # recompute ONLY the [B, H, S, S] attention buffers (named
                # "attn_big" in ops/transformer/attention.py) — ~1% extra
                # FLOPs instead of full remat's 33%, while removing exactly
                # the buffers whose no-remat residuals blow compile memory
                # at bert/gpt2 bench dims. NOTE: only the XLA attention
                # path names those tensors. Under the in-repo Pallas flash
                # kernel (ops/transformer/pallas_flash.py) no S^2 buffer
                # exists to recompute: the kernel's custom-VJP residuals
                # are O(S) — q/k/v, the output, and the row LSE — and this
                # save-everything-else policy saves exactly those, so the
                # backward re-runs only the blockwise tile recomputation
                # already priced into the flash backward. The LSE residual
                # REPLACES the attn_big checkpoint: same memory contract
                # (no quadratic residual), enforced by the kernel instead
                # of the remat namer.
                policy = jax.checkpoint_policies \
                    .save_anything_except_these_names("attn_big")
            elif c.remat_policy and c.remat_policy not in ("full",
                                                           "nothing_saveable"):
                policy = getattr(jax.checkpoint_policies, c.remat_policy)
            block_fn = jax.checkpoint(block_fn, policy=policy)

        if layer_mask is None:
            keep = jnp.ones((c.num_layers,), c.dtype)
        else:
            keep = layer_mask.astype(c.dtype)
        xs = (params["blocks"], keep)
        if self._windows is not None:
            xs = xs + (jnp.asarray(self._windows, jnp.int32),)
        init = (x, positions, jnp.zeros((), jnp.float32))
        if alternating:
            # HALF-remat: scan over layer pairs, checkpointing only the
            # first of each pair — the backward recomputes every other
            # layer (half the recompute FLOPs of full remat) while the
            # scan stores residuals for only half the layers (half the
            # activation memory of no remat). The sweet spot when full
            # activations don't fit but full recompute over-pays.
            ck_fn = jax.checkpoint(block_fn)

            def pair_fn(carry, xs_pair):
                carry, _ = ck_fn(carry, jax.tree.map(lambda a: a[0], xs_pair))
                carry, _ = block_fn(carry, jax.tree.map(lambda a: a[1], xs_pair))
                return carry, None

            n_pairs = c.num_layers // 2
            xs_even = jax.tree.map(
                lambda a: a[:n_pairs * 2].reshape((n_pairs, 2) + a.shape[1:]),
                xs)
            (x, _, aux), _ = jax.lax.scan(pair_fn, init, xs_even)
            if c.num_layers % 2:  # odd depth: last layer, checkpointed
                (x, _, aux), _ = ck_fn(
                    (x, positions, aux),
                    jax.tree.map(lambda a: a[-1], xs))
        else:
            (x, _, aux), _ = jax.lax.scan(block_fn, init, xs)
        if return_hidden:
            if self._ln_f is not None:
                x = self._ln_f(params["ln_f"], x)
            return x, aux
        return self.head(params, x), aux

    # The three loss ingredients are separate methods because the ZeRO
    # overlap schedule (engine._build_zeropp_micro_overlap) composes the
    # loss around its own embed/blocks/head vjp pipeline — both schedules
    # MUST share these definitions or `overlap_comm` would silently change
    # the training objective.
    def derive_labels(self, batch: Dict[str, jax.Array]) -> jax.Array:
        """Explicit labels, or the causal next-token shift (-100 = ignore)."""
        labels = batch.get("labels")
        if labels is not None:
            return labels
        if not self.config.causal:
            raise ValueError("encoder (MLM) training requires explicit "
                             "labels — next-token shift is meaningless "
                             "bidirectionally")
        return jnp.pad(batch["input_ids"][:, 1:], ((0, 0), (0, 1)),
                       constant_values=-100)

    def head_loss(self, params: Params, x: jax.Array, labels: jax.Array,
                  extra_mask: Optional[jax.Array] = None) -> jax.Array:
        """Final norm + LM/MLM head + masked cross-entropy over the last
        block's output (the differentiated tail of the overlap schedule)."""
        return masked_cross_entropy(self.head(params, x), labels,
                                    extra_mask=extra_mask)

    def combine_aux(self, loss: jax.Array, aux: jax.Array) -> jax.Array:
        """Fold the accumulated MoE aux loss into the objective."""
        if self.config.moe is not None:
            loss = loss + self.config.moe.aux_loss_coef * aux / self.config.num_layers
        return loss

    def loss(self, params: Params, batch: Dict[str, jax.Array]) -> jax.Array:
        """Cross-entropy: next-token for causal LMs (labels derived by shift
        when absent), masked-LM for encoders (labels required, -100 = ignore).
        batch: input_ids [B,S], optional labels/loss_mask/token_type_ids/
        attention_mask."""
        labels = self.derive_labels(batch)
        logits, aux = self.apply(params, batch["input_ids"],
                                 layer_mask=batch.get("layer_mask"),
                                 token_type_ids=batch.get("token_type_ids"),
                                 attention_mask=batch.get("attention_mask"))
        loss = masked_cross_entropy(logits, labels,
                                    extra_mask=batch.get("loss_mask"))
        return self.combine_aux(loss, aux)
