"""OPT / Phi / Falcon presets.

Counterpart of the reference's per-arch inference implementations
(``inference/v2/model_implementations/{opt,phi,falcon}``): the same
decoder family expressed through ``TransformerConfig`` knobs —

- **OPT**   (opt/model.py): ReLU MLP, learned positions with the HF +2
  padding offset, tied embeddings, pre-LN.
- **Phi**   (phi/model.py): PARALLEL attention+MLP from one LayerNorm,
  partial rotary (rope over the first rotary_dim of each head), biased
  lm_head, untied embeddings.
- **Falcon** (falcon/model.py): parallel block, rope, LayerNorm with
  BIAS-FREE linears, multi-query / grouped KV attention, tied embeddings.
"""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig, TransformerLM

_OPT_PRESETS = {
    "opt-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                     intermediate_size=256, max_seq_len=64, vocab_size=256),
    "opt-125m": dict(num_layers=12, num_heads=12, hidden_size=768,
                     intermediate_size=3072, max_seq_len=2048),
    "opt-1.3b": dict(num_layers=24, num_heads=32, hidden_size=2048,
                     intermediate_size=8192, max_seq_len=2048),
    "opt-6.7b": dict(num_layers=32, num_heads=32, hidden_size=4096,
                     intermediate_size=16384, max_seq_len=2048),
    "opt-13b": dict(num_layers=40, num_heads=40, hidden_size=5120,
                    intermediate_size=20480, max_seq_len=2048),
    "opt-30b": dict(num_layers=48, num_heads=56, hidden_size=7168,
                    intermediate_size=28672, max_seq_len=2048),
}

_PHI_PRESETS = {
    "phi-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                     intermediate_size=256, max_seq_len=64, vocab_size=256,
                     rope_dim=8),
    "phi-1_5": dict(num_layers=24, num_heads=32, hidden_size=2048,
                    intermediate_size=8192, max_seq_len=2048, vocab_size=51200,
                    rope_dim=32),
    "phi-2": dict(num_layers=32, num_heads=32, hidden_size=2560,
                  intermediate_size=10240, max_seq_len=2048, vocab_size=51200,
                  rope_dim=32),
}

_FALCON_PRESETS = {
    "falcon-tiny": dict(num_layers=2, num_heads=4, num_kv_heads=1,
                        hidden_size=64, intermediate_size=256,
                        max_seq_len=64, vocab_size=256),
    "falcon-7b": dict(num_layers=32, num_heads=71, num_kv_heads=1,
                      hidden_size=4544, intermediate_size=18176,
                      max_seq_len=2048, vocab_size=65024),
    "falcon-40b": dict(num_layers=60, num_heads=128, num_kv_heads=8,
                       hidden_size=8192, intermediate_size=32768,
                       max_seq_len=2048, vocab_size=65024,
                       parallel_norms=True),
}


def opt_config(preset: str = "opt-125m", dtype=jnp.bfloat16,
               **overrides) -> TransformerConfig:
    base = dict(vocab_size=50272, activation="relu", norm="layernorm",
                position="learned", position_offset=2, tie_embeddings=True,
                dtype=dtype)
    base.update(_OPT_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def opt_model(preset: str = "opt-125m", **overrides) -> TransformerLM:
    return TransformerLM(opt_config(preset, **overrides))


def phi_config(preset: str = "phi-2", dtype=jnp.bfloat16,
               **overrides) -> TransformerConfig:
    base = dict(activation="gelu", norm="layernorm", position="rope",
                parallel_block=True, tie_embeddings=False, lm_head_bias=True,
                dtype=dtype)
    base.update(_PHI_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def phi_model(preset: str = "phi-2", **overrides) -> TransformerLM:
    return TransformerLM(phi_config(preset, **overrides))


def falcon_config(preset: str = "falcon-7b", dtype=jnp.bfloat16,
                  **overrides) -> TransformerConfig:
    base = dict(activation="gelu_exact", norm="layernorm", position="rope",
                parallel_block=True, linear_bias=False, tie_embeddings=True,
                dtype=dtype)
    base.update(_FALCON_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def falcon_model(preset: str = "falcon-7b", **overrides) -> TransformerLM:
    return TransformerLM(falcon_config(preset, **overrides))
