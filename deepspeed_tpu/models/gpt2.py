"""GPT-2 presets — the `configs[0]` model of BASELINE.json."""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig, TransformerLM

_PRESETS = {
    "gpt2-tiny": dict(num_layers=2, num_heads=4, hidden_size=128, max_seq_len=256, vocab_size=1024),
    "gpt2-125m": dict(num_layers=12, num_heads=12, hidden_size=768, max_seq_len=1024),
    "gpt2-medium": dict(num_layers=24, num_heads=16, hidden_size=1024, max_seq_len=1024),
    "gpt2-large": dict(num_layers=36, num_heads=20, hidden_size=1280, max_seq_len=1024),
    "gpt2-xl": dict(num_layers=48, num_heads=25, hidden_size=1600, max_seq_len=1024),
}


def gpt2_config(preset: str = "gpt2-125m", dtype=jnp.float32, **overrides) -> TransformerConfig:
    base = dict(
        vocab_size=50257,
        activation="gelu",
        norm="layernorm",
        position="learned",
        tie_embeddings=True,
        dtype=dtype,
    )
    base.update(_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def gpt2_model(preset: str = "gpt2-125m", **overrides) -> TransformerLM:
    return TransformerLM(gpt2_config(preset, **overrides))
