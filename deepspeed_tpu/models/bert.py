"""BERT-family encoder presets (BERT / RoBERTa).

Counterpart of the reference's encoder kernel-injection policies
(``module_inject/containers/bert.py``, ``distil_bert.py``; HF bert/roberta
dominate the reference inference test matrix, ``tests/unit/inference/
test_inference.py:62``) and its "fastest BERT training" kernel stack
(``csrc/transformer``, ``docs/_posts/2020-05-28-fastest-bert-training.md``).

Expressed through ``TransformerConfig``: bidirectional attention
(``causal=False``), post-LN blocks, learned positions + segment embeddings
with an embedding LayerNorm, and the MLM prediction head (dense → act → LN →
tied decoder + bias). RoBERTa is the same body with its +2 position-padding
offset.
"""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig, TransformerLM

_BERT_PRESETS = {
    "bert-tiny": dict(num_layers=2, num_heads=4, hidden_size=64,
                      intermediate_size=256, max_seq_len=64, vocab_size=256),
    "bert-base": dict(num_layers=12, num_heads=12, hidden_size=768,
                      intermediate_size=3072),
    "bert-large": dict(num_layers=24, num_heads=16, hidden_size=1024,
                       intermediate_size=4096),
}


def bert_config(preset: str = "bert-base", dtype=jnp.bfloat16,
                **overrides) -> TransformerConfig:
    base = dict(vocab_size=30522, max_seq_len=512, activation="gelu_exact",
                norm="layernorm", position="learned", causal=False,
                norm_style="post", embedding_norm=True, type_vocab_size=2,
                mlm_head=True, tie_embeddings=True, dtype=dtype)
    base.update(_BERT_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def bert_model(preset: str = "bert-base", **overrides) -> TransformerLM:
    return TransformerLM(bert_config(preset, **overrides))


def roberta_config(preset: str = "bert-base", dtype=jnp.bfloat16,
                   **overrides) -> TransformerConfig:
    """RoBERTa: bert body, vocab 50265, ONE token type, and HF's pad-aware
    position ids (cumsum over non-pad tokens + padding_idx, so padded
    batches match ``create_position_ids_from_input_ids`` exactly)."""
    base = dict(vocab_size=50265, max_seq_len=512, activation="gelu_exact",
                norm="layernorm", position="learned", position_offset=2,
                pad_based_positions=True, pad_token_id=1,
                causal=False, norm_style="post", embedding_norm=True,
                type_vocab_size=1, mlm_head=True, tie_embeddings=True,
                dtype=dtype)
    base.update(_BERT_PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


def roberta_model(preset: str = "bert-base", **overrides) -> TransformerLM:
    return TransformerLM(roberta_config(preset, **overrides))
