"""FLOPs profiler.

Counterpart of the reference ``profiling/flops_profiler/profiler.py``
(``FlopsProfiler`` :28): per-step FLOPs/params/latency reporting. The
reference monkey-patches torch functional ops and walks module hooks; on TPU
the compiler already knows — ``jax.jit(...).lower(...).compile().cost_analysis()``
returns XLA's exact FLOPs/bytes estimate for the compiled program, including
fusion effects the hook-based approach cannot see.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax

from ...utils.logging import log_dist


def get_model_profile(fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """Compile ``fn(*args)`` and return {'flops', 'bytes_accessed', 'params'}.

    The reference's ``get_model_profile`` (profiler.py:1100+) runs hooks over
    a forward; here the lowered XLA computation is the ground truth.
    """
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # some backends wrap in a list
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
        "utilization_hint": cost,
    }


class FlopsProfiler:
    """Engine-attached profiler (reference profiler.py:28).

    Used by the engine at ``flops_profiler.profile_step``: measures one
    train step's wall time and pairs it with XLA's static cost analysis.
    """

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self.started = False
        self._t0 = 0.0
        self.flops = 0.0
        self.latency = 0.0

    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        jax.effects_barrier()
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        if not self.started:
            return
        jax.effects_barrier()
        self.latency = time.perf_counter() - self._t0

    def get_total_flops(self, as_string: bool = False):
        flops = self.flops
        return _num_to_string(flops) + "FLOPs" if as_string else flops

    def get_total_duration(self, as_string: bool = False):
        return _duration_to_string(self.latency) if as_string else self.latency

    def get_total_params(self, as_string: bool = False):
        n = 0
        if self.ds_engine is not None:
            n = sum(x.size for x in jax.tree.leaves(self.ds_engine.state["params"]))
        elif self.model is not None and hasattr(self.model, "config"):
            n = self.model.config.num_parameters()
        return _num_to_string(n) if as_string else n

    def set_flops(self, flops: float) -> None:
        self.flops = flops

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        tflops = self.flops / max(self.latency, 1e-9) / 1e12
        msg = (f"flops profiler @ step {profile_step}: params={self.get_total_params(True)}, "
               f"fwd+bwd flops={self.get_total_flops(True)}, latency="
               f"{self.get_total_duration(True)}, achieved={tflops:.2f} TFLOPS")
        if output_file:
            with open(output_file, "a") as f:
                f.write(msg + "\n")
        else:
            log_dist(msg, ranks=[0])

    def end_profile(self) -> None:
        self.started = False


def _num_to_string(num: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num / div:.2f} {unit}"
    return f"{num:.0f} "


def _duration_to_string(seconds: float) -> str:
    if seconds >= 1:
        return f"{seconds:.2f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds * 1e6:.2f} us"
