"""Communication frontend.

TPU-native counterpart of ``deepspeed/comm/comm.py``: the reference wraps
torch.distributed (NCCL) with a backend-agnostic API plus op-level logging
(``timed_op`` comm.py:101, ``init_distributed`` comm.py:604). Here the
"backend" is XLA itself: collectives are ``jax.lax`` primitives over named
mesh axes, compiled and scheduled by XLA onto ICI/DCN. There is no NCCL
rendezvous; multi-host bootstrap is ``jax.distributed.initialize``.

Two usage contexts:

1. **Inside** ``shard_map``/``pjit`` with named axes — the functions below
   lower to XLA collectives (`psum`, `all_gather`, `psum_scatter`,
   `all_to_all`, `ppermute`). This is the hot path; ops are recorded by the
   ``CommsLogger`` at *trace* time (size/count — wall-time per op is
   meaningless under XLA fusion; use the profiler for that).
2. **Outside** jit, at process level — ``get_rank``/``get_world_size``/
   ``barrier`` operate on jax processes.

The reduce path mirrors the reference semantics: ``ReduceOp.AVG`` divides by
the axis size like ZeRO's ``average_tensor`` (stage_1_and_2.py:1004).
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import os
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.groups import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS
from ..utils.jax_compat import axis_size as _compat_axis_size
from ..utils.logging import logger

AxisNames = Union[str, Sequence[str]]


class ReduceOp(enum.Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


# -- transport planner (ISSUE 8 tentpole) ------------------------------------
#
# Every collective launch resolves through a per-bucket TransportPlan:
# wire WIDTH (full | bf16 | int8 | fp8) chosen from the tensor KIND
# (param / grad / activation) and bucket bytes, and ALGORITHM (flat |
# hierarchical) chosen from the mesh topology — two-tier decomposition
# when the axis tuple spans the DCN-eligible 'data' axis plus intra-slice
# ICI axes (*The Big Send-off*, arXiv:2504.18658: intra-ICI reduce-scatter
# + inter-tier reduction on the 1/n shard), EQuARX-style
# quantize->reduce->dequantize with per-group scales for the low-precision
# widths (arXiv:2506.17615). Full rules: docs/COLLECTIVES.md.

WIDTH_FULL = "full"
WIDTH_BF16 = "bf16"
WIDTH_INT8 = "int8"
WIDTH_FP8 = "fp8"
ALGO_FLAT = "flat"
ALGO_HIERARCHICAL = "hierarchical"

KIND_PARAM = "param"
KIND_GRAD = "grad"
KIND_ACTIVATION = "activation"

_WIDTHS = (WIDTH_FULL, WIDTH_BF16, WIDTH_INT8, WIDTH_FP8)
_KINDS = (KIND_PARAM, KIND_GRAD, KIND_ACTIVATION)

#: process-global transport policy (engine config block ``comm_transport``
#: lands here via :func:`configure_transport`; tests/tools flip the env
#: gates). ``DSTPU_COMM_QUANT=0`` is the kill switch: planner DEFAULTS
#: escape to full width (explicitly-requested widths — the ZeRO++
#: qwZ/qgZ config knobs — are a user contract and keep riding).
#: ``DSTPU_COMM_HIER=0`` pins the flat algorithm.
_TRANSPORT_DEFAULTS = dict(
    enabled=True,
    grad_width=WIDTH_INT8,          # gradient reductions (EF-compensable)
    activation_width=WIDTH_BF16,    # MoE dispatch / seq all-to-all resharding
    permute_width=WIDTH_INT8,       # ring KV hops (explicit sideband scales)
    hierarchical=True,
    group_size=256,
    min_bytes=1024,                 # buckets below this stay full width
    error_feedback=False,           # costs one fp32 copy of each grad bucket
)
_TRANSPORT = dict(_TRANSPORT_DEFAULTS)

#: widths each collective op can move. Reductions need sideband scales
#: (int8/fp8 quantize->sum); pure data movement can also plain-cast
#: (bf16). Unsupported requests degrade to the nearest supported width
#: rather than erroring — the plan is a performance policy, not an API.
_OP_WIDTHS = {
    "all_reduce": (WIDTH_FULL, WIDTH_INT8, WIDTH_FP8),
    "reduce_scatter": (WIDTH_FULL, WIDTH_INT8, WIDTH_FP8),
    "all_gather": (WIDTH_FULL, WIDTH_BF16, WIDTH_INT8, WIDTH_FP8),
    "all_to_all": (WIDTH_FULL, WIDTH_BF16),
    "ppermute": (WIDTH_FULL, WIDTH_BF16, WIDTH_INT8),
}
_WIDTH_FALLBACK = {
    ("all_reduce", WIDTH_BF16): WIDTH_FULL,
    ("reduce_scatter", WIDTH_BF16): WIDTH_FULL,
    ("all_to_all", WIDTH_INT8): WIDTH_BF16,
    ("all_to_all", WIDTH_FP8): WIDTH_BF16,
    ("ppermute", WIDTH_FP8): WIDTH_INT8,
}


def configure_transport(**kwargs) -> None:
    """Set process-global transport policy (engine ``comm_transport``
    config block). Unknown keys or widths raise — a typo'd policy must
    not silently revert to defaults."""
    for key, val in kwargs.items():
        if key not in _TRANSPORT_DEFAULTS:
            raise ValueError(
                f"unknown comm_transport key {key!r} "
                f"(known: {', '.join(sorted(_TRANSPORT_DEFAULTS))})")
        if key.endswith("_width") and val not in _WIDTHS:
            raise ValueError(f"comm_transport.{key}={val!r} not in {_WIDTHS}")
        _TRANSPORT[key] = val


def transport_config() -> dict:
    return dict(_TRANSPORT)


def reset_transport() -> None:
    _TRANSPORT.clear()
    _TRANSPORT.update(_TRANSPORT_DEFAULTS)


def _quant_defaults_on() -> bool:
    return _TRANSPORT["enabled"] and os.environ.get(
        "DSTPU_COMM_QUANT", "1") != "0"


def _hier_on() -> bool:
    return _TRANSPORT["hierarchical"] and os.environ.get(
        "DSTPU_COMM_HIER", "1") != "0"


@dataclasses.dataclass(frozen=True)
class TransportPlan:
    """How one collective launch moves its bytes. ``inner``/``outer``
    are the hierarchical tiers (intra-slice ICI axes / the DCN-eligible
    'data' axis); empty under the flat algorithm."""
    width: str = WIDTH_FULL
    algo: str = ALGO_FLAT
    inner: Tuple[str, ...] = ()
    outer: Tuple[str, ...] = ()
    group_size: int = 256
    error_feedback: bool = False

    @property
    def quantized(self) -> bool:
        return self.width in (WIDTH_INT8, WIDTH_FP8)

    def wire_bytes(self, n_elems: int, itemsize: int) -> int:
        """Estimated bytes on the wire for an ``n_elems`` payload whose
        logical element width is ``itemsize`` — what
        :func:`record_collective`'s ``wire_bytes`` column carries so the
        overlap ledger stays honest under quantized transport. Sideband
        scale/zero arrays are charged; the hierarchical outer leg adds
        its full-width 1/n_inner shard."""
        groups = -(-n_elems // max(self.group_size, 1))
        if self.width == WIDTH_INT8:
            base = n_elems + groups * 8       # int8 payload + f32 scale/zero
        elif self.width == WIDTH_FP8:
            base = n_elems + groups * 4       # fp8 payload + f32 scale
        elif self.width == WIDTH_BF16:
            base = n_elems * min(2, itemsize)
        else:
            base = n_elems * itemsize
        if self.algo == ALGO_HIERARCHICAL and self.inner:
            ni = 1
            for a in self.inner:
                ni *= _transport_axis_size(a)
            base += (n_elems // max(ni, 1)) * 4   # full-width outer leg
        return int(base)


FULL_FLAT_PLAN = TransportPlan()


def _transport_axis_size(axis) -> int:
    """Axis size for planning: the global topology when initialized (host
    side), the bound mesh axis inside shard_map otherwise. Unknown -> 1
    (treated as a dead axis; the plan degrades to flat/full, never
    crashes a trace)."""
    from ..runtime import topology as topo_mod
    if topo_mod.is_initialized():
        try:
            return topo_mod.get_topology().axis_size(axis)
        except (KeyError, TypeError):
            pass
    try:
        return int(_compat_axis_size(axis))
    except (NameError, KeyError, ValueError, TypeError):
        return 1


def resolve_transport(kind: Optional[str], op: str, nbytes: int,
                      axes: AxisNames, axis_sizes: Optional[dict] = None,
                      requested: Optional[str] = None) -> TransportPlan:
    """Resolve one launch's :class:`TransportPlan`.

    ``kind`` is the tensor kind (``param``/``grad``/``activation``;
    ``None`` = unclassified traffic, always full/flat — generic frontend
    callers keep their exact pre-planner behavior). ``requested`` is an
    explicit width contract (the ZeRO++ qwZ/qgZ config knobs) that
    survives the ``DSTPU_COMM_QUANT=0`` kill switch; planner *defaults*
    do not. ``axis_sizes`` supplies host-known mesh sizes (the bucket
    planner's dict); otherwise sizes come from the topology/bound mesh.
    """
    if kind is None and requested is None:
        return FULL_FLAT_PLAN     # unclassified traffic: exact pre-planner path
    axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
    size_of = (axis_sizes.get if axis_sizes is not None
               else lambda a, _=None: _transport_axis_size(a))
    live = tuple(a for a in axes_t if (size_of(a, 1) or 1) > 1)

    width = requested if requested in _WIDTHS else WIDTH_FULL
    if (requested is None and kind in _KINDS and _quant_defaults_on()
            and nbytes >= _TRANSPORT["min_bytes"]):
        if kind == KIND_GRAD:
            width = _TRANSPORT["grad_width"]
        elif kind == KIND_ACTIVATION:
            width = (_TRANSPORT["permute_width"] if op == "ppermute"
                     else _TRANSPORT["activation_width"])
        # KIND_PARAM default stays full: the param all-gather width is the
        # user's qwZ contract (zero_quantized_weights -> requested="int8")
    while width not in _OP_WIDTHS.get(op, (WIDTH_FULL,)):
        width = _WIDTH_FALLBACK.get((op, width), WIDTH_FULL)

    algo, inner, outer = ALGO_FLAT, (), ()
    if (op in ("all_reduce", "reduce_scatter", "all_gather")
            and _quant_defaults_on() and _hier_on()):
        out_axes = tuple(a for a in live if a == DATA_AXIS)
        in_axes = tuple(a for a in live if a != DATA_AXIS)
        if out_axes and in_axes:
            algo, inner, outer = ALGO_HIERARCHICAL, in_axes, out_axes
    return TransportPlan(width=width, algo=algo, inner=inner, outer=outer,
                         group_size=_TRANSPORT["group_size"],
                         error_feedback=(bool(_TRANSPORT["error_feedback"])
                                         and kind == KIND_GRAD))


_INITIALIZED = False
_COMMS_LOGGER = None  # set by configure()


def _telemetry():
    """The process-global telemetry (NULL object when disabled) — comm
    records feed its trace/overlap metrics alongside the CommsLogger."""
    from ..telemetry import get_telemetry
    return get_telemetry()


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host communication (reference comm.py:604).

    Single-host (including a single TPU slice visible to one process) needs no
    rendezvous. Multi-host pods are detected via the standard coordinator env
    vars and use ``jax.distributed.initialize`` over DCN — this replaces the
    reference's MASTER_ADDR/NCCL bootstrap and ``mpi_discovery``
    (comm.py:673), which TPU metadata makes unnecessary.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    if coord:
        kwargs = {"coordinator_address": coord}
        if world_size > 0:
            kwargs["num_processes"] = world_size
        elif os.environ.get("JAX_NUM_PROCESSES"):
            kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if rank >= 0:
            kwargs["process_id"] = rank
        elif os.environ.get("JAX_PROCESS_ID"):
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        else:
            # mpirun-launched jobs (reference ``mpi_discovery``, comm.py:673):
            # one command line cannot bake a per-process id, so identity
            # comes from the MPI runtime — OpenMPI's OMPI_COMM_WORLD_RANK,
            # the PMI vars MPICH/Intel MPI set, or MVAPICH's
            # MV2_COMM_WORLD_RANK (mpirun_rsh). Size fallback likewise.
            for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK",
                        "MV2_COMM_WORLD_RANK"):
                if os.environ.get(var):
                    kwargs["process_id"] = int(os.environ[var])
                    break
            if "num_processes" not in kwargs:
                for var in ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
                            "MV2_COMM_WORLD_SIZE"):
                    if os.environ.get(var):
                        kwargs["num_processes"] = int(os.environ[var])
                        break
        jax.distributed.initialize(**kwargs)
        if verbose:
            logger.info(f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}")
    elif verbose:
        logger.info("Single-process communication init (no coordinator address set)")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def configure(config=None, comms_logger=None) -> None:
    """Attach a CommsLogger (reference ``dist.configure``, engine.py:251)."""
    global _COMMS_LOGGER
    if comms_logger is not None:
        _COMMS_LOGGER = comms_logger
        return
    if config is not None and getattr(config, "comms_logger_enabled", False):
        from ..utils.comms_logging import CommsLogger
        _COMMS_LOGGER = CommsLogger(config.comms_config)


def _record(op_name: str, x, axis: AxisNames,
            plan: Optional[TransportPlan] = None) -> None:
    tele = _telemetry()
    if _COMMS_LOGGER is None and not tele.enabled:
        return
    n = int(np.prod(jnp.shape(x)))
    itemsize = jnp.result_type(x).itemsize
    size = n * itemsize
    wire = plan.wire_bytes(n, itemsize) if plan is not None else size
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.append(op_name, size, axis, wire_bytes=wire)
    if tele.enabled:
        tele.record_collective(op_name, size, axis, wire_bytes=wire)


def record_collective(op_name: str, nbytes: int, axis: AxisNames,
                      overlapped: Optional[bool] = None,
                      count: int = 1,
                      wire_bytes: Optional[int] = None) -> None:
    """Record a collective issued through raw ``jax.lax`` primitives (the
    ZeRO micro schedules build their own gathers/scatters) with its
    schedule class: ``overlapped=True`` means the launch is issued
    concurrently with independent compute (the pipelined layer schedule's
    in-scan prefetch/reduce-scatter), ``False`` means it sits on the
    critical path (barrier schedule, edge-of-step gathers). ``count`` is
    the executions-per-step of one trace site (a scan body traces once but
    launches per iteration). ``wire_bytes`` is what actually travels the
    links when the transport plan narrows the width (int8 payload +
    sideband scales); defaults to ``nbytes`` — full-width launches and
    logical accounting agree. Feeds the overlapped/exposed split column of
    :func:`log_summary` and the telemetry trace/overlap-efficiency metric
    (docs/OBSERVABILITY.md). No-op unless a CommsLogger or telemetry is
    configured."""
    wire = int(nbytes) if wire_bytes is None else int(wire_bytes)
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.append(op_name, int(nbytes), axis,
                             overlapped=overlapped, count=count,
                             wire_bytes=wire)
    tele = _telemetry()
    if tele.enabled:
        tele.record_collective(op_name, int(nbytes), axis,
                               overlapped=overlapped, count=count,
                               wire_bytes=wire)


class CollectiveLedger:
    """Minimal CommsLogger-shaped sink: collects ``record_collective``
    calls as dicts. Used (via :func:`record_into`) by the Layer-D parity
    test and ``tools/overlap_report.py`` to capture the runtime
    overlapped/exposed split of one traced step without configuring the
    full telemetry stack."""

    def __init__(self):
        self.records = []

    def append(self, op_name: str, nbytes: int, axis,
               overlapped: Optional[bool] = None, count: int = 1,
               wire_bytes: Optional[int] = None) -> None:
        self.records.append({"op": op_name, "bytes": int(nbytes),
                             "wire_bytes": int(nbytes if wire_bytes is None
                                               else wire_bytes),
                             "axes": tuple(axis) if isinstance(
                                 axis, (tuple, list)) else (axis,),
                             "overlapped": overlapped, "count": int(count)})

    def split(self, wire: bool = True) -> dict:
        """-> {"overlapped_bytes", "exposed_bytes"} (count-scaled;
        untagged records excluded, same as the telemetry metric).
        ``wire=True`` (default) charges WIRE bytes — the convention that
        matches Layer D's static split, which reads actual HLO operand
        bytes and therefore sees quantized payloads at their quantized
        size. ``wire=False`` restores logical full-width accounting."""
        key = "wire_bytes" if wire else "bytes"
        out = {"overlapped_bytes": 0, "exposed_bytes": 0}
        for r in self.records:
            if r["overlapped"] is True:
                out["overlapped_bytes"] += r[key] * r["count"]
            elif r["overlapped"] is False:
                out["exposed_bytes"] += r[key] * r["count"]
        return out

    # the rest of the CommsLogger surface the module-level helpers may
    # call while this ledger is installed (comms_log_tail from the stall
    # watchdog, log_summary) — a diagnostic path must not crash
    def tail(self, n: int = 12) -> str:
        return "\n".join(
            f"{r['op']} {r['bytes']} B axes={r['axes']} "
            f"overlapped={r['overlapped']} x{r['count']}"
            for r in self.records[-n:])

    def log_all(self, show_straggler: bool = False) -> None:
        logger.info(self.tail(len(self.records) or 1))


@contextlib.contextmanager
def record_into(ledger):
    """Temporarily route ``record_collective`` into ``ledger`` (anything
    with a CommsLogger-shaped ``append``), restoring the configured
    logger on exit. Collective records fire at TRACE time, so tracing a
    step under this context captures its full comm schedule without
    executing anything."""
    global _COMMS_LOGGER
    old = _COMMS_LOGGER
    _COMMS_LOGGER = ledger
    try:
        yield ledger
    finally:
        _COMMS_LOGGER = old


def comms_log_tail(n: int = 12) -> str:
    """The last ``n`` recorded collectives, formatted — the watchdog's
    comms dump: when a step stalls, the ops recorded closest to the hang
    point the finger at the wedged collective group."""
    if _COMMS_LOGGER is None:
        return ""
    return _COMMS_LOGGER.tail(n)


# -- process-level queries ---------------------------------------------------

def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return 0  # one process per host on TPU


def barrier(name: str = "deepspeed_tpu_barrier") -> None:
    """Cross-process barrier (reference comm.py barrier): a named psum over
    all global devices via multihost_utils, which blocks every process until
    all have entered."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


# -- hierarchical decompositions (transport planner, algo=hierarchical) ------

def _hier_psum(x, inner: Tuple[str, ...], outer: Tuple[str, ...]):
    """Two-tier all-reduce: reduce-scatter over the intra-tier (ICI) axes,
    all-reduce the 1/n_inner shard over the cross-tier (DCN) axes,
    all-gather back over the intra-tier axes. Cross-tier bytes shrink by
    the inner axis size. Falls back to the flat psum when the element
    count does not tile over the inner axes."""
    ni = axis_size(inner)
    if x.size % ni:
        return jax.lax.psum(x, inner + outer)
    flat = x.reshape(-1)
    part = jax.lax.psum_scatter(flat, inner, scatter_dimension=0, tiled=True)
    part = jax.lax.psum(part, outer)
    full = jax.lax.all_gather(part, inner, axis=0, tiled=True)
    return full.reshape(x.shape)


def _hier_regroup(xm, axes: Tuple[str, ...], inner: Tuple[str, ...],
                  outer: Tuple[str, ...]):
    """Rearrange a destination-major reduce-scatter input ([N*s, ...] in
    the flat compound-axis block order of ``axes``) into inner-major
    block order, so a two-stage scatter (inner then outer) delivers each
    member exactly the block the flat launch would. Size-1 axes in the
    caller's tuple (excluded from the plan's tiers) contribute factor 1
    to the block layout and are dropped from the math — exact."""
    axes = tuple(a for a in axes if a in inner or a in outer)
    sizes = [axis_size(a) for a in axes]
    n = int(np.prod(sizes))
    s = xm.shape[0] // n
    t = xm.reshape(tuple(sizes) + (s,) + xm.shape[1:])
    order = ([axes.index(a) for a in inner] + [axes.index(a) for a in outer]
             + list(range(len(sizes), t.ndim)))
    t = jnp.transpose(t, order)
    return t.reshape((n * s,) + xm.shape[1:])


def _hier_psum_scatter(xm, axes: Tuple[str, ...], inner: Tuple[str, ...],
                       outer: Tuple[str, ...], quantized_inner=None):
    """Two-tier reduce-scatter with the flat launch's output layout:
    stage 1 reduce-scatters over the intra-tier axes (optionally with a
    quantized wire via ``quantized_inner(x, axis)``), stage 2
    reduce-scatters the 1/n_inner partial over the cross-tier axes at
    full width — the Big Send-off split: the DCN tier moves 1/n_inner of
    the bytes. ``xm``: [N*s, ...] destination-major."""
    t = _hier_regroup(xm, axes, inner, outer)
    if quantized_inner is not None:
        part = quantized_inner(t, inner)
    else:
        part = jax.lax.psum_scatter(t, inner, scatter_dimension=0, tiled=True)
    return jax.lax.psum_scatter(part, outer, scatter_dimension=0, tiled=True)


def _hier_all_gather(x, axes: Tuple[str, ...], inner: Tuple[str, ...],
                     outer: Tuple[str, ...]):
    """Two-tier tiled all-gather reproducing the flat compound-axis block
    order of ``axes``: gather over the intra-tier axes, then the
    cross-tier axes, then reorder the (outer, inner) block grid back to
    the flat order. Size-1 axes drop out of the layout math (exact)."""
    axes = tuple(a for a in axes if a in inner or a in outer)
    gi = jax.lax.all_gather(x, inner, axis=0, tiled=False)     # [ni, s, ...]
    go = jax.lax.all_gather(gi, outer, axis=0, tiled=False)    # [no, ni, s,...]
    i_sizes = [axis_size(a) for a in inner]
    o_sizes = [axis_size(a) for a in outer]
    t = go.reshape(tuple(o_sizes) + tuple(i_sizes) + go.shape[2:])
    current = tuple(outer) + tuple(inner)
    order = ([current.index(a) for a in axes]
             + list(range(len(current), t.ndim)))
    t = jnp.transpose(t, order)
    n = int(np.prod(i_sizes)) * int(np.prod(o_sizes))
    return t.reshape((n * x.shape[0],) + x.shape[1:])


# -- in-mesh collectives (call inside shard_map / pjit) ----------------------

def _nbytes(tensor) -> int:
    return int(np.prod(jnp.shape(tensor))) * jnp.result_type(tensor).itemsize


def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, axis: AxisNames = DATA_AXIS,
               group=None, kind: Optional[str] = None):
    """psum/pmax/pmin over named axes (reference comm.py:466 all_reduce).

    ``kind`` routes SUM/AVG through the transport planner: ``grad``
    buckets default to the EQuARX-style quantized all-reduce, compound
    axes spanning 'data' decompose hierarchically. ``kind=None`` (and
    MAX/MIN/PRODUCT always) is the exact full-width psum."""
    plan = (resolve_transport(kind, "all_reduce", _nbytes(tensor), axis)
            if kind is not None and op in (ReduceOp.SUM, ReduceOp.AVG)
            else FULL_FLAT_PLAN)
    _record("all_reduce", tensor, axis, plan=plan)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        if plan.quantized:
            from ..ops.quantizer.quantizer import quantized_all_reduce
            inner = plan.inner if plan.algo == ALGO_HIERARCHICAL else axis
            outer = plan.outer if plan.algo == ALGO_HIERARCHICAL else ()
            out = quantized_all_reduce(tensor, axis=inner, outer=outer,
                                       group_size=plan.group_size,
                                       fp8=plan.width == WIDTH_FP8)
        elif plan.algo == ALGO_HIERARCHICAL:
            out = _hier_psum(tensor, plan.inner, plan.outer)
        else:
            out = jax.lax.psum(tensor, axis)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(tensor, axis)
    raise ValueError(f"Unsupported reduce op {op}")


def all_gather(tensor, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0,
               tiled: bool = True, kind: Optional[str] = None):
    """Concatenate shards along ``tensor_axis`` (reference all_gather_into_tensor,
    comm.py:308). ``kind='param'`` resolves the width through the
    transport planner (explicit qwZ requests ride ``ops/quantizer``)."""
    plan = (resolve_transport(kind, "all_gather", _nbytes(tensor), axis)
            if kind is not None and tiled else FULL_FLAT_PLAN)
    _record("all_gather", tensor, axis, plan=plan)
    if plan is FULL_FLAT_PLAN or plan == FULL_FLAT_PLAN:
        return jax.lax.all_gather(tensor, axis, axis=tensor_axis, tiled=tiled)
    xm = jnp.moveaxis(tensor, tensor_axis, 0)
    if plan.quantized:
        from ..ops.quantizer.quantizer import (fp8_all_gather,
                                               quantized_all_gather)
        g = (fp8_all_gather(xm, axis, plan.group_size)
             if plan.width == WIDTH_FP8
             else quantized_all_gather(xm, axis, group_size=plan.group_size))
    else:
        wire = xm.astype(jnp.bfloat16) if plan.width == WIDTH_BF16 else xm
        if plan.algo == ALGO_HIERARCHICAL:
            axes_t = (axis,) if isinstance(axis, str) else tuple(axis)
            g = _hier_all_gather(wire, axes_t, plan.inner, plan.outer)
        else:
            g = jax.lax.all_gather(wire, axis, axis=0, tiled=True)
        g = g.astype(tensor.dtype)
    return jnp.moveaxis(g, 0, tensor_axis)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM,
                   axis: AxisNames = DATA_AXIS, scatter_axis: int = 0,
                   kind: Optional[str] = None):
    """Sum then scatter shards (reference reduce_scatter_tensor, comm.py:257).

    ``kind='grad'`` resolves through the transport planner: int8/fp8
    widths take the ZeRO++ qgZ wire (quantize -> all-to-all -> local
    sum), compound axes spanning 'data' decompose into intra-tier
    reduce-scatter + cross-tier reduce-scatter on the 1/n shard."""
    plan = (resolve_transport(kind, "reduce_scatter", _nbytes(tensor), axis)
            if kind is not None and op in (ReduceOp.SUM, ReduceOp.AVG)
            else FULL_FLAT_PLAN)
    _record("reduce_scatter", tensor, axis, plan=plan)
    if plan is FULL_FLAT_PLAN or plan == FULL_FLAT_PLAN:
        out = jax.lax.psum_scatter(tensor, axis,
                                   scatter_dimension=scatter_axis, tiled=True)
    else:
        from ..ops.quantizer.quantizer import (fp8_reduce_scatter,
                                               quantized_reduce_scatter)
        q_inner = None
        if plan.width == WIDTH_FP8:
            q_inner = lambda x, ax: fp8_reduce_scatter(
                x, ax, group_size=plan.group_size)
        elif plan.width == WIDTH_INT8:
            q_inner = lambda x, ax: quantized_reduce_scatter(
                x, ax, group_size=plan.group_size)
        xm = jnp.moveaxis(tensor, scatter_axis, 0)
        if plan.algo == ALGO_HIERARCHICAL:
            axes_t = (axis,) if isinstance(axis, str) else tuple(axis)
            r = _hier_psum_scatter(xm, axes_t, plan.inner, plan.outer,
                                   quantized_inner=q_inner)
        elif q_inner is not None:
            r = q_inner(xm, axis)
        else:
            r = jax.lax.psum_scatter(xm, axis, scatter_dimension=0,
                                     tiled=True)
        out = jnp.moveaxis(r, 0, scatter_axis)
    if op == ReduceOp.AVG:
        out = out / axis_size(axis)
    return out


def all_to_all(tensor, axis: AxisNames = SEQ_AXIS, split_axis: int = 0,
               concat_axis: int = 0, kind: Optional[str] = None):
    """All-to-all resharding (reference all_to_all_single, comm.py:388) — the
    primitive behind Ulysses sequence parallelism and MoE dispatch.
    ``kind='activation'`` narrows the wire to bf16 (a pure-movement cast;
    the receive side restores the logical dtype)."""
    plan = (resolve_transport(kind, "all_to_all", _nbytes(tensor), axis)
            if kind is not None else FULL_FLAT_PLAN)
    _record("all_to_all", tensor, axis, plan=plan)
    wire = tensor
    if plan.width == WIDTH_BF16 and tensor.dtype.itemsize > 2:
        # NOTE: TPU backends move this natively at bf16; the CPU audit
        # backend LEGALIZES a bf16 all-to-all back to an f32 wire
        # wrapped in converts (values still bf16-rounded), so the
        # committed Layer-D maps charge these launches full width —
        # the ledger's wire_bytes column carries the plan's real wire
        wire = tensor.astype(jnp.bfloat16)
    out = jax.lax.all_to_all(wire, axis, split_axis=split_axis,
                             concat_axis=concat_axis, tiled=True)
    return out.astype(tensor.dtype) if wire is not tensor else out


def broadcast(tensor, src: int = 0, axis: AxisNames = DATA_AXIS):
    """Broadcast from ``src`` index along axis (reference comm.py:221).

    all_gather + static index: one gather's bandwidth ((n-1)/n · size per
    link) where a masked psum would pay a full ring allreduce (~2x), and
    the static slice lets XLA elide the unused shards.
    """
    _record("broadcast", tensor, axis)
    return jax.lax.all_gather(tensor, axis)[src]


def ppermute(tensor, perm, axis: AxisNames = PIPE_AXIS,
             kind: Optional[str] = None):
    """Point-to-point ring/permutation transfer — the TPU equivalent of the
    reference's pipeline ``p2p.send/recv`` (runtime/pipe/p2p.py:50,71).

    ``kind='activation'`` narrows the hop's wire per the transport plan:
    int8 quantizes before the permute and dequantizes after — one
    (re-)quantization PER HOP, so a value rotated around a ring of sp
    members is re-rounded sp times (ring attention accepts this: the
    per-hop straight-through VJP is what keeps K/V trainable, see
    ops/quantizer.quantized_ppermute and sequence/ring_attention.py);
    bf16 is a plain cast."""
    plan = (resolve_transport(kind, "ppermute", _nbytes(tensor), axis)
            if kind is not None else FULL_FLAT_PLAN)
    _record("ppermute", tensor, axis, plan=plan)
    if plan.width == WIDTH_INT8:
        from ..ops.quantizer.quantizer import quantized_ppermute
        return quantized_ppermute(tensor, perm, axis,
                                  group_size=plan.group_size)
    if plan.width == WIDTH_BF16 and tensor.dtype.itemsize > 2:
        return jax.lax.ppermute(tensor.astype(jnp.bfloat16), axis,
                                perm).astype(tensor.dtype)
    return jax.lax.ppermute(tensor, axis, perm)


def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM,
           axis: AxisNames = DATA_AXIS):
    """Reduce toward ``dst`` (reference comm.py reduce). SPMD has no cheap
    rooted reduce — every device computes the psum; non-dst members get
    zeros so the contract (result valid only on dst) still holds and XLA
    can dead-code the unused copies."""
    _record("reduce", tensor, axis)
    # jax.lax directly, not all_reduce(): the frontend wrapper would
    # _record a second (phantom) op in the CommsLogger
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(tensor, axis)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis)
    elif op == ReduceOp.MAX:
        out = jax.lax.pmax(tensor, axis)
    elif op == ReduceOp.MIN:
        out = jax.lax.pmin(tensor, axis)
    else:
        raise ValueError(f"Unsupported reduce op {op}")
    return jnp.where(jax.lax.axis_index(axis) == dst, out,
                     jnp.zeros_like(out))


def gather(tensor, dst: int = 0, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0):
    """Gather shards to ``dst`` (reference comm.py gather): all_gather with
    the same only-valid-on-dst contract (zeros elsewhere)."""
    _record("gather", tensor, axis)
    out = jax.lax.all_gather(tensor, axis, axis=tensor_axis, tiled=True)
    return jnp.where(jax.lax.axis_index(axis) == dst, out,
                     jnp.zeros_like(out))


def scatter(tensor, src: int = 0, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0):
    """Scatter ``src``'s shards across the axis (reference comm.py scatter):
    broadcast from src, then each member takes its static slice."""
    _record("scatter", tensor, axis)
    n = axis_size(axis)
    if tensor.shape[tensor_axis] % n:
        # torch.distributed raises on uneven scatter too — truncating
        # would silently drop the tail elements
        raise ValueError(
            f"scatter: dim {tensor_axis} ({tensor.shape[tensor_axis]}) "
            f"is not divisible by the {axis!r} axis size {n}")
    # jax.lax directly (broadcast() would double-_record in the logger)
    full = jax.lax.all_gather(tensor, axis)[src]
    k = full.shape[tensor_axis] // n
    idx = jax.lax.axis_index(axis) * k
    return jax.lax.dynamic_slice_in_dim(full, idx, k, axis=tensor_axis)


def all_to_all_single(tensor, axis: AxisNames = SEQ_AXIS, split_axis: int = 0,
                      concat_axis: int = 0):
    """Alias of :func:`all_to_all` (reference all_to_all_single,
    comm.py:388 — the tensor-form API)."""
    return all_to_all(tensor, axis=axis, split_axis=split_axis,
                      concat_axis=concat_axis)


def send(tensor, dst: int, axis: AxisNames = PIPE_AXIS):
    """Rooted two-sided p2p has no XLA/SPMD primitive — every device runs
    the same program, so transfers are expressed as permutations. Rejected
    loudly rather than silently mis-mapped (reference pipe p2p.send)."""
    raise NotImplementedError(
        "two-sided send does not exist under SPMD; express the transfer "
        "as a permutation with comm.ppermute(tensor, perm, axis) — e.g. "
        "pipeline next-stage transfer: perm=[(i, i+1), ...]")


def recv(tensor, src: int, axis: AxisNames = PIPE_AXIS):
    """See :func:`send` — same story in the receive direction (reference
    pipe p2p.recv signature: (tensor, src))."""
    raise NotImplementedError(
        "two-sided recv does not exist under SPMD; the matching ppermute "
        "on every member IS the receive — comm.ppermute(tensor, perm, "
        "axis) delivers each member the value permuted to its index")


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False,
                      timeout_s: float = 300.0,
                      name: str = "dstpu_monitored_barrier") -> None:
    """Barrier that names the stragglers instead of hanging silently
    (reference comm.py monitored_barrier): waits in a helper thread and
    logs every ``timeout_s`` with the barrier name until it completes.

    ``group``/``timeout``/``wait_all_ranks`` mirror the reference signature
    for drop-in callers: group is accepted and ignored (the XLA barrier is
    global), ``timeout`` (seconds or datetime.timedelta) aliases
    ``timeout_s``, and wait_all_ranks is moot — the watchdog never raises,
    it reports while continuing to wait."""
    if timeout is not None:
        timeout_s = float(getattr(timeout, "total_seconds", lambda: timeout)())
    if jax.process_count() <= 1:
        return
    import threading
    done = threading.Event()

    def watchdog():
        waited = 0.0
        while not done.wait(timeout_s):
            waited += timeout_s
            logger.warning(
                f"monitored_barrier '{name}': process {get_rank()} still "
                f"waiting after {waited:.0f}s — a peer has not arrived")

    t = threading.Thread(target=watchdog, daemon=True)
    t.start()
    try:
        barrier(name)
    finally:
        done.set()


def axis_index(axis: AxisNames):
    return jax.lax.axis_index(axis)


def axis_size(axis: AxisNames) -> int:
    return _compat_axis_size(axis)


def inference_all_reduce(tensor, axis: AxisNames = MODEL_AXIS):
    """Low-latency TP allreduce (reference comm.py:500) — same psum on TPU;
    XLA already picks the latency-optimal ICI algorithm."""
    _record("inference_all_reduce", tensor, axis)
    return jax.lax.psum(tensor, axis)


def log_summary(show_straggler: bool = False):
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.log_all(show_straggler=show_straggler)
