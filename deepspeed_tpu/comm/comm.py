"""Communication frontend.

TPU-native counterpart of ``deepspeed/comm/comm.py``: the reference wraps
torch.distributed (NCCL) with a backend-agnostic API plus op-level logging
(``timed_op`` comm.py:101, ``init_distributed`` comm.py:604). Here the
"backend" is XLA itself: collectives are ``jax.lax`` primitives over named
mesh axes, compiled and scheduled by XLA onto ICI/DCN. There is no NCCL
rendezvous; multi-host bootstrap is ``jax.distributed.initialize``.

Two usage contexts:

1. **Inside** ``shard_map``/``pjit`` with named axes — the functions below
   lower to XLA collectives (`psum`, `all_gather`, `psum_scatter`,
   `all_to_all`, `ppermute`). This is the hot path; ops are recorded by the
   ``CommsLogger`` at *trace* time (size/count — wall-time per op is
   meaningless under XLA fusion; use the profiler for that).
2. **Outside** jit, at process level — ``get_rank``/``get_world_size``/
   ``barrier`` operate on jax processes.

The reduce path mirrors the reference semantics: ``ReduceOp.AVG`` divides by
the axis size like ZeRO's ``average_tensor`` (stage_1_and_2.py:1004).
"""

from __future__ import annotations

import contextlib
import enum
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.groups import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS
from ..utils.jax_compat import axis_size as _compat_axis_size
from ..utils.logging import logger

AxisNames = Union[str, Sequence[str]]


class ReduceOp(enum.Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


_INITIALIZED = False
_COMMS_LOGGER = None  # set by configure()


def _telemetry():
    """The process-global telemetry (NULL object when disabled) — comm
    records feed its trace/overlap metrics alongside the CommsLogger."""
    from ..telemetry import get_telemetry
    return get_telemetry()


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host communication (reference comm.py:604).

    Single-host (including a single TPU slice visible to one process) needs no
    rendezvous. Multi-host pods are detected via the standard coordinator env
    vars and use ``jax.distributed.initialize`` over DCN — this replaces the
    reference's MASTER_ADDR/NCCL bootstrap and ``mpi_discovery``
    (comm.py:673), which TPU metadata makes unnecessary.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    if coord:
        kwargs = {"coordinator_address": coord}
        if world_size > 0:
            kwargs["num_processes"] = world_size
        elif os.environ.get("JAX_NUM_PROCESSES"):
            kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if rank >= 0:
            kwargs["process_id"] = rank
        elif os.environ.get("JAX_PROCESS_ID"):
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        else:
            # mpirun-launched jobs (reference ``mpi_discovery``, comm.py:673):
            # one command line cannot bake a per-process id, so identity
            # comes from the MPI runtime — OpenMPI's OMPI_COMM_WORLD_RANK,
            # the PMI vars MPICH/Intel MPI set, or MVAPICH's
            # MV2_COMM_WORLD_RANK (mpirun_rsh). Size fallback likewise.
            for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK",
                        "MV2_COMM_WORLD_RANK"):
                if os.environ.get(var):
                    kwargs["process_id"] = int(os.environ[var])
                    break
            if "num_processes" not in kwargs:
                for var in ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
                            "MV2_COMM_WORLD_SIZE"):
                    if os.environ.get(var):
                        kwargs["num_processes"] = int(os.environ[var])
                        break
        jax.distributed.initialize(**kwargs)
        if verbose:
            logger.info(f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}")
    elif verbose:
        logger.info("Single-process communication init (no coordinator address set)")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def configure(config=None, comms_logger=None) -> None:
    """Attach a CommsLogger (reference ``dist.configure``, engine.py:251)."""
    global _COMMS_LOGGER
    if comms_logger is not None:
        _COMMS_LOGGER = comms_logger
        return
    if config is not None and getattr(config, "comms_logger_enabled", False):
        from ..utils.comms_logging import CommsLogger
        _COMMS_LOGGER = CommsLogger(config.comms_config)


def _record(op_name: str, x, axis: AxisNames) -> None:
    tele = _telemetry()
    if _COMMS_LOGGER is None and not tele.enabled:
        return
    size = int(np.prod(jnp.shape(x))) * jnp.result_type(x).itemsize
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.append(op_name, size, axis)
    if tele.enabled:
        tele.record_collective(op_name, size, axis)


def record_collective(op_name: str, nbytes: int, axis: AxisNames,
                      overlapped: Optional[bool] = None,
                      count: int = 1) -> None:
    """Record a collective issued through raw ``jax.lax`` primitives (the
    ZeRO micro schedules build their own gathers/scatters) with its
    schedule class: ``overlapped=True`` means the launch is issued
    concurrently with independent compute (the pipelined layer schedule's
    in-scan prefetch/reduce-scatter), ``False`` means it sits on the
    critical path (barrier schedule, edge-of-step gathers). ``count`` is
    the executions-per-step of one trace site (a scan body traces once but
    launches per iteration). Feeds the overlapped/exposed split column of
    :func:`log_summary` and the telemetry trace/overlap-efficiency metric
    (docs/OBSERVABILITY.md). No-op unless a CommsLogger or telemetry is
    configured."""
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.append(op_name, int(nbytes), axis,
                             overlapped=overlapped, count=count)
    tele = _telemetry()
    if tele.enabled:
        tele.record_collective(op_name, int(nbytes), axis,
                               overlapped=overlapped, count=count)


class CollectiveLedger:
    """Minimal CommsLogger-shaped sink: collects ``record_collective``
    calls as dicts. Used (via :func:`record_into`) by the Layer-D parity
    test and ``tools/overlap_report.py`` to capture the runtime
    overlapped/exposed split of one traced step without configuring the
    full telemetry stack."""

    def __init__(self):
        self.records = []

    def append(self, op_name: str, nbytes: int, axis,
               overlapped: Optional[bool] = None, count: int = 1) -> None:
        self.records.append({"op": op_name, "bytes": int(nbytes),
                             "axes": tuple(axis) if isinstance(
                                 axis, (tuple, list)) else (axis,),
                             "overlapped": overlapped, "count": int(count)})

    def split(self) -> dict:
        """-> {"overlapped_bytes", "exposed_bytes"} (count-scaled;
        untagged records excluded, same as the telemetry metric)."""
        out = {"overlapped_bytes": 0, "exposed_bytes": 0}
        for r in self.records:
            if r["overlapped"] is True:
                out["overlapped_bytes"] += r["bytes"] * r["count"]
            elif r["overlapped"] is False:
                out["exposed_bytes"] += r["bytes"] * r["count"]
        return out

    # the rest of the CommsLogger surface the module-level helpers may
    # call while this ledger is installed (comms_log_tail from the stall
    # watchdog, log_summary) — a diagnostic path must not crash
    def tail(self, n: int = 12) -> str:
        return "\n".join(
            f"{r['op']} {r['bytes']} B axes={r['axes']} "
            f"overlapped={r['overlapped']} x{r['count']}"
            for r in self.records[-n:])

    def log_all(self, show_straggler: bool = False) -> None:
        logger.info(self.tail(len(self.records) or 1))


@contextlib.contextmanager
def record_into(ledger):
    """Temporarily route ``record_collective`` into ``ledger`` (anything
    with a CommsLogger-shaped ``append``), restoring the configured
    logger on exit. Collective records fire at TRACE time, so tracing a
    step under this context captures its full comm schedule without
    executing anything."""
    global _COMMS_LOGGER
    old = _COMMS_LOGGER
    _COMMS_LOGGER = ledger
    try:
        yield ledger
    finally:
        _COMMS_LOGGER = old


def comms_log_tail(n: int = 12) -> str:
    """The last ``n`` recorded collectives, formatted — the watchdog's
    comms dump: when a step stalls, the ops recorded closest to the hang
    point the finger at the wedged collective group."""
    if _COMMS_LOGGER is None:
        return ""
    return _COMMS_LOGGER.tail(n)


# -- process-level queries ---------------------------------------------------

def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return 0  # one process per host on TPU


def barrier(name: str = "deepspeed_tpu_barrier") -> None:
    """Cross-process barrier (reference comm.py barrier): a named psum over
    all global devices via multihost_utils, which blocks every process until
    all have entered."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


# -- in-mesh collectives (call inside shard_map / pjit) ----------------------

def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, axis: AxisNames = DATA_AXIS, group=None):
    """psum/pmax/pmin over named axes (reference comm.py:466 all_reduce)."""
    _record("all_reduce", tensor, axis)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(tensor, axis)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(tensor, axis)
    raise ValueError(f"Unsupported reduce op {op}")


def all_gather(tensor, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0, tiled: bool = True):
    """Concatenate shards along ``tensor_axis`` (reference all_gather_into_tensor,
    comm.py:308)."""
    _record("all_gather", tensor, axis)
    return jax.lax.all_gather(tensor, axis, axis=tensor_axis, tiled=tiled)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, axis: AxisNames = DATA_AXIS, scatter_axis: int = 0):
    """Sum then scatter shards (reference reduce_scatter_tensor, comm.py:257)."""
    _record("reduce_scatter", tensor, axis)
    out = jax.lax.psum_scatter(tensor, axis, scatter_dimension=scatter_axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / axis_size(axis)
    return out


def all_to_all(tensor, axis: AxisNames = SEQ_AXIS, split_axis: int = 0, concat_axis: int = 0):
    """All-to-all resharding (reference all_to_all_single, comm.py:388) — the
    primitive behind Ulysses sequence parallelism and MoE dispatch."""
    _record("all_to_all", tensor, axis)
    return jax.lax.all_to_all(tensor, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, axis: AxisNames = DATA_AXIS):
    """Broadcast from ``src`` index along axis (reference comm.py:221).

    all_gather + static index: one gather's bandwidth ((n-1)/n · size per
    link) where a masked psum would pay a full ring allreduce (~2x), and
    the static slice lets XLA elide the unused shards.
    """
    _record("broadcast", tensor, axis)
    return jax.lax.all_gather(tensor, axis)[src]


def ppermute(tensor, perm, axis: AxisNames = PIPE_AXIS):
    """Point-to-point ring/permutation transfer — the TPU equivalent of the
    reference's pipeline ``p2p.send/recv`` (runtime/pipe/p2p.py:50,71)."""
    _record("ppermute", tensor, axis)
    return jax.lax.ppermute(tensor, axis, perm)


def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM,
           axis: AxisNames = DATA_AXIS):
    """Reduce toward ``dst`` (reference comm.py reduce). SPMD has no cheap
    rooted reduce — every device computes the psum; non-dst members get
    zeros so the contract (result valid only on dst) still holds and XLA
    can dead-code the unused copies."""
    _record("reduce", tensor, axis)
    # jax.lax directly, not all_reduce(): the frontend wrapper would
    # _record a second (phantom) op in the CommsLogger
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(tensor, axis)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis)
    elif op == ReduceOp.MAX:
        out = jax.lax.pmax(tensor, axis)
    elif op == ReduceOp.MIN:
        out = jax.lax.pmin(tensor, axis)
    else:
        raise ValueError(f"Unsupported reduce op {op}")
    return jnp.where(jax.lax.axis_index(axis) == dst, out,
                     jnp.zeros_like(out))


def gather(tensor, dst: int = 0, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0):
    """Gather shards to ``dst`` (reference comm.py gather): all_gather with
    the same only-valid-on-dst contract (zeros elsewhere)."""
    _record("gather", tensor, axis)
    out = jax.lax.all_gather(tensor, axis, axis=tensor_axis, tiled=True)
    return jnp.where(jax.lax.axis_index(axis) == dst, out,
                     jnp.zeros_like(out))


def scatter(tensor, src: int = 0, axis: AxisNames = DATA_AXIS, tensor_axis: int = 0):
    """Scatter ``src``'s shards across the axis (reference comm.py scatter):
    broadcast from src, then each member takes its static slice."""
    _record("scatter", tensor, axis)
    n = axis_size(axis)
    if tensor.shape[tensor_axis] % n:
        # torch.distributed raises on uneven scatter too — truncating
        # would silently drop the tail elements
        raise ValueError(
            f"scatter: dim {tensor_axis} ({tensor.shape[tensor_axis]}) "
            f"is not divisible by the {axis!r} axis size {n}")
    # jax.lax directly (broadcast() would double-_record in the logger)
    full = jax.lax.all_gather(tensor, axis)[src]
    k = full.shape[tensor_axis] // n
    idx = jax.lax.axis_index(axis) * k
    return jax.lax.dynamic_slice_in_dim(full, idx, k, axis=tensor_axis)


def all_to_all_single(tensor, axis: AxisNames = SEQ_AXIS, split_axis: int = 0,
                      concat_axis: int = 0):
    """Alias of :func:`all_to_all` (reference all_to_all_single,
    comm.py:388 — the tensor-form API)."""
    return all_to_all(tensor, axis=axis, split_axis=split_axis,
                      concat_axis=concat_axis)


def send(tensor, dst: int, axis: AxisNames = PIPE_AXIS):
    """Rooted two-sided p2p has no XLA/SPMD primitive — every device runs
    the same program, so transfers are expressed as permutations. Rejected
    loudly rather than silently mis-mapped (reference pipe p2p.send)."""
    raise NotImplementedError(
        "two-sided send does not exist under SPMD; express the transfer "
        "as a permutation with comm.ppermute(tensor, perm, axis) — e.g. "
        "pipeline next-stage transfer: perm=[(i, i+1), ...]")


def recv(tensor, src: int, axis: AxisNames = PIPE_AXIS):
    """See :func:`send` — same story in the receive direction (reference
    pipe p2p.recv signature: (tensor, src))."""
    raise NotImplementedError(
        "two-sided recv does not exist under SPMD; the matching ppermute "
        "on every member IS the receive — comm.ppermute(tensor, perm, "
        "axis) delivers each member the value permuted to its index")


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False,
                      timeout_s: float = 300.0,
                      name: str = "dstpu_monitored_barrier") -> None:
    """Barrier that names the stragglers instead of hanging silently
    (reference comm.py monitored_barrier): waits in a helper thread and
    logs every ``timeout_s`` with the barrier name until it completes.

    ``group``/``timeout``/``wait_all_ranks`` mirror the reference signature
    for drop-in callers: group is accepted and ignored (the XLA barrier is
    global), ``timeout`` (seconds or datetime.timedelta) aliases
    ``timeout_s``, and wait_all_ranks is moot — the watchdog never raises,
    it reports while continuing to wait."""
    if timeout is not None:
        timeout_s = float(getattr(timeout, "total_seconds", lambda: timeout)())
    if jax.process_count() <= 1:
        return
    import threading
    done = threading.Event()

    def watchdog():
        waited = 0.0
        while not done.wait(timeout_s):
            waited += timeout_s
            logger.warning(
                f"monitored_barrier '{name}': process {get_rank()} still "
                f"waiting after {waited:.0f}s — a peer has not arrived")

    t = threading.Thread(target=watchdog, daemon=True)
    t.start()
    try:
        barrier(name)
    finally:
        done.set()


def axis_index(axis: AxisNames):
    return jax.lax.axis_index(axis)


def axis_size(axis: AxisNames) -> int:
    return _compat_axis_size(axis)


def inference_all_reduce(tensor, axis: AxisNames = MODEL_AXIS):
    """Low-latency TP allreduce (reference comm.py:500) — same psum on TPU;
    XLA already picks the latency-optimal ICI algorithm."""
    _record("inference_all_reduce", tensor, axis)
    return jax.lax.psum(tensor, axis)


def log_summary(show_straggler: bool = False):
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.log_all(show_straggler=show_straggler)
