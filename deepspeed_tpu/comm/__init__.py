from .comm import (CollectiveLedger, ReduceOp, TransportPlan,  # noqa: F401
                   all_gather, all_reduce, all_to_all, all_to_all_single,
                   axis_index, axis_size, barrier, broadcast, comms_log_tail,
                   configure, configure_transport, gather, get_local_rank,
                   get_rank, get_world_size, inference_all_reduce,
                   init_distributed, is_initialized, log_summary,
                   monitored_barrier, ppermute, record_collective,
                   record_into, recv, reduce, reduce_scatter,
                   reset_transport, resolve_transport, scatter, send,
                   transport_config)
