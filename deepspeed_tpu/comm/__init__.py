from .comm import (ReduceOp, all_gather, all_reduce, all_to_all, axis_index,  # noqa: F401
                   axis_size, barrier, broadcast, configure, get_local_rank,
                   get_rank, get_world_size, inference_all_reduce, init_distributed,
                   is_initialized, log_summary, ppermute, reduce_scatter)
