"""Multi-host launcher.

Counterpart of the reference ``deepspeed/launcher/runner.py`` (``main``
:388: hostfile parsing, resource filters, PDSH/MPI/SLURM runners) and
``launch.py`` (:132: per-rank ``Popen`` + signal fan-out).

TPU redesign: a TPU pod slice runs ONE process per host and JAX discovers
peers via the TPU metadata service, so the reference's per-GPU rank spawning
and NCCL rendezvous vanish. What remains and is implemented here:

- hostfile / include-exclude resource filtering (same syntax:
  ``host:slot1,slot2@host2``) for DCN (multi-slice / CPU cluster) launches;
- environment propagation (.deepspeed_env equivalent);
- per-host remote execution over ssh (the PDSH-style runner);
- local single-host exec (the common TPU-VM case) with signal forwarding.

CLI: ``python -m deepspeed_tpu.launcher.runner [args] script.py ...`` or the
``bin/dstpu`` wrapper.
"""

from __future__ import annotations

import argparse
import os
import re
import shlex
import signal
import subprocess
import sys
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHON", "PATH", "LD_LIBRARY", "JAX", "XLA", "TPU", "DSTPU"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile listing 'hostname slots=N' per line")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="nodes to include: 'host1@host2' or 'host1:0,1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="nodes to exclude (same syntax)")
    parser.add_argument("--master_addr", type=str, default="",
                        help="coordinator address (defaults to first host)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "local", "popen", "slurm",
                                 "openmpi", "mpich", "impi", "pdsh",
                                 "mvapich"],
                        help="remote exec method ('popen' spawns one local "
                             "process per hostfile entry — the reference "
                             "launch.py per-rank spawner, for single-host "
                             "multi-process runs; 'slurm' emits one srun "
                             "step, one task per node; 'openmpi'/'mpich'/"
                             "'impi' emit one mpirun with one task per node "
                             "— rank identity comes from the MPI runtime's "
                             "OMPI_COMM_WORLD_RANK / PMI_RANK)")
    parser.add_argument("--slurm_args", type=str, default="",
                        help="extra arguments spliced into the srun command "
                             "(e.g. '--partition=tpu --time=2:00:00')")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra arguments spliced into the mpirun "
                             "command (openmpi/mpich/impi/pdsh/mvapich launchers)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--elastic_training", action="store_true",
                        help="supervise workers through the elastic agent: "
                             "restart (shrinking the world if needed) on "
                             "failure instead of tearing the job down")
    parser.add_argument("--max_elastic_restarts", type=int, default=3)
    parser.add_argument("--elastic_checkpoint_dir", type=str, default="",
                        help="checkpoint dir threaded to elastic workers "
                             "(DSTPU_ELASTIC): every (re)started world "
                             "resumes from the last committed tag there")
    parser.add_argument("--elastic_restart_backoff", type=float, default=1.0,
                        help="base seconds of exponential backoff between "
                             "elastic restarts (0 disables)")
    parser.add_argument("--deepspeed_config", type=str, default="",
                        help="ds_config json (elastic agent reads its "
                             "elasticity section)")
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse 'hostname slots=N' lines (reference runner.py:200)."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: Dict[str, int] = {}
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)", line)
            if m is None:
                raise ValueError(f"Malformed hostfile line: '{line}'")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"Duplicate host {host} in hostfile")
            resource_pool[host] = slots
    return resource_pool


def _parse_inclusion_exclusion(resource_pool: Dict[str, int], inclusion: str,
                               exclusion: str) -> Dict[str, List[int]]:
    """Reference runner.py:255 parse_resource_filter."""
    active: Dict[str, List[int]] = {k: list(range(v)) for k, v in resource_pool.items()}
    if inclusion:
        included: Dict[str, List[int]] = {}
        for node in inclusion.split("@"):
            if ":" in node:
                host, slots = node.split(":")
                included[host] = [int(s) for s in slots.split(",")]
            else:
                included[node] = active.get(node, [])
            if node.split(":")[0] not in active:
                raise ValueError(f"Included host {node} not in hostfile")
        active = included
    if exclusion:
        for node in exclusion.split("@"):
            if ":" in node:
                host, slots = node.split(":")
                excl = {int(s) for s in slots.split(",")}
                active[host] = [s for s in active.get(host, []) if s not in excl]
            else:
                active.pop(node, None)
        active = {k: v for k, v in active.items() if v}
    return active


def encode_world_info(resource_pool: Dict[str, List[int]]) -> str:
    import base64
    import json
    return base64.urlsafe_b64encode(json.dumps(resource_pool).encode()).decode()


def _collect_env_exports() -> Dict[str, str]:
    exports = {}
    for key, value in os.environ.items():
        if any(key.startswith(prefix) for prefix in EXPORT_ENVS):
            exports[key] = value
    if os.path.isfile(DEEPSPEED_ENVIRONMENT_NAME):
        with open(DEEPSPEED_ENVIRONMENT_NAME) as f:
            for line in f:
                if "=" in line:
                    k, v = line.strip().split("=", 1)
                    exports[k] = v
    return exports


def _spawn_and_forward(cmd: List[str], what: str,
                       env: Optional[Dict[str, str]] = None) -> int:
    """Popen + SIGINT/SIGTERM forwarding + wait — the shared tail of the
    single-child runners (local / srun / mpirun)."""
    logger.info(f"launching {what}: {' '.join(map(shlex.quote, cmd))}")
    proc = subprocess.Popen(cmd, env=env)

    def forward(sig, frame):
        proc.send_signal(sig)

    signal.signal(signal.SIGINT, forward)
    signal.signal(signal.SIGTERM, forward)
    return proc.wait()


def _run_local(args) -> int:
    """Single-host exec with signal forwarding (reference launch.py:249,313)."""
    cmd = [sys.executable, args.user_script] + args.user_args
    return _spawn_and_forward(cmd, "local")


def _install_fan_out(procs: List[subprocess.Popen]) -> None:
    """SIGINT/SIGTERM forward to every child. Installed BEFORE spawning so
    an interrupt mid-spawn cannot orphan already-started ranks (the list
    fills in as children start)."""
    def fan_out(sig, frame):
        for p in procs:
            if p.poll() is None:
                p.send_signal(sig)

    signal.signal(signal.SIGINT, fan_out)
    signal.signal(signal.SIGTERM, fan_out)


def _wait_all(procs: List[subprocess.Popen], poll_s: float = 0.2) -> int:
    """Poll ALL children; on the first failure terminate the rest
    (reference launch.py:313 kill-all-on-any-failure). A sequential
    wait() would deadlock: surviving ranks block in rendezvous/collectives
    for the dead peer and the first wait never returns."""
    import time as _time
    rc = 0
    live = list(procs)
    while live:
        for p in list(live):
            code = p.poll()
            if code is None:
                continue
            live.remove(p)
            if code and not rc:
                rc = code  # FIRST failure's code, not peers' SIGTERM status
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
        if live:
            _time.sleep(poll_s)
    return rc


def _run_popen(args, active: Dict[str, List[int]]) -> int:
    """Per-rank local spawner (reference launch.py:249: ``Popen`` per rank
    with RANK/WORLD env + signal fan-out + kill-all-on-any-failure). One
    process per SLOT across all hostfile entries ('localhost slots=8' →
    8 ranks), rendezvous over localhost."""
    ranks = [(host, slot) for host, slots in active.items() for slot in slots]
    master = args.master_addr or "localhost"
    world_info = encode_world_info(active)
    exports = _collect_env_exports()  # .deepspeed_env parity with _run_ssh
    procs: List[subprocess.Popen] = []
    _install_fan_out(procs)
    for idx, (host, slot) in enumerate(ranks):
        env = dict(os.environ)
        env.update(exports)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"{master}:{args.master_port}",
            "JAX_NUM_PROCESSES": str(len(ranks)),
            "JAX_PROCESS_ID": str(idx),
            "DSTPU_WORLD_INFO": world_info,
        })
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info(f"launching local process {idx}/{len(ranks)} "
                    f"({host} slot {slot})")
        procs.append(subprocess.Popen(cmd, env=env))
    return _wait_all(procs)


def _run_ssh(args, active: Dict[str, List[int]]) -> int:
    """PDSH-style per-host ssh runner (reference multinode_runner.py:51)."""
    hosts = list(active.keys())
    master = args.master_addr or hosts[0]
    exports = _collect_env_exports()
    procs: List[subprocess.Popen] = []
    _install_fan_out(procs)
    world_info = encode_world_info(active)
    for idx, host in enumerate(hosts):
        env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in exports.items())
        remote = (f"{env_str} JAX_COORDINATOR_ADDRESS={master}:{args.master_port} "
                  f"JAX_NUM_PROCESSES={len(hosts)} JAX_PROCESS_ID={idx} "
                  f"DSTPU_WORLD_INFO={world_info} "
                  f"{sys.executable} {args.user_script} "
                  + " ".join(map(shlex.quote, args.user_args)))
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
        logger.info(f"launching on {host} (process {idx}/{len(hosts)})")
        procs.append(subprocess.Popen(cmd))
    return _wait_all(procs)


def build_srun_command(args, active: Dict[str, List[int]],
                       exports: Dict[str, str]) -> List[str]:
    """srun command for a batch-scheduled TPU fleet (reference
    ``SlurmRunner.get_cmd``, multinode_runner.py:117). One task per node —
    a TPU host runs a single JAX process; per-task identity comes from
    SLURM_PROCID/SLURM_NTASKS, which ``jax.distributed.initialize()``
    auto-detects, so no JAX_PROCESS_ID is baked into the command."""
    hosts = sorted(active.keys())
    n = len(hosts)
    master = args.master_addr or hosts[0]
    cmd = ["srun", "--nodes", str(n), "--ntasks", str(n),
           "--ntasks-per-node", "1"]
    synthetic = all(h.startswith("slurm-node-") for h in hosts)
    if hosts and hosts != ["localhost"] and not synthetic:
        # real hostnames pin the step to the hostfile's nodes; the
        # synthetic names main() makes inside an allocation do not exist,
        # so srun places tasks itself there
        cmd += ["--nodelist", ",".join(hosts)]
    if args.slurm_args:
        cmd += shlex.split(args.slurm_args)
    export_kvs = {}
    if args.master_addr or not synthetic:
        export_kvs["JAX_COORDINATOR_ADDRESS"] = f"{master}:{args.master_port}"
    # else: jax.distributed.initialize() derives the coordinator from the
    # SLURM environment (first node of SLURM_JOB_NODELIST)
    export_kvs["DSTPU_WORLD_INFO"] = encode_world_info(active)
    # --export=ALL forwards the whole submitting environment — the
    # collected exports (and .deepspeed_env) are injected into srun's OWN
    # env by _run_slurm, NOT listed here: srun splits the --export list on
    # commas, so values like TPU_PROCESS_BOUNDS=2,2,1 would be truncated.
    # Only the two computed (comma-free) variables ride the list.
    for v in export_kvs.values():
        assert "," not in str(v), f"--export value may not contain commas: {v}"
    cmd += ["--export=" + ",".join(
        ["ALL"] + [f"{k}={v}" for k, v in sorted(export_kvs.items())])]
    cmd += [sys.executable, args.user_script] + args.user_args
    return cmd


def _launch_env_kvs(args, active: Dict[str, List[int]],
                    exports: Dict[str, str]) -> Dict[str, str]:
    """The launch env every multi-node builder ships: collected exports
    minus any leaked JAX_PROCESS_ID (identity must come from the runtime
    or a per-host substitution), plus coordinator/size/world-info."""
    hosts = sorted(active.keys())
    master = args.master_addr or hosts[0]
    env_kvs = dict(exports)
    env_kvs.pop("JAX_PROCESS_ID", None)
    env_kvs["JAX_COORDINATOR_ADDRESS"] = f"{master}:{args.master_port}"
    env_kvs["JAX_NUM_PROCESSES"] = str(len(hosts))
    env_kvs["DSTPU_WORLD_INFO"] = encode_world_info(active)
    return env_kvs


def build_mpirun_command(args, active: Dict[str, List[int]],
                         exports: Dict[str, str]) -> List[str]:
    """mpirun command for MPI-scheduled fleets (reference
    ``OpenMPIRunner``/``MPICHRunner``/``IMPIRunner``,
    multinode_runner.py:18-117). One task per node — a TPU host runs a
    single JAX process. Per-task identity is NOT baked into the command:
    the MPI runtime sets OMPI_COMM_WORLD_RANK (OpenMPI) or PMI_RANK
    (MPICH/Intel MPI), which ``init_distributed`` reads (comm.py
    mpi_discovery parity)."""
    hosts = sorted(active.keys())
    n = len(hosts)
    env_kvs = _launch_env_kvs(args, active, exports)
    if args.launcher == "openmpi":
        # --host h:1 caps one slot per node; -x FOO=bar sets + forwards
        cmd = ["mpirun", "-np", str(n),
               "--host", ",".join(f"{h}:1" for h in hosts),
               "--map-by", "ppr:1:node"]
        if args.launcher_args:
            cmd += shlex.split(args.launcher_args)
        for k, v in sorted(env_kvs.items()):
            cmd += ["-x", f"{k}={v}"]
    else:  # mpich / impi share the hydra CLI: -ppn + -genv K V
        cmd = ["mpirun", "-n", str(n), "-ppn", "1",
               "-hosts", ",".join(hosts)]
        if args.launcher_args:
            cmd += shlex.split(args.launcher_args)
        for k, v in sorted(env_kvs.items()):
            cmd += ["-genv", k, str(v)]
    cmd += [sys.executable, args.user_script] + args.user_args
    return cmd


def build_pdsh_command(args, active: Dict[str, List[int]],
                       exports: Dict[str, str]) -> List[str]:
    """pdsh fan-out (reference ``PDSHRunner.get_cmd``,
    multinode_runner.py:51): ONE pdsh invocation runs the command on every
    host; per-host identity comes from pdsh's ``%n`` substitution (the
    target's 0-based position in the -w list), which becomes
    JAX_PROCESS_ID remotely. ``-S`` propagates the largest remote exit
    code; ``-f 1024`` fans out in parallel."""
    hosts = sorted(active.keys())
    env_kvs = _launch_env_kvs(args, active, exports)

    # pdsh treats % as a substitution char — escape any literal % in
    # values AND in the user command so a stray TPU_…=50% or a user arg
    # like --log-format=%h cannot be rewritten by pdsh
    def pq(v: str) -> str:
        return shlex.quote(str(v)).replace("%", "%%")

    env_str = " ".join(f"{k}={pq(v)}" for k, v in sorted(env_kvs.items()))
    # cd to the launch cwd first: ssh/pdsh land in $HOME, where a relative
    # user_script does not exist (reference PDSHRunner prepends the same)
    remote = (f"cd {pq(os.path.abspath(os.curdir))}; "
              f"{env_str} JAX_PROCESS_ID=%n "
              f"{pq(sys.executable)} {pq(args.user_script)} "
              + " ".join(map(pq, args.user_args))).strip()
    cmd = ["pdsh", "-S", "-f", "1024", "-w", ",".join(hosts)]
    if args.launcher_args:
        cmd += shlex.split(args.launcher_args)
    return cmd + [remote]


def build_mvapich_command(args, active: Dict[str, List[int]],
                          exports: Dict[str, str]) -> List[str]:
    """mpirun_rsh command filling the reference ``MVAPICHRunner`` slot
    (multinode_runner.py:374 — the reference drives hydra mpirun there;
    mpirun_rsh is MVAPICH's own native launcher, with hosts listed
    positionally and env as K=V args before the program). Rank identity
    from MV2_COMM_WORLD_RANK (read by ``init_distributed``'s MPI
    discovery alongside PMI_RANK)."""
    hosts = sorted(active.keys())
    env_kvs = _launch_env_kvs(args, active, exports)
    cmd = ["mpirun_rsh", "-np", str(len(hosts))]
    if args.launcher_args:
        cmd += shlex.split(args.launcher_args)
    cmd += hosts
    # quote EVERYTHING that rides mpirun_rsh's re-serialized ssh command
    # line: env values (XLA_FLAGS='-a -b') and user args ('my run') alike
    cmd += [f"{k}={shlex.quote(str(v))}" for k, v in sorted(env_kvs.items())]
    return (cmd + [sys.executable, shlex.quote(args.user_script)]
            + [shlex.quote(a) for a in args.user_args])


def _run_pdsh(args, active: Dict[str, List[int]]) -> int:
    cmd = build_pdsh_command(args, active, _collect_env_exports())
    # ssh transport: pdsh's compiled-in default rcmd module is often rsh,
    # which no TPU-VM fleet runs (reference PDSHRunner sets the same,
    # multinode_runner.py:74)
    env = dict(os.environ, PDSH_RCMD_TYPE=os.environ.get(
        "PDSH_RCMD_TYPE", "ssh"))
    return _spawn_and_forward(cmd, "pdsh", env=env)


def _run_mvapich(args, active: Dict[str, List[int]]) -> int:
    cmd = build_mvapich_command(args, active, _collect_env_exports())
    env = {k: v for k, v in os.environ.items() if k != "JAX_PROCESS_ID"}
    return _spawn_and_forward(cmd, "mpirun_rsh", env=env)


def _run_mpi(args, active: Dict[str, List[int]]) -> int:
    cmd = build_mpirun_command(args, active, _collect_env_exports())
    # mpirun inherits and propagates its own environment too (hydra fully,
    # OpenMPI to launch-node ranks) — strip the leaked identity there as
    # well, not just from the -genv/-x list
    env = {k: v for k, v in os.environ.items() if k != "JAX_PROCESS_ID"}
    return _spawn_and_forward(cmd, "mpirun", env=env)


def _run_slurm(args, active: Dict[str, List[int]]) -> int:
    exports = _collect_env_exports()
    cmd = build_srun_command(args, active, exports)
    env = dict(os.environ)
    env.update(exports)  # forwarded via --export=ALL, commas intact
    return _spawn_and_forward(cmd, "srun", env=env)


def main(args=None) -> int:
    argv = sys.argv[1:] if args is None else list(args)
    if argv and argv[0] == "lint":
        # `dstpu lint ...` — the static analysis suite, not a launch
        # (AST layer; --jaxpr traces entry points; --spmd compiles them
        # and audits the partitioned artifact against
        # tools/memory_budgets.json — see docs/STATIC_ANALYSIS.md).
        from ..analysis.cli import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] == "plan":
        # `dstpu plan ...` — the Layer-E static config-feasibility oracle
        # (analysis/feasibility.py): compile-and-audit candidate configs
        # without running a step — see docs/STATIC_ANALYSIS.md.
        from ..analysis.feasibility import main as plan_main
        return plan_main(argv[1:])
    if argv and argv[0] == "tune":
        # `dstpu tune ...` — measured autotuning over the oracle's
        # survivors (autotuning/search.py): successive-halving trials to
        # a crash-consistent ledger — see docs/AUTOTUNING.md.
        from ..autotuning.cli import main as tune_main
        return tune_main(argv[1:])
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    if args.elastic_training:
        return _run_elastic(args, resource_pool)
    if args.launcher == "slurm" and not resource_pool:
        # inside an existing allocation: srun infers the node set itself
        n = int(os.environ.get("SLURM_NNODES", "0"))
        if not n:
            raise ValueError(
                "--launcher slurm needs a hostfile or an active SLURM "
                "allocation (SLURM_NNODES)")
        resource_pool = {f"slurm-node-{i}": 1 for i in range(n)}
    if args.launcher in ("openmpi", "mpich", "impi", "pdsh",
                         "mvapich") and not resource_pool:
        # silently degrading the requested multi-host job to one local
        # process would be the worst failure mode
        raise ValueError(f"--launcher {args.launcher} needs a hostfile "
                         f"(none at {args.hostfile})")
    if not resource_pool or args.launcher == "local":
        return _run_local(args)
    active = _parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    if args.launcher == "popen":
        # popen spawns per SLOT — a single-host 'localhost slots=8' entry
        # is its primary use case, so no single-host short-circuit
        return _run_popen(args, active)
    if args.launcher == "slurm":
        return _run_slurm(args, active)
    if args.launcher in ("openmpi", "mpich", "impi"):
        return _run_mpi(args, active)
    if args.launcher == "pdsh":
        return _run_pdsh(args, active)
    if args.launcher == "mvapich":
        return _run_mvapich(args, active)
    if len(active) == 1 and not args.force_multi:
        return _run_local(args)
    return _run_ssh(args, active)


def _run_elastic(args, resource_pool: Optional[Dict[str, int]]) -> int:
    """--elastic_training: local slots supervised by DSElasticAgent
    (reference elastic_agent.py:28 via torch elastic; here restart +
    batch-reshape through the elasticity solver). Honors the same
    --include/--exclude filters and .deepspeed_env propagation as the
    other launcher paths."""
    import json as _json

    from ..elasticity.elastic_agent import DSElasticAgent

    ds_config = {}
    if args.deepspeed_config:
        with open(args.deepspeed_config) as f:
            ds_config = _json.load(f)
    if resource_pool:
        active = _parse_inclusion_exclusion(resource_pool, args.include,
                                            args.exclude)
        slots = sum(len(s) for s in active.values())
    else:
        slots = 1
    agent = DSElasticAgent(
        args.user_script, args.user_args, ds_config=ds_config,
        num_slots=slots, max_restarts=args.max_elastic_restarts,
        master_addr=args.master_addr or "localhost",
        master_port=args.master_port,
        extra_env=_collect_env_exports(),
        checkpoint_dir=args.elastic_checkpoint_dir or None,
        restart_backoff_s=args.elastic_restart_backoff)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
