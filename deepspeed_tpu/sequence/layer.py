"""Ulysses sequence parallelism.

Counterpart of the reference ``deepspeed/sequence/layer.py`` (113 LoC):
``DistributedAttention`` wraps any local attention with two all-to-alls —
scatter heads / gather sequence before attention, and the inverse after
(``_SeqAllToAll`` layer.py:44, ``DistributedAttention`` layer.py:60).

Two equivalent TPU implementations are provided:

1. ``ulysses_attention`` — the **compiler-driven** form used inside ``jit``:
   resharding constraints flip the sharded dimension from sequence to heads
   and back; XLA's SPMD partitioner inserts the same two all-to-alls over the
   ``seq`` ICI axis that the reference issues manually. This composes with TP
   (heads stay additionally sharded over ``model``) and ZeRO for free.

2. ``DistributedAttention`` — the **explicit** form for ``shard_map`` users,
   API-compatible with the reference class: all-to-all via
   ``deepspeed_tpu.comm.all_to_all`` with (scatter_idx, gather_idx) semantics.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import comm
from ..runtime.topology import BATCH_AXES, DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def _constraint(x: jax.Array, spec: P) -> jax.Array:
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, TypeError, RuntimeError):  # outside a mesh context
        return x


# spec of activations [B, S, H, D] while sequence-sharded (outside attention)
SEQ_SHARDED = P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
# spec while head-sharded (inside attention): full sequence per device,
# heads split over both model and seq axes
HEAD_SHARDED = P(BATCH_AXES, None, (MODEL_AXIS, SEQ_AXIS), None)


def ulysses_attention(attn_fn: Callable, q: jax.Array, k: jax.Array, v: jax.Array,
                      **kwargs) -> jax.Array:
    """Run ``attn_fn(q, k, v, **kwargs)`` with Ulysses resharding around it.

    q/k/v: [batch, seq, heads, head_dim], sequence-sharded on entry.
    """
    q = _constraint(q, HEAD_SHARDED)
    k = _constraint(k, HEAD_SHARDED)
    v = _constraint(v, HEAD_SHARDED)
    out = attn_fn(q, k, v, **kwargs)
    return _constraint(out, SEQ_SHARDED)


class DistributedAttention:
    """Explicit all-to-all wrapper (reference sequence/layer.py:60) for use
    under ``shard_map`` where mesh axes are in scope."""

    def __init__(self, local_attention: Callable, sequence_process_group: str = SEQ_AXIS,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.axis = sequence_process_group
        self.scatter_idx = scatter_idx  # heads dim
        self.gather_idx = gather_idx    # sequence dim

    def __call__(self, query: jax.Array, key: jax.Array, value: jax.Array, *args, **kwargs) -> jax.Array:
        # scatter heads, gather sequence (reference single_all_to_all, layer.py:15)
        q = comm.all_to_all(query, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        k = comm.all_to_all(key, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        v = comm.all_to_all(value, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        context = self.local_attn(q, k, v, *args, **kwargs)
        # inverse: scatter sequence, gather heads
        return comm.all_to_all(context, axis=self.axis, split_axis=self.gather_idx, concat_axis=self.scatter_idx)
