"""Ulysses sequence parallelism.

Counterpart of the reference ``deepspeed/sequence/layer.py`` (113 LoC):
``DistributedAttention`` wraps any local attention with two all-to-alls —
scatter heads / gather sequence before attention, and the inverse after
(``_SeqAllToAll`` layer.py:44, ``DistributedAttention`` layer.py:60).

Two equivalent TPU implementations are provided:

1. ``ulysses_attention`` — used inside ``jit``. When the mesh has a real
   ``seq`` degree it wraps the local attention in a ``shard_map`` region with
   two **explicit** ``lax.all_to_all`` collectives (scatter heads / gather
   sequence before attention, the inverse after) — the literal TPU form of
   the reference's ``_SeqAllToAll``. Explicit collectives matter here: the
   seq→head sharding flip is a transition GSPMD cannot express without
   "involuntary full rematerialization" (a full replicate + repartition), so
   the constraint-driven form is kept only as a fallback for shapes the
   all-to-all cannot split evenly. This composes with TP (heads stay
   additionally sharded over ``model``) and ZeRO for free.

2. ``DistributedAttention`` — the **explicit** form for ``shard_map`` users,
   API-compatible with the reference class: all-to-all via
   ``deepspeed_tpu.comm.all_to_all`` with (scatter_idx, gather_idx) semantics.

The local attention both forms wrap is ``attention.flash_attention``, whose
long-sequence default is the in-repo Pallas flash kernel
(``ops/transformer/pallas_flash.py``) — the post-all-to-all call sees the
FULL sequence with heads scattered, exactly the regime where the blockwise
kernel (O(S) memory, MXU-aligned tiles) replaces chunked XLA. GQA divides
cleanly: the all-to-all requires ``kv_heads % (tp*sp) == 0`` and the kernel
is GQA-native at any resulting ratio.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import comm
from ..runtime import topology as topo_mod
from ..utils.groups import BATCH_AXES, MODEL_AXIS, SEQ_AXIS
from ..utils.jax_compat import shard_map, with_sharding_constraint
from ..utils.logging import logger


def _constraint(x: jax.Array, spec: P) -> jax.Array:
    return with_sharding_constraint(x, spec)


# spec of activations [B, S, H, D] while sequence-sharded (outside attention)
SEQ_SHARDED = P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
# spec while head-sharded (inside attention): full sequence per device,
# heads split over both model and seq axes
HEAD_SHARDED = P(BATCH_AXES, None, (MODEL_AXIS, SEQ_AXIS), None)


def _constraint_form(attn_fn: Callable, q, k, v, kwargs):
    """Compiler-driven fallback: reshard via constraints (may cost a full
    rematerialization in GSPMD for the seq<->head flip)."""
    q = _constraint(q, HEAD_SHARDED)
    k = _constraint(k, HEAD_SHARDED)
    v = _constraint(v, HEAD_SHARDED)
    out = attn_fn(q, k, v, **kwargs)
    return _constraint(out, SEQ_SHARDED)


def _all_to_all_form(attn_fn: Callable, q, k, v, mesh, kwargs):
    """Explicit Ulysses: two all-to-alls per tensor inside one shard_map
    region (reference sequence/layer.py:15 ``single_all_to_all``).

    The exchanges ride the comm frontend with the overlap planner's
    transport binding (ROADMAP item 1(c)): ``kind="activation"`` resolves
    the bf16 wire for fp32 activations — a pure-movement cast, restored
    on receive; attention itself computes in the logical dtype. The
    Ulysses reshard is a dependence chain (attention needs the full
    sequence before one FLOP runs), so the planner binds WIDTH rather
    than placement — see runtime/overlap_planner.py ``_plan_ulysses``.
    ``DSTPU_OVERLAP_PLAN=0`` / ``DSTPU_COMM_QUANT=0`` keep the exchange
    full-width bitwise."""
    from ..runtime.overlap_planner import plan_for

    plan = plan_for("ulysses-attention")
    wire_kind = plan.transport_kind  # None when the planner is disabled

    def local_fn(q, k, v):
        # per-shard [b, s/sp, h/tp, d] -> [b, s, h/(tp*sp), d]
        gather_seq = lambda x: comm.all_to_all(
            x, axis=SEQ_AXIS, split_axis=2, concat_axis=1, kind=wire_kind)
        out = attn_fn(gather_seq(q), gather_seq(k), gather_seq(v), **kwargs)
        # inverse: scatter sequence, gather heads
        return comm.all_to_all(out, axis=SEQ_AXIS, split_axis=1,
                               concat_axis=2, kind=wire_kind)

    return shard_map(local_fn, mesh=mesh,
                     in_specs=(SEQ_SHARDED, SEQ_SHARDED, SEQ_SHARDED),
                     out_specs=SEQ_SHARDED, check_vma=False)(q, k, v)


def ulysses_attention(attn_fn: Callable, q: jax.Array, k: jax.Array, v: jax.Array,
                      **kwargs) -> jax.Array:
    """Run ``attn_fn(q, k, v, **kwargs)`` with Ulysses resharding around it.

    q/k/v: [batch, seq, heads, head_dim], sequence-sharded on entry.
    """
    topo = topo_mod.get_topology() if topo_mod.is_initialized() else None
    sp = topo.sequence_parallel_size if topo is not None else 1
    # alibi_slopes is per-GLOBAL-head; inside the shard_map form it would be
    # closure-captured whole while heads are scattered, biasing every shard
    # with the wrong slope slice — use the constraint form (like segment_ids)
    if (sp > 1 and kwargs.get("segment_ids") is None
            and kwargs.get("alibi_slopes") is None):
        tp = topo.model_parallel_size
        hq, hkv, s = q.shape[2], k.shape[2], q.shape[1]
        if hq % (tp * sp) == 0 and hkv % (tp * sp) == 0 and s % sp == 0:
            try:
                return _all_to_all_form(attn_fn, q, k, v, topo.mesh, kwargs)
            except Exception as e:  # e.g. shard_map under an outer vmap (pipeline)
                global _FALLBACK_WARNED
                if not _FALLBACK_WARNED:
                    _FALLBACK_WARNED = True
                    logger.warning(
                        "ulysses_attention: explicit all-to-all form failed "
                        f"({type(e).__name__}: {e}); using the constraint "
                        "fallback — expect an SPMD rematerialization cliff")
    return _constraint_form(attn_fn, q, k, v, kwargs)


_FALLBACK_WARNED = False


class DistributedAttention:
    """Explicit all-to-all wrapper (reference sequence/layer.py:60) for use
    under ``shard_map`` where mesh axes are in scope."""

    def __init__(self, local_attention: Callable, sequence_process_group: str = SEQ_AXIS,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.axis = sequence_process_group
        self.scatter_idx = scatter_idx  # heads dim
        self.gather_idx = gather_idx    # sequence dim

    def __call__(self, query: jax.Array, key: jax.Array, value: jax.Array, *args, **kwargs) -> jax.Array:
        # scatter heads, gather sequence (reference single_all_to_all, layer.py:15)
        q = comm.all_to_all(query, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        k = comm.all_to_all(key, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        v = comm.all_to_all(value, axis=self.axis, split_axis=self.scatter_idx, concat_axis=self.gather_idx)
        context = self.local_attn(q, k, v, *args, **kwargs)
        # inverse: scatter sequence, gather heads
        return comm.all_to_all(context, axis=self.axis, split_axis=self.gather_idx, concat_axis=self.scatter_idx)
