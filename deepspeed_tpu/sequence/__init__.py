from .layer import DistributedAttention, ulysses_attention  # noqa: F401
