"""Ring attention: sequence parallelism for contexts longer than Ulysses
can carry.

Ulysses (``layer.py``) all-to-alls the FULL sequence onto every device and
splits heads — its context ceiling is one device's memory for S×H/(sp·tp)
activations, and sp cannot exceed the head count. Ring attention removes
both limits: K/V stay sequence-sharded and ROTATE around the ``seq`` mesh
axis via ``ppermute`` while each device's resident Q block accumulates
online-softmax partial attention against every passing K/V block
(blockwise attention over a ring; the technique of Liu et al., "Ring
Attention with Blockwise Transformers" — reference DeepSpeed has no
equivalent, its Ulysses is the only SP form).

TPU form: one ``shard_map`` region; a static ``fori_loop`` of sp steps,
each step = one [s_local × s_local] attention tile (MXU work) overlapped by
XLA with the next ``ppermute`` hop over ICI. fp32 running max/denominator;
GQA native (no KV repeat); exact causal masking by global block positions
(blocks strictly in the future contribute nothing — the classic
unbalanced-causal-ring tradeoff, accepted for simplicity over zigzag
scheduling).

Two per-hop bodies (r6): the default rides the in-repo Pallas flash kernel
(``ops/transformer/pallas_flash.py``) — each hop is one blockwise kernel
call returning (output, row LSE), and hops combine by EXACT partial-softmax
accumulation (``merge_partials``), so no [s, s] score buffer exists per hop
and past hops are never re-normalized. ``DSTPU_ATTN=xla`` (or non-128-tile
local shards) restores the round-5 pure-XLA online-softmax body below.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from ..runtime import topology as topo_mod
from ..utils.groups import SEQ_AXIS
from ..utils.jax_compat import shard_map
from .layer import SEQ_SHARDED

NEG_INF = -1e30


class _HopWire:
    """Per-hop K/V wire format (ISSUE 8): the transport plan decides how
    the rotating blocks travel the ring. ``int8`` sends each hop as a
    quantized payload + per-group fp32 scales via
    ``ops.quantizer.quantized_ppermute`` — whose straight-through VJP
    permutes cotangents along the inverse ring at full width, so K/V
    keep training — and the exact LSE merge across hops is untouched.
    ``bf16`` is a plain cast; ``full`` is the identity (pre-planner
    behavior, bitwise)."""

    def __init__(self, width: str, shape, dtype, group_size: int = 256):
        self.width = width
        self.dtype = dtype
        size = 1
        for d in shape:
            size *= d
        self.size = size
        self.group_size = max(1, min(group_size, size))

    def hop(self, t, perm):
        if self.width == "int8":
            from ..ops.quantizer.quantizer import quantized_ppermute
            return quantized_ppermute(t, perm, SEQ_AXIS,
                                      group_size=self.group_size)
        if self.width == "bf16" and t.dtype.itemsize > 2:
            return jax.lax.ppermute(t.astype(jnp.bfloat16), SEQ_AXIS,
                                    perm).astype(self.dtype)
        return jax.lax.ppermute(t, SEQ_AXIS, perm)

    def wire_bytes(self) -> int:
        if self.width == "int8":
            groups = -(-self.size // self.group_size)
            return self.size + groups * 8
        if self.width == "bf16":
            return self.size * min(2, jnp.dtype(self.dtype).itemsize)
        return self.size * jnp.dtype(self.dtype).itemsize


def _hop_wires(k, v):
    """Resolve the ring transport plan and record the rotation's bytes
    (sp hops of K and V each; schedule class untagged — the static
    Layer-D map owns the ring's overlap classification)."""
    from .. import comm as dist
    nbytes = k.size * k.dtype.itemsize
    plan = dist.resolve_transport("activation", "ppermute", nbytes, SEQ_AXIS)
    kw = _HopWire(plan.width, k.shape, k.dtype, plan.group_size)
    vw = _HopWire(plan.width, v.shape, v.dtype, plan.group_size)
    sp = dist.axis_size(SEQ_AXIS)
    dist.record_collective("ppermute", nbytes, SEQ_AXIS, count=sp,
                           wire_bytes=kw.wire_bytes())
    dist.record_collective("ppermute", v.size * v.dtype.itemsize, SEQ_AXIS,
                           count=sp, wire_bytes=vw.wire_bytes())
    return kw, vw


def _ring_local(q: jax.Array, k: jax.Array, v: jax.Array, *, sp: int,
                causal: bool, scale: float) -> jax.Array:
    """Per-device body. q/k/v local shards [B, s, H|kvH, D]."""
    r = jax.lax.axis_index(SEQ_AXIS)
    B, s, H, D = q.shape
    kvH = k.shape[2]
    G = H // kvH
    qg = q.reshape(B, s, kvH, G, D)
    q_pos = r * s + jnp.arange(s)

    m0 = jnp.full((B, kvH, G, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, kvH, G, s, 1), jnp.float32)
    a0 = jnp.zeros((B, kvH, G, s, D), jnp.float32)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    kw, vw = _hop_wires(k, v)

    def step(i, carry):
        m, l, acc, k_cur, v_cur = carry
        owner = (r - i) % sp                      # origin rank of k_cur
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = owner * s + jnp.arange(s)
            ok = q_pos[:, None] >= k_pos[None, :]          # [s, s]
            logits = jnp.where(ok[None, None, None], logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        # fully-masked rows have m_new == NEG_INF; without a floor,
        # exp(NEG_INF - NEG_INF) == 1 would count every masked key. The
        # floor (10x above NEG_INF) keeps their exp() at exactly 0 while
        # never touching rows with any real logit.
        m_safe = jnp.maximum(m_new, NEG_INF / 10)
        p = jnp.exp(logits - m_safe)
        corr = jnp.exp(m - m_safe)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p.astype(v_cur.dtype), v_cur)
        k_cur = kw.hop(k_cur, perm)
        v_cur = vw.hop(v_cur, perm)
        return m_new, l, acc, k_cur, v_cur

    m, l, acc, _, _ = jax.lax.fori_loop(0, sp, step, (m0, l0, a0, k, v))
    out = acc / jnp.maximum(l, 1e-37)
    # [B, kvH, G, s, D] -> [B, s, H, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, s, H, D).astype(q.dtype)


def _ring_local_flash(q: jax.Array, k: jax.Array, v: jax.Array, *, sp: int,
                      causal: bool, scale: float,
                      interpret: bool) -> jax.Array:
    """Per-device body riding the in-repo Pallas flash kernel: each hop is
    ONE blockwise kernel call over the resident Q shard and the k/v shard
    currently passing by, and hops combine by accumulating the kernel's
    partial softmax state (normalized output + row LSE) — no per-hop
    [s, s] score materialization, no re-normalization of past hops
    (``pallas_flash.merge_partials`` is exact). Causality across shards is
    the kernel's ``q_offset``: the resident q rows start ``(r - owner) *
    s`` after the passing k rows; hops entirely in the future come back as
    (0, MASK_VALUE) partials that merge to a no-op."""
    from ..ops.transformer.pallas_flash import (MASK_VALUE,
                                                flash_attention_with_lse,
                                                merge_partials)
    r = jax.lax.axis_index(SEQ_AXIS)
    B, s, H, D = q.shape
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    kw, vw = _hop_wires(k, v)
    # fp32 cross-hop carry: merging in the input dtype would re-round the
    # running output once per hop (the XLA body's accumulator is fp32 too)
    o0 = jnp.zeros((B, s, H, D), jnp.float32)
    lse0 = jnp.full((B, H, s), MASK_VALUE, jnp.float32)

    def step(i, carry):
        o, lse, k_cur, v_cur = carry
        owner = (r - i) % sp                      # origin rank of k_cur
        # non-causal hops ignore positions entirely — pass a literal 0 so
        # axis_index never reaches the kernel as a dead operand (an unused
        # partition-id in the shard_map body trips the SPMD partitioner)
        o_h, lse_h = flash_attention_with_lse(
            q, k_cur, v_cur, causal=causal, scale=scale,
            q_offset=(r - owner) * s if causal else 0, interpret=interpret)
        o, lse = merge_partials(o, lse, o_h.astype(jnp.float32), lse_h)
        k_cur = kw.hop(k_cur, perm)
        v_cur = vw.hop(v_cur, perm)
        return o, lse, k_cur, v_cur

    o, _, _, _ = jax.lax.fori_loop(0, sp, step, (o0, lse0, k, v))
    return o.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """q/k/v ``[batch, seq, heads, head_dim]``, sequence-sharded on entry
    (same calling convention as :func:`~deepspeed_tpu.sequence.layer.ulysses_attention`'s
    inputs). Falls back to plain local attention when the mesh has no
    sequence degree.
    """
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    topo = topo_mod.get_topology() if topo_mod.is_initialized() else None
    sp = topo.sequence_parallel_size if topo is not None else 1
    if sp <= 1 or q.shape[1] % sp:
        from ..ops.transformer.attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)

    import functools

    from jax.sharding import PartitionSpec as P

    from ..runtime.topology import BATCH_AXES, MODEL_AXIS

    # Per-hop attention implementation: the in-repo Pallas flash kernel
    # (partial-softmax state accumulated across hops) wherever it can run —
    # compiled on TPU for MXU-aligned local shards, interpret mode when
    # forced (DSTPU_ATTN=pallas, the CPU test path). DSTPU_ATTN=xla keeps
    # the round-5 pure-XLA online-softmax body.
    from ..ops.transformer.attention import attn_mode
    mode = attn_mode()
    s_local = q.shape[1] // sp
    on_cpu = jax.default_backend() == "cpu"
    # same shape gate as attention.py's dispatch, on the PER-SHARD shapes
    # the hops will see (head counts divide uniformly under any further
    # model-axis sharding, so the global ratio is representative)
    from ..ops.transformer import pallas_flash as _pf
    local_ok = _pf.supports(
        (q.shape[0], s_local) + q.shape[2:],
        (k.shape[0], s_local) + k.shape[2:],
        compiled=not on_cpu)
    use_flash = (mode != "xla" and local_ok
                 and (mode == "pallas" or not on_cpu))
    if use_flash:
        local = functools.partial(_ring_local_flash, sp=sp, causal=causal,
                                  scale=scale, interpret=on_cpu)
    else:
        local = functools.partial(_ring_local, sp=sp, causal=causal,
                                  scale=scale)
    batch_axes = BATCH_AXES if isinstance(BATCH_AXES, tuple) else (BATCH_AXES,)
    batch_deg = 1
    for a in batch_axes:
        batch_deg *= topo.mesh.shape[a]
    spec = (SEQ_SHARDED if q.shape[0] % max(batch_deg, 1) == 0
            else P(None, SEQ_AXIS, MODEL_AXIS, None))
    return shard_map(local, mesh=topo.mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
