from .auto_tp import AutoTP, shard_param_tree  # noqa: F401
from .layers import LinearAllreduce, LinearLayer  # noqa: F401
