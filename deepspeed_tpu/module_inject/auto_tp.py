"""Automatic tensor parallelism.

Counterpart of the reference ``module_inject/auto_tp.py`` (``AutoTP`` :187,
``tp_parser`` :271, ``_replace`` :317) + ``tp_shard.py``: decide, for every
linear weight in a model, whether it should be column-sharded (sliced, no
comm — reference ``LinearLayer``) or row-sharded (followed by an all-reduce —
reference ``LinearAllreduce``), then shard checkpoint weights accordingly.

The reference walks torch module graphs and maintains per-architecture
policy lists. On TPU the model is a param *pytree*; classification runs on
leaf paths + shapes, and "replacement" is emitting a ``PartitionSpec`` tree
that the SPMD partitioner uses to insert the all-reduces the reference
performs by hand. The same name heuristics are kept (reference
``tp_parser`` looks for out_proj/o_proj/down_proj/dense_4h_to_h... as the
all-reduce set).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from ..runtime.topology import MODEL_AXIS

# reference auto_tp.py tp_parser: layers whose OUTPUT needs an all-reduce
# (row-parallel). Everything matmul-like that isn't row-parallel and isn't
# marked keep-replicated becomes column-parallel.
_ROW_PATTERNS = (
    "o_proj", "out_proj", "down_proj", "fc_out", "fc2", "dense_4h_to_h",
    "attention.dense", "self_attention.dense", "attn.c_proj", "mlp.c_proj",
    "wo", "w2",
)
_COLUMN_PATTERNS = (
    "q_proj", "k_proj", "v_proj", "query", "key", "value", "qkv",
    "gate_proj", "up_proj", "fc_in", "fc1", "dense_h_to_4h", "c_attn", "c_fc",
    "wi", "w1", "w3", "query_key_value",
)
_REPLICATED_PATTERNS = (
    "norm", "ln_", "layernorm", "bias_only", "rotary",
)


def _path_str(path) -> str:
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return ".".join(parts).lower()


class AutoTP:
    """Classify a param tree into TP sharding specs.

    ``tp_parser`` returns {'column': [...], 'row': [...], 'replicated': [...]}
    path lists (the reference returns policy tuples); ``build_specs`` emits
    the PartitionSpec tree.
    """

    def __init__(self, hidden_size: Optional[int] = None):
        self.hidden_size = hidden_size

    def classify(self, path: str, shape: Tuple[int, ...]) -> str:
        if len(shape) < 2:
            # 1-D: bias of a column-parallel layer is sharded with it; detect
            # by the owning layer's name
            if any(p in path for p in _COLUMN_PATTERNS):
                return "column_bias"
            return "replicated"
        if any(p in path for p in _REPLICATED_PATTERNS):
            return "replicated"
        for pat in _ROW_PATTERNS:
            if pat in path:
                return "row"
        for pat in _COLUMN_PATTERNS:
            if pat in path:
                return "column"
        # shape heuristic (reference falls back to module-type scanning):
        # widening matmul -> column, narrowing -> row
        if self.hidden_size is not None and len(shape) >= 2:
            d_in, d_out = shape[-2], shape[-1]
            if d_in == self.hidden_size and d_out > d_in:
                return "column"
            if d_out == self.hidden_size and d_in > d_out:
                return "row"
        return "replicated"

    def tp_parser(self, params: Any) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {"column": [], "row": [], "replicated": []}
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            kind = self.classify(_path_str(path), np.shape(leaf)).replace("_bias", "")
            out[kind].append(_path_str(path))
        return out

    def build_specs(self, params: Any) -> Any:
        """PartitionSpec tree: the TPU form of ``AutoTP._replace``."""

        def spec_for(path, leaf):
            kind = self.classify(_path_str(path), np.shape(leaf))
            nd = np.ndim(leaf)
            if kind == "column":
                return P(*([None] * (nd - 1)), MODEL_AXIS)
            if kind == "row":
                return P(*([None] * (nd - 2)), MODEL_AXIS, None)
            if kind == "column_bias":
                return P(*([None] * (nd - 1)), MODEL_AXIS)
            return P(*([None] * nd))

        return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_param_tree(params: Any, specs: Any, tp_rank: int, tp_size: int) -> Any:
    """Slice a full (host) param tree into rank ``tp_rank``'s TP shard —
    the reference ``tp_shard.py`` checkpoint resharding used when loading a
    non-TP checkpoint into a TP engine."""

    def shard(leaf, spec):
        leaf = np.asarray(leaf)
        for dim, axis in enumerate(spec):
            if axis == MODEL_AXIS:
                size = leaf.shape[dim]
                assert size % tp_size == 0, (leaf.shape, dim, tp_size)
                k = size // tp_size
                idx = [slice(None)] * leaf.ndim
                idx[dim] = slice(tp_rank * k, (tp_rank + 1) * k)
                return leaf[tuple(idx)]
        return leaf

    return jax.tree.map(shard, params, specs,
                        is_leaf=lambda x: isinstance(x, P))
