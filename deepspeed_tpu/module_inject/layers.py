"""TP layer forms.

Counterpart of the reference ``module_inject/layers.py`` (``LinearAllreduce``
:16, ``LinearLayer`` :62). On TPU these are not module replacements but the
two canonical sharding layouts of a dense layer over the ``model`` axis —
re-exported views of :class:`deepspeed_tpu.nn.layers.Linear`:

- ``LinearLayer``    ≡ ``Linear(shard='column')``: output features split;
  no communication (the reference's sliced Linear).
- ``LinearAllreduce`` ≡ ``Linear(shard='row')``: input features split; XLA
  inserts the psum the reference calls explicitly after the matmul.
"""

from __future__ import annotations

import functools

from ..nn.layers import Linear

LinearLayer = functools.partial(Linear, shard="column")
LinearAllreduce = functools.partial(Linear, shard="row")
