"""Environment/compat report.

Counterpart of the reference ``bin/ds_report`` (+ ``deepspeed/env_report.py``):
prints framework versions, accelerator, op availability. CLI:
``python -m deepspeed_tpu.env_report`` or ``bin/dstpu_report``.
"""

from __future__ import annotations

import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_FAIL = "\033[91m[FAIL]\033[0m"


def op_report() -> list:
    """Which op implementations are usable here (reference ds_report op table)."""
    import jax
    on_tpu = jax.default_backend() not in ("cpu",)
    rows = []
    rows.append(("fused_adam (pallas)", True, "interpret mode on cpu"))
    rows.append(("quantizer int8/int4", True, "XLA"))
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        rows.append(("flash_attention (pallas)", on_tpu, "tpu only; XLA fallback elsewhere"))
    except ImportError:
        rows.append(("flash_attention (pallas)", False, "pallas ops unavailable"))
    try:
        from deepspeed_tpu.ops.aio import AsyncIOBuilder
        rows.append(("async_io (C++)", AsyncIOBuilder().is_compatible(), "NVMe offload tier"))
    except ImportError:
        rows.append(("async_io (C++)", False, "not built"))
    return rows


def main() -> int:
    print("-" * 60)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 60)
    for name, ok, note in op_report():
        print(f"{name:<28} {GREEN_OK if ok else RED_FAIL:<18} {note}")
    print("-" * 60)
    print("General environment:")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        try:
            m = importlib.import_module(mod)
            print(f"{mod:<12} version: {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod:<12} NOT INSTALLED")
    import jax
    print(f"platform: {jax.default_backend()}")
    try:
        devs = jax.devices()
        print(f"devices: {len(devs)} x {devs[0].device_kind if devs else '?'}")
    except Exception as e:  # pragma: no cover
        print(f"devices: unavailable ({e})")
    import deepspeed_tpu
    print(f"deepspeed_tpu version: {deepspeed_tpu.__version__}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
