"""Functional layer library with sharding metadata.

This fills the role the reference fills with raw ``torch.nn`` plus its TP
wrappers (``module_inject/layers.py:16,62`` ``LinearAllreduce``/
``LinearLayer``): every layer is a small dataclass that can ``init`` a params
pytree, report a parallel ``specs`` pytree of ``PartitionSpec`` describing its
tensor-parallel layout over the ``model`` mesh axis, and apply itself purely.

Instead of *replacing* modules to introduce TP (the reference's AutoTP,
``module_inject/auto_tp.py:187``), layers declare ``shard='column'|'row'``
and XLA's SPMD partitioner inserts the all-reduces the reference does by hand
— a row-sharded Linear after a column-sharded one needs exactly one psum,
which XLA places automatically from the sharding constraints.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import MODEL_AXIS

Params = Dict[str, Any]


def _init_dense(rng, shape, scale: float, dtype) -> jax.Array:
    return (jax.random.normal(rng, shape, dtype=jnp.float32) * scale).astype(dtype)


@dataclasses.dataclass(frozen=True)
class Linear:
    """Dense layer; ``shard='column'`` splits out_features over the model
    axis (reference ``LinearLayer``), ``shard='row'`` splits in_features and
    relies on a following psum (reference ``LinearAllreduce``)."""
    in_features: int
    out_features: int
    use_bias: bool = True
    shard: Optional[str] = None  # None | 'column' | 'row'
    init_scale: float = 0.02

    def init(self, rng, dtype=jnp.float32) -> Params:
        k_rng, _ = jax.random.split(rng)
        params = {"kernel": _init_dense(k_rng, (self.in_features, self.out_features), self.init_scale, dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,), dtype=dtype)
        return params

    def specs(self) -> Params:
        if self.shard == "column":
            kernel, bias = P(None, MODEL_AXIS), P(MODEL_AXIS)
        elif self.shard == "row":
            kernel, bias = P(MODEL_AXIS, None), P()
        else:
            kernel, bias = P(None, None), P()
        out = {"kernel": kernel}
        if self.use_bias:
            out["bias"] = bias
        return out

    def __call__(self, params: Params, x: jax.Array) -> jax.Array:
        if "q" in params:
            # weight-only-quantized kernel (inference/quantization): int
            # weights feed the matmul directly, scales factored per group
            from ..inference.quantization.quantization import quantized_matmul
            y = quantized_matmul(x, params)
        else:
            y = x @ params["kernel"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


@dataclasses.dataclass(frozen=True)
class Embedding:
    """Token embedding, vocab-sharded over the model axis when ``shard``."""
    num_embeddings: int
    features: int
    shard: bool = False
    init_scale: float = 0.02

    def init(self, rng, dtype=jnp.float32) -> Params:
        return {"embedding": _init_dense(rng, (self.num_embeddings, self.features), self.init_scale, dtype)}

    def specs(self) -> Params:
        return {"embedding": P(MODEL_AXIS, None) if self.shard else P(None, None)}

    def __call__(self, params: Params, ids: jax.Array) -> jax.Array:
        # mode="clip": jnp.take's default out-of-bounds mode is "fill",
        # which yields NaN rows for any id >= vocab — a silent poison that
        # surfaces steps later as a NaN loss. Clipping matches torch-side
        # frameworks' observable behavior closely enough while the engine
        # validates ids loudly on the host (engine._device_batch).
        return jnp.take(params["embedding"], ids, axis=0, mode="clip")

    def attend(self, params: Params, x: jax.Array) -> jax.Array:
        """Tied-unembedding logits."""
        return x @ params["embedding"].astype(x.dtype).T


@dataclasses.dataclass(frozen=True)
class LayerNorm:
    features: int
    eps: float = 1e-5
    use_bias: bool = True

    def init(self, rng, dtype=jnp.float32) -> Params:
        p = {"scale": jnp.ones((self.features,), dtype=dtype)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.features,), dtype=dtype)
        return p

    def specs(self) -> Params:
        out = {"scale": P()}
        if self.use_bias:
            out["bias"] = P()
        return out

    def __call__(self, params: Params, x: jax.Array) -> jax.Array:
        # Norm statistics in fp32 regardless of compute dtype (matches the
        # reference's fused LN kernels which accumulate in fp32).
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["scale"].astype(jnp.float32)
        if self.use_bias:
            y = y + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class RMSNorm:
    """Pre-norm used by Llama-family models (reference rms_norm.cu)."""
    features: int
    eps: float = 1e-6

    def init(self, rng, dtype=jnp.float32) -> Params:
        return {"scale": jnp.ones((self.features,), dtype=dtype)}

    def specs(self) -> Params:
        return {"scale": P()}

    def __call__(self, params: Params, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (y * params["scale"].astype(jnp.float32)).astype(x.dtype)


def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)


def silu(x: jax.Array) -> jax.Array:
    return jax.nn.silu(x)


def rotary_embedding(x: jax.Array, positions: jax.Array, theta: float = 10000.0,
                     style: str = "half") -> jax.Array:
    """Apply rotary position embeddings.

    x: [..., seq, heads, head_dim]; positions: [..., seq].
    ``style='half'`` pairs dim i with dim i+half (llama/gpt-neox "rotate
    half"); ``style='interleaved'`` pairs adjacent dims (2i, 2i+1) — gpt-j's
    "rotate every two". TPU-native equivalent of the reference's
    ``apply_rotary_pos_emb.cu``; left to XLA fusion (elementwise, fuses into
    the surrounding matmuls).
    """
    head_dim = x.shape[-1]
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., seq, half]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    if style == "interleaved":
        x1, x2 = x[..., 0::2], x[..., 1::2]
        y1 = x1 * cos - x2 * sin
        y2 = x2 * cos + x1 * sin
        # re-interleave: [..., half, 2] -> [..., head_dim]
        return jnp.stack([y1, y2], axis=-1).reshape(x.shape).astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.concatenate([y1, y2], axis=-1).astype(x.dtype)


def dropout(rng, x: jax.Array, rate: float, deterministic: bool) -> jax.Array:
    if deterministic or rate == 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


def init_tree(layers: Dict[str, Any], rng, dtype=jnp.float32) -> Tuple[Params, Params]:
    """Init a dict of layers → (params, specs) trees with per-layer rng split."""
    params, specs = {}, {}
    rngs = jax.random.split(rng, len(layers))
    for r, (name, layer) in zip(rngs, sorted(layers.items())):
        params[name] = layer.init(r, dtype)
        specs[name] = layer.specs()
    return params, specs
