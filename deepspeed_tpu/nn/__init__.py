from . import layers  # noqa: F401
from .layers import Embedding, LayerNorm, Linear, RMSNorm  # noqa: F401
