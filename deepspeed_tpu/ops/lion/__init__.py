"""Lion optimizer kernels (reference ``ops/lion`` / ``csrc/lion``)."""

from .pallas_lion import lion_bucket_update  # noqa: F401
