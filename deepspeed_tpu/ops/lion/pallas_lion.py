"""Fused Pallas Lion update kernel.

TPU-native counterpart of the reference ``csrc/lion`` multi-tensor kernel,
sharing the flat-bucket layout, dispatch gate (``DSTPU_OPT_KERNEL``), SR
hash stream, and aliasing discipline with ``ops/adam/pallas_adam.py`` (see
that module's docstring — this file is the one-moment sibling: Lion reads
grad + fp32 master + exp_avg and writes master, the bf16 compute-param
cast and the SR-narrowed moment in a single pass)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..adam.pallas_adam import (_LANES, _BLOCK_ROWS, _global_idx,
                                _pad_to_rows, _store, bucket_geometry)


def _lion_kernel(g_ref, p_ref, m_ref, scal_ref, seed_ref, *out_refs,
                 beta1, beta2, weight_decay, sr_m, m_dtype, param_dtype,
                 block_elems):
    """One block of the fused Lion step (``Optimizer._lion_leaf`` math:
    sign of the b1-interpolated moment, decoupled wd, b2 EMA store)."""
    f32 = jnp.float32
    lr = scal_ref[0]
    g = g_ref[:].astype(f32) * scal_ref[1]
    p = p_ref[:].astype(f32)
    m = m_ref[:].astype(f32)

    u = jnp.sign(beta1 * m + (1.0 - beta1) * g)
    if weight_decay:
        u = u + weight_decay * p
    p2 = p - lr * u
    m2 = beta2 * m + (1.0 - beta2) * g

    refs = list(out_refs)
    refs.pop(0)[:] = p2
    if param_dtype is not None:
        refs.pop(0)[:] = p2.astype(param_dtype)
    idx = _global_idx(block_elems, g.shape) if sr_m else None
    refs.pop(0)[:] = _store(m2, m_dtype, seed_ref[0], idx, sr_m)


def lion_bucket_update(grads: jax.Array, master: jax.Array,
                       exp_avg: jax.Array, *, lr, beta1: float = 0.9,
                       beta2: float = 0.99, weight_decay: float = 0.0,
                       grad_scale=None, seed_m=None,
                       m_dtype=jnp.float32, param_dtype=None,
                       sr: bool = True, block_rows: int = _BLOCK_ROWS,
                       interpret: bool = False, alias: bool = True):
    """One fused Lion step on a flat bucket. Returns
    ``(master_f32, param_cast_or_None, m_store)``; aliasing/padding
    semantics identical to :func:`~..adam.pallas_adam.adam_bucket_update`."""
    assert grads.ndim == 1, "bucket updates operate on flat buffers"
    n = grads.shape[0]
    padded, bm, grid = bucket_geometry(n, block_rows)
    scal = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32),
    ])
    seeds = jnp.stack([jnp.zeros((), jnp.uint32) if seed_m is None
                       else seed_m])
    sr_m = sr and jnp.dtype(m_dtype) == jnp.dtype(jnp.bfloat16)
    g2 = _pad_to_rows(grads, padded)
    p2 = _pad_to_rows(master, padded)
    m2 = _pad_to_rows(exp_avg, padded)

    spec = pl.BlockSpec((bm, _LANES), lambda i: (i, 0))
    svec = pl.BlockSpec((2,), lambda i: (0,))
    seed_spec = pl.BlockSpec((1,), lambda i: (0,))
    rows_p = padded // _LANES
    shp = lambda dt: jax.ShapeDtypeStruct((rows_p, _LANES), dt)
    want_pc = param_dtype is not None
    out_shape = [shp(jnp.float32)]
    if want_pc:
        out_shape.append(shp(param_dtype))
    out_shape.append(shp(m_dtype))

    aliases = {}
    if alias and padded == n:
        # operands: g=0 p=1 m=2; outputs: [p2, (pc), m]
        if jnp.dtype(master.dtype) == jnp.dtype(jnp.float32):
            aliases[1] = 0
        if want_pc and jnp.dtype(grads.dtype) == jnp.dtype(param_dtype):
            aliases[0] = 1
        if jnp.dtype(exp_avg.dtype) == jnp.dtype(m_dtype):
            aliases[2] = 2 if want_pc else 1

    outs = pl.pallas_call(
        functools.partial(
            _lion_kernel, beta1=float(beta1), beta2=float(beta2),
            weight_decay=float(weight_decay), sr_m=sr_m,
            m_dtype=jnp.dtype(m_dtype),
            param_dtype=jnp.dtype(param_dtype) if want_pc else None,
            block_elems=bm * _LANES),
        grid=(grid,),
        in_specs=[spec, spec, spec, svec, seed_spec],
        out_specs=[spec] * len(out_shape),
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(g2, p2, m2, scal, seeds)

    outs = [o.reshape(-1)[:n] for o in outs]
    if want_pc:
        return outs[0], outs[1], outs[2]
    return outs[0], None, outs[1]
