"""Fused Pallas MoE dispatch/combine kernel pair (ISSUE 11 tentpole).

TPU-native replacement for the XLA-default expert path in
``moe/layer.py`` — the csrc-port mission named by the SNIPPETS header
(the reference's cutlass ``moe_gather``/``moe_scatter`` layout kernels +
``moe_gemm`` grouped GEMM, ``inference/v2/kernels/cutlass_ops``). The
XLA path spends its bytes on buffers that exist only to feed the next
op: the gathered ``[E*C, H]`` dispatch buffer, its wire-cast copy, the
``[E, C, H]`` expert output, and the ``[T, K, H]`` picked rows all
round-trip HBM between fusion boundaries. The kernel pair does the same
math in three launches that each read their operands once:

1. **route kernel** — top-k route select fused with the capacity-slot
   scatter: softmax, top-k pick, per-expert position ranks, capacity
   clamp, weight normalization and the inverse slot→token map
   (``src``/``slot_w``) emerge from ONE launch over the logits instead
   of the ~20-op XLA gating chain.
2. **dispatch gather+cast kernel** — the capacity-slot gather fused with
   the WIRE cast: a scalar-prefetched grid (one slot row per step, the
   paged-attention table-lookup idiom) reads each routed token row from
   HBM exactly once and writes the exchange payload directly at wire
   width. The cast never materializes a full-width copy in HBM first —
   the FlexLink (arXiv:2510.15882) compute-collective fusion framing.
   ``quantize_int8=True`` extends the ``pallas_quant``
   byte-identical-payload contract to int8 dispatch traffic: payload +
   scale sideband match ``quantize_rows_int8`` (and therefore
   ``quantize_blockwise``) byte-for-byte inside jitted programs; the
   bf16 payload is byte-identical to the XLA ``astype`` it replaces.
3. **grouped expert-FFN + combine kernel** — all local experts'
   up/act/down projections run as ONE grid over (expert, capacity-block,
   ffn-block) with the weighted combine-scatter fused into the epilogue:
   after a capacity block's last ffn-block, its rows scatter-accumulate
   straight into the token-major output, so neither ``expert_out`` nor
   the picked rows ever hit HBM. When the token output exceeds the VMEM
   residency budget the combine falls back to a separate token-major
   gather kernel (one launch, online accumulation over the k slots) and
   the FFN kernel writes ``[E, C, H]`` once.

Dispatch
--------
``DSTPU_MOE_KERNEL`` follows the PR 10 discipline
(``ops/adam/pallas_adam.py``):

- ``''``/``'auto'``: Pallas on a SINGLE-CHIP TPU, XLA elsewhere. A live
  expert/pipeline mesh keeps the XLA path — the sharding-constraint
  exchange is GSPMD-mediated and a ``pallas_call`` over sharded operands
  would make the partitioner rematerialize the dispatch buffers (the
  same reasoning as ``engine._opt_kernel_choice``; the multi-chip
  enablement is the shard_map composition the ``fused-moe-dispatch``
  lint entry already exercises).
- ``'xla'``: bitwise escape hatch — the pre-kernel layer program.
- ``'pallas'``: force (interpret mode off-TPU — the tests' path).

Numerics contract: routing decisions (top-k picks, positions, capacity
clamps, combine weights) are computed in fp32 with the exact operation
sequence of ``sharded_moe.top_k_gating_indices`` — bit-identical routes.
The FFN computes fp32 in-register (vs the XLA path's compute-dtype
einsums), so outputs agree to dtype tolerance, not bitwise; the ``xla``
hatch is the bitwise anchor. The backward is the XLA reference VJP
(``moe/layer.py`` ``moe_reference_forward``) via ``jax.custom_vjp`` —
recompute-style residuals (the layer input), one statement of the
gradient math shared with the hatch path.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..adam.pallas_adam import opt_kernel_interpret

#: VMEM residency budget for the fused combine-scatter epilogue: the
#: token-major output must stay resident across the whole FFN grid.
_FUSED_OUT_BUDGET = 4 * 1024 * 1024
#: route kernel VMEM budget for the [T, E] gating intermediates.
_ROUTE_BUDGET = 4 * 1024 * 1024
#: FFN kernel VMEM budget for one grid step's working set (payload +
#: weight blocks double-buffered by the Mosaic pipeline, plus the f32
#: accumulator scratch) — shapes over it keep the XLA path.
_FFN_BUDGET = 12 * 1024 * 1024
#: capacity/ffn block caps (divisor-clamped to the actual extents).
_CAP_BLOCK = 256
_FFN_BLOCK = 512


def moe_kernel_mode(env_var: str = "DSTPU_MOE_KERNEL") -> str:
    """Resolve the MoE kernel gate to 'pallas' | 'xla'. Auto is
    single-chip-TPU-only — stricter than ``opt_kernel_mode`` — because
    the kernel replaces a GSPMD-mediated exchange path (see module
    docstring)."""
    mode = os.environ.get(env_var, "").strip().lower()
    if mode not in ("", "auto", "xla", "pallas"):
        raise ValueError(f"{env_var} must be ''|'auto'|'xla'|'pallas', "
                         f"got {mode!r}")
    if mode in ("xla", "pallas"):
        return mode
    return ("pallas" if jax.default_backend() == "tpu"
            and jax.device_count() == 1 else "xla")


def moe_kernel_interpret() -> bool:
    return opt_kernel_interpret()


def moe_kernel_supported(*, top_k: int, activation: str, dtype,
                         tokens: int, num_experts: int,
                         hidden: int) -> bool:
    """True when the kernel pair serves this geometry. Unsupported
    shapes keep the XLA path (never an error): top-k beyond 2 (the
    in-kernel pick is a masked-argmax chain), exotic activations, fp16
    (the pad-row overflow case the XLA path masks), token counts whose
    gating intermediates exceed the route kernel's VMEM budget, and
    hidden sizes whose FFN-grid working set (a [cap_block, H] payload
    block + three [H, ffn_block] weight blocks, double-buffered, plus
    the [cap_block, H] f32 accumulator) exceeds the FFN budget."""
    if top_k not in (1, 2):
        return False
    if activation not in ("silu_gated", "gelu"):
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    if tokens * num_experts * 4 > _ROUTE_BUDGET:
        return False
    itemsize = jnp.dtype(dtype).itemsize
    ffn_step = hidden * (2 * (_CAP_BLOCK + 3 * _FFN_BLOCK) * itemsize
                         + _CAP_BLOCK * 4)
    if ffn_step > _FFN_BUDGET:
        return False
    return True


def moe_kernel_resolution(*, top_k: int, activation: str, dtype,
                          tokens: int, num_experts: int, hidden: int,
                          kernel: Optional[str] = None) -> str:
    """The layer's FULL kernel gate as one resolver: mode (env or the
    per-layer ``kernel=`` override), the live expert/pipe-axis pin, the
    ``DSTPU_MOE_MASK_PAD`` pin, and the geometry support check — in the
    same order ``moe/layer.py`` applies them. Returns ``'pallas'`` or
    ``'xla'``/``'xla (<reason>)'``; the reason string is the bench
    honesty marker's, so the A/B is skipped for exactly the pins the
    layer actually takes."""
    mode = kernel if kernel in ("xla", "pallas") else moe_kernel_mode()
    if mode == "xla":
        forced = os.environ.get("DSTPU_MOE_KERNEL", "").strip().lower()
        if (kernel != "xla" and forced not in ("xla", "pallas")
                and jax.device_count() > 1):
            return "xla (multi-device auto-pin)"
        return "xla"
    from ...runtime import topology as topo_mod
    if topo_mod.is_initialized() and (
            topo_mod.get_topology().expert_parallel_size > 1
            or topo_mod.get_topology().pipe_parallel_size > 1):
        return "xla (live expert/pipe axis pin)"
    if os.environ.get("DSTPU_MOE_MASK_PAD") == "1":
        return "xla (mask-pad pin)"
    if not moe_kernel_supported(top_k=top_k, activation=activation,
                                dtype=dtype, tokens=tokens,
                                num_experts=num_experts, hidden=hidden):
        return "xla (unsupported geometry)"
    return "pallas"


def moe_fused_combine_fits(tokens: int, hidden: int) -> bool:
    """True when the token-major f32 combine output stays VMEM-resident
    across the FFN grid (``moe_ffn_combine``'s epilogue scatter). Shapes
    over the budget take the split FFN + token-major combine kernels —
    which also means the planner's chunked scan-carry placement does NOT
    execute (the per-chunk accumulation rides the fused epilogue); the
    layer gates its chunk derivation on this so a derived ``n_chunks``
    is never silently ignored."""
    return tokens * hidden * 4 <= _FUSED_OUT_BUDGET


def _divisor_block(extent: int, cap: int) -> int:
    """Largest divisor of ``extent`` that is <= ``cap`` (>= 1)."""
    b = min(extent, cap)
    while extent % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# 1. route kernel: top-k select + capacity-slot scatter in one launch
# ---------------------------------------------------------------------------

def _route_kernel(logits_ref, src_ref, slw_ref, slot_tk_ref, w_tk_ref,
                  me_ref, ce_ref, *, top_k: int, cap: int):
    """One launch over [T, E] logits. Replicates
    ``top_k_gating_indices``'s fp32 operation sequence exactly (argmax ==
    ``lax.top_k``'s lowest-index tie rule; the k=2 pick is a masked
    re-argmax), then scatters the inverse slot→token map: ``src[slot]`` =
    token index + 1 (0 = unfilled), ``slot_w[slot]`` = that choice's
    normalized combine weight. Token-major combine metadata
    (``slot_tk``/``w_tk``) feeds the split combine path."""
    logits = logits_ref[...].astype(jnp.float32)        # [T, E]
    T, E = logits.shape
    S = E * cap
    gates = jax.nn.softmax(logits, axis=-1)

    src_ref[...] = jnp.zeros_like(src_ref)
    slw_ref[...] = jnp.zeros_like(slw_ref)

    counts = jnp.zeros((E,), jnp.int32)
    gate_sum = jnp.zeros((T,), jnp.float32)
    picked = gates
    idxs, poss, keeps, gatews = [], [], [], []
    for k in range(top_k):
        idx_k = jnp.argmax(picked, axis=1).astype(jnp.int32)     # [T]
        mask_k = jax.nn.one_hot(idx_k, E, dtype=jnp.int32)
        if k == 0:
            me_ref[...] = jnp.mean(gates, axis=0)
            ce_ref[...] = jnp.mean(mask_k.astype(jnp.float32), axis=0)
        pos_in_expert = jnp.cumsum(mask_k, axis=0) - mask_k
        pos_k = (jnp.sum(pos_in_expert * mask_k, axis=1)
                 + jnp.sum(mask_k * counts[None, :], axis=1))
        keep = pos_k < cap
        gate_k = jnp.sum(gates * mask_k.astype(jnp.float32), axis=1) * keep
        idxs.append(idx_k)
        poss.append(jnp.minimum(pos_k, cap - 1).astype(jnp.int32))
        keeps.append(keep)
        gatews.append(gate_k)
        counts = counts + jnp.sum(mask_k * keep[:, None].astype(jnp.int32),
                                  axis=0)
        gate_sum = gate_sum + gate_k
        picked = jnp.where(mask_k > 0, -jnp.inf, picked)

    denom = jnp.maximum(gate_sum, 1e-9)
    for k in range(top_k):
        w_k = gatews[k] / denom                                   # [T]
        slot_k = jnp.where(keeps[k], idxs[k] * cap + poss[k], S)
        slot_tk_ref[:, k] = jnp.where(keeps[k], slot_k, 0).astype(jnp.int32)
        w_tk_ref[:, k] = w_k * keeps[k]

        def body(t, _):
            slot = slot_k[t]

            @pl.when(slot < S)
            def _():
                src_ref[slot] = t + 1
                slw_ref[slot] = w_k[t]
            return 0

        jax.lax.fori_loop(0, T, body, 0)


def moe_route(logits: jax.Array, *, top_k: int, capacity: int,
              interpret: Optional[bool] = None):
    """Fused gating -> ``(src [E*C] i32, slot_w [E*C] f32,
    slot_tk [T, K] i32, w_tk [T, K] f32, me [E] f32, ce [E] f32)``.
    ``aux = sum(me * ce) * E`` (GShard) is left to the caller — a 3-op
    epilogue, not a launch."""
    if interpret is None:
        interpret = moe_kernel_interpret()
    T, E = logits.shape
    S = E * capacity
    full2 = pl.BlockSpec((T, E), lambda i: (0, 0))
    vec = lambda n: pl.BlockSpec((n,), lambda i: (0,))
    tk = pl.BlockSpec((T, top_k), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_route_kernel, top_k=top_k, cap=capacity),
        grid=(1,),
        in_specs=[full2],
        out_specs=[vec(S), vec(S), tk, tk, vec(E), vec(E)],
        out_shape=[jax.ShapeDtypeStruct((S,), jnp.int32),
                   jax.ShapeDtypeStruct((S,), jnp.float32),
                   jax.ShapeDtypeStruct((T, top_k), jnp.int32),
                   jax.ShapeDtypeStruct((T, top_k), jnp.float32),
                   jax.ShapeDtypeStruct((E,), jnp.float32),
                   jax.ShapeDtypeStruct((E,), jnp.float32)],
        interpret=interpret,
    )(logits)


# ---------------------------------------------------------------------------
# 2. dispatch gather + wire cast (payload emerges launch-ready)
# ---------------------------------------------------------------------------

def _gather_kernel(src_ref, tok_ref, out_ref, *, mask_pad: bool):
    i = pl.program_id(0)
    row = tok_ref[0, :].astype(jnp.float32)
    if mask_pad:
        row = jnp.where(src_ref[i] > 0, row, 0.0)
    out_ref[0, :] = row.astype(out_ref.dtype)


def _gather_int8_kernel(src_ref, tok_ref, q_ref, s_ref, *, mask_pad: bool):
    i = pl.program_id(0)
    row = tok_ref[0, :].astype(jnp.float32)
    if mask_pad:
        row = jnp.where(src_ref[i] > 0, row, 0.0)
    # quantize_rows_int8 / quantize_blockwise symmetric int8 math,
    # byte-for-byte (absmax/127, zero-scale -> 1, round-half-even, clip)
    absmax = jnp.max(jnp.abs(row))
    scale = absmax / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q_ref[0, :] = jnp.clip(jnp.round(row / scale), -128, 127
                           ).astype(jnp.int8)
    s_ref[0] = scale


def moe_dispatch_gather(tokens: jax.Array, src: jax.Array, *,
                        wire_dtype=None, mask_pad: bool = False,
                        interpret: Optional[bool] = None) -> jax.Array:
    """The fused capacity-slot gather + wire cast: one scalar-prefetched
    grid step per slot DMAs exactly the routed token row (the
    ``src``-lookup IS the index map) and stores it at wire width —
    payload ``[S, H]`` in ``wire_dtype`` (default: the compute dtype),
    byte-identical to ``tokens[max(src-1, 0)].astype(wire_dtype)``."""
    from jax.experimental.pallas import tpu as pltpu
    if interpret is None:
        interpret = moe_kernel_interpret()
    S = src.shape[0]
    T, H = tokens.shape
    out_dtype = jnp.dtype(wire_dtype) if wire_dtype is not None \
        else tokens.dtype
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[pl.BlockSpec((1, H),
                               lambda i, src: (jnp.maximum(src[i] - 1, 0),
                                               0))],
        out_specs=pl.BlockSpec((1, H), lambda i, src: (i, 0)),
    )
    return pl.pallas_call(
        functools.partial(_gather_kernel, mask_pad=mask_pad),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H), out_dtype),
        interpret=interpret,
    )(src.astype(jnp.int32), tokens)


def moe_dispatch_gather_int8(tokens: jax.Array, src: jax.Array, *,
                             mask_pad: bool = False,
                             interpret: Optional[bool] = None):
    """int8 wire fusion: gather + symmetric per-row int8 quantize in one
    launch -> ``(q [S, H] int8, scale [S] f32)``, byte-identical to
    ``quantize_rows_int8(tokens[max(src-1, 0)])`` inside jitted programs
    (the ``pallas_quant`` contract, extended to dispatch traffic)."""
    from jax.experimental.pallas import tpu as pltpu
    if interpret is None:
        interpret = moe_kernel_interpret()
    S = src.shape[0]
    T, H = tokens.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[pl.BlockSpec((1, H),
                               lambda i, src: (jnp.maximum(src[i] - 1, 0),
                                               0))],
        out_specs=[pl.BlockSpec((1, H), lambda i, src: (i, 0)),
                   pl.BlockSpec((1,), lambda i, src: (i,))],
    )
    return pl.pallas_call(
        functools.partial(_gather_int8_kernel, mask_pad=mask_pad),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((S, H), jnp.int8),
                   jax.ShapeDtypeStruct((S,), jnp.float32)],
        interpret=interpret,
    )(src.astype(jnp.int32), tokens)


# ---------------------------------------------------------------------------
# 3. grouped expert FFN + fused combine-scatter epilogue
# ---------------------------------------------------------------------------

def _ffn_block(x, wg_ref, wu_ref, wo_ref, activation):
    """One (capacity-block, ffn-block) partial: fp32 on the MXU."""
    if activation == "silu_gated":
        g = jax.lax.dot_general(x, wg_ref[0].astype(jnp.float32),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, wu_ref[0].astype(jnp.float32),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mid = jax.nn.silu(g) * u
    else:
        g = jax.lax.dot_general(x, wg_ref[0].astype(jnp.float32),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mid = jax.nn.gelu(g)
    return jax.lax.dot_general(mid, wo_ref[0].astype(jnp.float32),
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _ffn_combine_kernel(x_ref, wg_ref, wu_ref, wo_ref, src_ref, slw_ref,
                        out_ref, y_acc, *, activation: str, cap: int,
                        cap_block: int):
    """Grid (E, C/Cb, F/Fb), f innermost. The last f step of each
    capacity block runs the fused combine epilogue: every filled slot
    row scatter-accumulates ``slot_w * y`` into its token's output row —
    ``expert_out`` never exists in HBM."""
    e, c, f = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nf = pl.num_programs(2)

    @pl.when((e == 0) & (c == 0) & (f == 0))
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = x_ref[0].astype(jnp.float32)                    # [Cb, H]
    y = _ffn_block(x, wg_ref, wu_ref, wo_ref, activation)

    @pl.when(f == 0)
    def _first():
        y_acc[...] = y

    @pl.when(f > 0)
    def _accum():
        y_acc[...] = y_acc[...] + y

    @pl.when(f == nf - 1)
    def _combine():
        base = e * cap + c * cap_block

        def body(r, _):
            slot = base + r

            @pl.when(src_ref[slot] > 0)
            def _():
                t = src_ref[slot] - 1
                out_ref[t, :] = (out_ref[t, :]
                                 + slw_ref[slot] * y_acc[r, :])
            return 0

        jax.lax.fori_loop(0, cap_block, body, 0)


def _ffn_kernel(x_ref, wg_ref, wu_ref, wo_ref, y_ref, y_acc, *,
                activation: str):
    """Plain grouped FFN (split combine path): grid (E, C/Cb, F/Fb)."""
    f = pl.program_id(2)
    nf = pl.num_programs(2)
    x = x_ref[0].astype(jnp.float32)
    y = _ffn_block(x, wg_ref, wu_ref, wo_ref, activation)

    @pl.when(f == 0)
    def _first():
        y_acc[...] = y

    @pl.when(f > 0)
    def _accum():
        y_acc[...] = y_acc[...] + y

    @pl.when(f == nf - 1)
    def _store():
        y_ref[0] = y_acc[...]


def _combine_kernel(slots_ref, w_tk_ref, y_ref, out_ref):
    """Split combine: grid (T, K), k innermost — token t's output block
    is revisited K times, accumulating its picked rows online."""
    t, k = pl.program_id(0), pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[0, :] = jnp.zeros_like(out_ref[0, :])
    out_ref[0, :] = out_ref[0, :] + w_tk_ref[0, k] * y_ref[0, :]


def _ffn_specs(E, C, H, F, cap_block, ffn_block):
    xspec = pl.BlockSpec((1, cap_block, H), lambda e, c, f: (e, c, 0))
    wspec = pl.BlockSpec((1, H, ffn_block), lambda e, c, f: (e, 0, f))
    wospec = pl.BlockSpec((1, ffn_block, H), lambda e, c, f: (e, f, 0))
    return xspec, wspec, wospec


def moe_ffn_combine(payload: jax.Array, wi_gate: jax.Array,
                    wi_up: Optional[jax.Array], wo: jax.Array,
                    src: jax.Array, slot_w: jax.Array, n_tokens: int, *,
                    activation: str,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused grouped-FFN + combine-scatter: ``payload`` [E, C, H] (wire
    or compute dtype) -> token-major partial output [n_tokens, H] f32.
    ``src``/``slot_w`` must match the payload's slot layout (length
    E*C) — capacity-chunked callers pass the chunk's slices. The caller
    sums partials over chunks and casts once."""
    if interpret is None:
        interpret = moe_kernel_interpret()
    E, C, H = payload.shape
    F = wi_gate.shape[-1]
    gated = activation == "silu_gated"
    cap_block = _divisor_block(C, _CAP_BLOCK)
    ffn_block = _divisor_block(F, _FFN_BLOCK)
    xspec, wspec, wospec = _ffn_specs(E, C, H, F, cap_block, ffn_block)
    S = src.shape[0]
    assert S == E * C, (S, E, C)
    vec_i = pl.BlockSpec((S,), lambda e, c, f: (0,))
    out_spec = pl.BlockSpec((n_tokens, H), lambda e, c, f: (0, 0))
    from jax.experimental.pallas import tpu as pltpu
    wu = wi_up if gated else wi_gate
    return pl.pallas_call(
        functools.partial(_ffn_combine_kernel, activation=activation,
                          cap=C, cap_block=cap_block),
        grid=(E, C // cap_block, F // ffn_block),
        in_specs=[xspec, wspec, wspec, wospec, vec_i, vec_i],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((n_tokens, H), jnp.float32),
        scratch_shapes=[pltpu.VMEM((cap_block, H), jnp.float32)],
        interpret=interpret,
    )(payload, wi_gate, wu, wo, src.astype(jnp.int32), slot_w)


def moe_ffn(payload: jax.Array, wi_gate: jax.Array,
            wi_up: Optional[jax.Array], wo: jax.Array, *,
            activation: str, interpret: Optional[bool] = None
            ) -> jax.Array:
    """Split path: grouped FFN only -> [E, C, H] f32 expert outputs."""
    if interpret is None:
        interpret = moe_kernel_interpret()
    E, C, H = payload.shape
    F = wi_gate.shape[-1]
    gated = activation == "silu_gated"
    cap_block = _divisor_block(C, _CAP_BLOCK)
    ffn_block = _divisor_block(F, _FFN_BLOCK)
    xspec, wspec, wospec = _ffn_specs(E, C, H, F, cap_block, ffn_block)
    yspec = pl.BlockSpec((1, cap_block, H), lambda e, c, f: (e, c, 0))
    from jax.experimental.pallas import tpu as pltpu
    wu = wi_up if gated else wi_gate
    return pl.pallas_call(
        functools.partial(_ffn_kernel, activation=activation),
        grid=(E, C // cap_block, F // ffn_block),
        in_specs=[xspec, wspec, wspec, wospec],
        out_specs=yspec,
        out_shape=jax.ShapeDtypeStruct((E, C, H), jnp.float32),
        scratch_shapes=[pltpu.VMEM((cap_block, H), jnp.float32)],
        interpret=interpret,
    )(payload, wi_gate, wu, wo)


def moe_combine(y: jax.Array, slot_tk: jax.Array, w_tk: jax.Array, *,
                interpret: Optional[bool] = None) -> jax.Array:
    """Split combine: flat expert outputs ``y`` [S, H] + token-major
    combine metadata -> [T, H] f32 (grid (T, K), scalar-prefetched slot
    table — dropped choices carry weight 0 on slot 0)."""
    from jax.experimental.pallas import tpu as pltpu
    if interpret is None:
        interpret = moe_kernel_interpret()
    S, H = y.shape
    T, K = slot_tk.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T, K),
        in_specs=[pl.BlockSpec((1, K), lambda t, k, st: (t, 0)),
                  pl.BlockSpec((1, H), lambda t, k, st: (st[t * K + k], 0))],
        out_specs=pl.BlockSpec((1, H), lambda t, k, st: (t, 0)),
    )
    return pl.pallas_call(
        _combine_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H), jnp.float32),
        interpret=interpret,
    )(slot_tk.astype(jnp.int32).reshape(-1), w_tk, y)


# ---------------------------------------------------------------------------
# the full kernel-path forward (custom VJP; backward = XLA reference)
# ---------------------------------------------------------------------------

def make_moe_forward(*, top_k: int, capacity: int, activation: str,
                     mask_pad: bool, n_chunks: int = 1,
                     wire_dtype=None, interpret: Optional[bool] = None):
    """Build the kernel-path MoE forward ``(params, tokens) -> (out
    [T, H] tokens.dtype, aux f32)`` for one static geometry.

    ``n_chunks`` > 1 executes the overlap planner's scan-carry placement
    on the kernel path: the capacity dim is chunked and chunk c+1's
    dispatch gather+cast launches from the scan carry while chunk c's
    FFN+combine kernel computes (depth 1 — the executor clamp for a
    deeper plan recommendation). Exact per slot: chunking changes launch
    placement only.

    Backward: ``jax.custom_vjp`` whose bwd is the VJP of the XLA
    reference path (``moe_reference_forward``) — recompute-style, one
    statement of the gradient math shared with the ``xla`` hatch.
    """
    if interpret is None:
        interpret = moe_kernel_interpret()
    cap = capacity
    gated = activation == "silu_gated"

    def _impl(params, tokens):
        T, H = tokens.shape
        E = params["gate"].shape[-1]
        logits = tokens @ params["gate"].astype(tokens.dtype)
        src, slot_w, slot_tk, w_tk, me, ce = moe_route(
            logits.astype(jnp.float32), top_k=top_k, capacity=cap,
            interpret=interpret)
        aux = jnp.sum(me * ce) * E
        wi_gate = params["wi_gate"] if gated else params["wi"]
        wi_up = params.get("wi_up")
        wo = params["wo"]
        fused = moe_fused_combine_fits(T, H)

        nc = n_chunks
        while nc > 1 and cap % nc:
            nc -= 1
        if nc > 1 and fused:
            capc = cap // nc
            # slot-major src is [E, cap]; chunk c is columns
            # [c*capc, (c+1)*capc) of every expert row — the chunk's
            # src/slot_w slices feed both the prefetch gather and the
            # combine epilogue (same slot layout as its payload)
            src_c = src.reshape(E, nc, capc).transpose(1, 0, 2)\
                .reshape(nc, E * capc)
            slw_c = slot_w.reshape(E, nc, capc).transpose(1, 0, 2)\
                .reshape(nc, E * capc)

            def fetch(sc):
                return moe_dispatch_gather(
                    tokens, sc, wire_dtype=wire_dtype,
                    mask_pad=mask_pad,
                    interpret=interpret).reshape(E, capc, H)

            def chunk_out(payload, sc, wc):
                return moe_ffn_combine(
                    payload, wi_gate, wi_up, wo, sc, wc, T,
                    activation=activation, interpret=interpret)

            cur = fetch(src_c[0])

            def body(carry, xs):
                buf, sc_cur, wc_cur, acc = carry
                sc_nxt, wc_nxt = xs
                nxt = fetch(sc_nxt)     # independent of the FFN below
                acc = acc + chunk_out(buf, sc_cur, wc_cur)
                return (nxt, sc_nxt, wc_nxt, acc), 0

            init = (cur, src_c[0], slw_c[0],
                    jnp.zeros((T, H), jnp.float32))
            (last, sc_last, wc_last, acc), _ = jax.lax.scan(
                body, init, (src_c[1:], slw_c[1:]))
            out = acc + chunk_out(last, sc_last, wc_last)
        elif fused:
            payload = moe_dispatch_gather(
                tokens, src, wire_dtype=wire_dtype, mask_pad=mask_pad,
                interpret=interpret).reshape(E, cap, H)
            out = moe_ffn_combine(payload, wi_gate, wi_up, wo, src,
                                  slot_w, T, activation=activation,
                                  interpret=interpret)
        else:
            payload = moe_dispatch_gather(
                tokens, src, wire_dtype=wire_dtype, mask_pad=mask_pad,
                interpret=interpret).reshape(E, cap, H)
            y = moe_ffn(payload, wi_gate, wi_up, wo,
                        activation=activation, interpret=interpret)
            out = moe_combine(y.reshape(E * cap, H), slot_tk, w_tk,
                              interpret=interpret)
        return out.astype(tokens.dtype), aux

    @jax.custom_vjp
    def fwd(params, tokens):
        return _impl(params, tokens)

    def fwd_fwd(params, tokens):
        return _impl(params, tokens), (params, tokens)

    def fwd_bwd(res, cts):
        from ...moe.layer import moe_reference_forward
        params, tokens = res
        _, vjp = jax.vjp(
            lambda p, t: moe_reference_forward(
                p, t, top_k=top_k, capacity=cap, activation=activation,
                mask_pad=mask_pad), params, tokens)
        return vjp(cts)

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return fwd
