"""In-repo Pallas TPU flash attention — forward AND backward kernels.

The training-attention slot's long-context fast path. The stock JAX kernels
this repo previously imported cover only plain causal MHA: the GQA splash
kernel has no bias/window/segment support, and the stock flash kernel
repeats K/V up to the query head count. This kernel pair supports the full
feature matrix the XLA reference path (`attention._xla_attention`) already
has — causal (bottom-right aligned via ``q_offset``), GQA-NATIVE (K/V stay
at kv_heads), sliding window (shared ``sliding_window_allowed`` semantics),
segment ids, ALiBi — with fp32 accumulation and saved row-max/row-sum LSE
residuals, bound with ``jax.custom_vjp`` so the backward is blockwise too
(no O(S^2) score re-materialization: backward FLOPs are recomputed per
tile, memory stays O(S) + the LSE).

``q_offset`` and ``window`` ride scalar prefetch (SMEM), so they may be
TRACED values — the same compiled kernel serves the main training call
(offset 0), the Ulysses post-all-to-all call, and ring attention's per-hop
calls (offset ``(rank - owner) * s_local``, possibly negative = hop fully
in the future). The with-LSE entry point returns the per-row logsumexp so
ring attention can accumulate partial softmax state across ppermute hops
exactly (see ``sequence/ring_attention.py``).

Runs in interpret mode off-TPU (``pl.pallas_call(interpret=True)``) so the
CPU tier-1 tests validate numerics of the same program the chip runs.

Layout conventions (GQA-folded, MXU-aligned tiles):
  q  [B, Sq, H, D]   -> [B*kvH, G, Sq, D]
  k,v[B, Sk, kvH, D] -> [B*kvH, Sk, D]
LSE and the backward's di term are carried lane-broadcast ([..., 128]) in
kernel-facing buffers — sublane->lane transposes are the expensive shape on
TPU, lane replication is free.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NUM_LANES = 128
NUM_SUBLANES = 8
# Finite mask value (not -inf): keeps every exp()/max() chain NaN-free.
# A row that never sees an unmasked key ends with l == 0 and LSE stored as
# MASK_VALUE — a finite sentinel the ring-hop merge can exponentiate
# (exp(MASK - anything_real) underflows to exactly 0.0 in fp32).
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
# Floor used inside exponents: exp(MASK_VALUE - HALF_MASK) == 0 exactly,
# while any real logit (|s| << 1e30) keeps its exact max.
HALF_MASK = MASK_VALUE * 0.5


@dataclasses.dataclass(frozen=True)
class FlashConfig:
    """Static kernel configuration (hashable: rides custom_vjp
    nondiff_argnums and the pallas_call trace cache)."""
    causal: bool
    scale: float
    use_seg: bool
    use_alibi: bool
    use_window: bool
    kv_heads: int
    block_q: int
    block_k: int
    interpret: bool


def _lanes(x: jax.Array, n: int) -> jax.Array:
    """Broadcast a lane-replicated [rows, 128] buffer to n columns. Every
    lane holds the same per-row value, so slicing or tiling are both
    exact."""
    if n <= NUM_LANES:
        return x[:, :n]
    if n % NUM_LANES:
        raise NotImplementedError(f"width {n} not a multiple of {NUM_LANES}")
    return jnp.concatenate([x] * (n // NUM_LANES), axis=1)


def _should_run(cfg: FlashConfig, i, j, info_ref):
    """Whether q-block i has ANY unmasked key in k-block j (block-level
    flop skip). info = [q_offset, window] (traced scalars in SMEM)."""
    if not cfg.causal:
        return True
    q_off = info_ref[0]
    bq, bk = cfg.block_q, cfg.block_k
    # last q row of the block sits at or after the block's first key
    run = (q_off + (i + 1) * bq - 1) >= (j * bk)
    if cfg.use_window:
        w = info_ref[1]
        # first q row within window of the block's last key
        run = run & ((w <= 0) | ((q_off + i * bq) - (j * bk + bk - 1) < w))
    return run


def _tile_logits(cfg: FlashConfig, q, k, i, j, info_ref, slopes_ref,
                 head_idx, qseg, kseg):
    """Masked, scaled fp32 logits for one (block_q, block_k) tile — ONE
    definition shared by the forward and both backward kernels so the
    recomputed tiles cannot diverge from the forward's."""
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    if cfg.scale != 1.0:
        s = s * cfg.scale
    bq, bk = s.shape
    rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * cfg.block_q
    cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * cfg.block_k
    q_pos = rows + info_ref[0]
    if cfg.use_alibi:
        # bias = slope * (key_pos - query_pos), the row-shifted HF-BLOOM
        # form the XLA path uses (softmax is shift-invariant per row)
        slope = slopes_ref[head_idx]
        s = s + slope * (cols - q_pos).astype(jnp.float32)
    mask = None
    if cfg.use_seg:
        # qseg [bq, 128] lane-replicated; kseg [8, bk] sublane-replicated
        mask = _lanes(qseg, bk) == kseg[:1, :]
    if cfg.causal:
        cm = q_pos >= cols
        if cfg.use_window:
            w = info_ref[1]
            cm = cm & ((w <= 0) | ((q_pos - cols) < w))
        mask = cm if mask is None else mask & cm
    if mask is not None:
        s = jnp.where(mask, s, MASK_VALUE)
    return s


def _head_index(cfg: FlashConfig, b, g, G):
    """Global query-head index for (folded batch*kv_head, group) — the
    ALiBi slope lookup."""
    return (b % cfg.kv_heads) * G + g


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(info, slopes, q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                cfg: FlashConfig, G: int, nk: int, head_dim: int):
    b, g = pl.program_id(0), pl.program_id(1)
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, MASK_VALUE, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(_should_run(cfg, i, j, info))
    def _compute():
        q = q_ref[0, 0]          # [bq, D]
        k = k_ref[0]             # [bk, D]
        v = v_ref[0]
        qseg = qseg_ref[0] if cfg.use_seg else None
        kseg = kseg_ref[0] if cfg.use_seg else None
        s = _tile_logits(cfg, q, k, i, j, info, slopes,
                         _head_index(cfg, b, g, G), qseg, kseg)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        m_safe = jnp.maximum(m_next, HALF_MASK)
        p = jnp.exp(s - _lanes(m_safe, s.shape[1]))
        alpha = jnp.exp(jnp.maximum(m_prev, HALF_MASK) - m_safe)
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        acc_scr[...] = (acc_scr[...] * _lanes(alpha, head_dim)
                        + lax.dot(p.astype(v.dtype), v,
                                  preferred_element_type=jnp.float32))

    @pl.when(j == nk - 1)
    def _store():
        l = l_scr[...]
        m_safe = jnp.maximum(m_scr[...], HALF_MASK)
        inv = jnp.where(l == 0.0, 0.0, 1.0 / jnp.where(l == 0.0, 1.0, l))
        o_ref[0, 0] = (acc_scr[...] * _lanes(inv, head_dim)).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l == 0.0, MASK_VALUE,
            m_safe + jnp.log(jnp.where(l == 0.0, 1.0, l)))


def _fwd_call(cfg: FlashConfig, q, k, v, qseg_b, kseg_b, slopes, info):
    BK, G, Sq, D = q.shape
    Sk = k.shape[1]
    bq, bk = cfg.block_q, cfg.block_k
    nq, nk = Sq // bq, Sk // bk
    grid = (BK, G, nq, nk)
    kvH = cfg.kv_heads

    def kv_idx(b, g, i, j, info, slopes):
        if cfg.causal:
            j = lax.select(_should_run(cfg, i, j, info), j, 0)
        return (b, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, g, i, j, *_: (b, g, i, 0)),
        pl.BlockSpec((1, bk, D), kv_idx),
        pl.BlockSpec((1, bk, D), kv_idx),
    ]
    if cfg.use_seg:
        in_specs.append(pl.BlockSpec(
            (1, bq, NUM_LANES), lambda b, g, i, j, *_: (b // kvH, i, 0)))

        def kseg_idx(b, g, i, j, info, slopes):
            if cfg.causal:
                j = lax.select(_should_run(cfg, i, j, info), j, 0)
            return (b // kvH, 0, j)
        in_specs.append(pl.BlockSpec((1, NUM_SUBLANES, bk), kseg_idx))
    else:
        in_specs += [None, None]

    out_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, g, i, j, *_: (b, g, i, 0)),
        pl.BlockSpec((1, 1, bq, NUM_LANES),
                     lambda b, g, i, j, *_: (b, g, i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((BK, G, Sq, D), q.dtype),
        jax.ShapeDtypeStruct((BK, G, Sq, NUM_LANES), jnp.float32),
    ]
    kernel = functools.partial(_fwd_kernel, cfg=cfg, G=G, nk=nk, head_dim=D)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((bq, NUM_LANES), jnp.float32),
                pltpu.VMEM((bq, NUM_LANES), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]),
        out_shape=out_shape,
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=cfg.interpret,
    )(info, slopes, q, k, v, qseg_b, kseg_b)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _masked_p(cfg, s, lse_b):
    """exp(s - lse) with the empty-row guard: rows whose LSE is the
    MASK_VALUE sentinel (no unmasked key anywhere) contribute exactly 0."""
    p = jnp.exp(s - lse_b)
    return jnp.where(lse_b > HALF_MASK, p, 0.0)


def _dq_kernel(info, slopes, q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
               do_ref, lse_ref, di_ref, dq_ref, dq_scr, *,
               cfg: FlashConfig, G: int, nk: int):
    b, g = pl.program_id(0), pl.program_id(1)
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    @pl.when(_should_run(cfg, i, j, info))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0, 0]
        qseg = qseg_ref[0] if cfg.use_seg else None
        kseg = kseg_ref[0] if cfg.use_seg else None
        s = _tile_logits(cfg, q, k, i, j, info, slopes,
                         _head_index(cfg, b, g, G), qseg, kseg)
        bk = s.shape[1]
        p = _masked_p(cfg, s, _lanes(lse_ref[0, 0], bk))
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - _lanes(di_ref[0, 0], bk))
        if cfg.scale != 1.0:
            ds = ds * cfg.scale
        dq_scr[...] += lax.dot(ds.astype(k.dtype), k,
                               preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _store():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(info, slopes, q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
                do_ref, lse_ref, di_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                cfg: FlashConfig, G: int, nq: int):
    b = pl.program_id(0)
    j, g, i = pl.program_id(1), pl.program_id(2), pl.program_id(3)

    @pl.when((g == 0) & (i == 0))
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    @pl.when(_should_run(cfg, i, j, info))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0, 0]
        qseg = qseg_ref[0] if cfg.use_seg else None
        kseg = kseg_ref[0] if cfg.use_seg else None
        s = _tile_logits(cfg, q, k, i, j, info, slopes,
                         _head_index(cfg, b, g, G), qseg, kseg)
        bk = s.shape[1]
        p = _masked_p(cfg, s, _lanes(lse_ref[0, 0], bk))
        # dv += P^T @ dO   (contract the q rows)
        dv_scr[...] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - _lanes(di_ref[0, 0], bk))
        if cfg.scale != 1.0:
            ds = ds * cfg.scale
        # dk += dS^T @ q
        dk_scr[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((g == G - 1) & (i == nq - 1))
    def _store():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_call(cfg: FlashConfig, q, k, v, qseg_b, kseg_b, slopes, info,
              o, lse, do, dlse):
    BK, G, Sq, D = q.shape
    Sk = k.shape[1]
    bq, bk = cfg.block_q, cfg.block_k
    nq, nk = Sq // bq, Sk // bk
    kvH = cfg.kv_heads

    # di = rowsum(dO * O) (the softmax-jacobian diagonal term); a cotangent
    # on the LSE output folds in here: dL/ds = P*(dP - di) + dlse*P
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        di = di - dlse.astype(jnp.float32)
    di_b = lax.broadcast_in_dim(di, (BK, G, Sq, NUM_LANES), (0, 1, 2))
    lse_b = lax.broadcast_in_dim(lse, (BK, G, Sq, NUM_LANES), (0, 1, 2))

    def kv_idx(b, g, i, j, info, slopes):
        if cfg.causal:
            j = lax.select(_should_run(cfg, i, j, info), j, 0)
        return (b, j, 0)

    def q_row_idx(b, g, i, j, *_):
        return (b, g, i, 0)

    seg_specs = [None, None]
    if cfg.use_seg:
        def kseg_idx(b, g, i, j, info, slopes):
            if cfg.causal:
                j = lax.select(_should_run(cfg, i, j, info), j, 0)
            return (b // kvH, 0, j)
        seg_specs = [
            pl.BlockSpec((1, bq, NUM_LANES),
                         lambda b, g, i, j, *_: (b // kvH, i, 0)),
            pl.BlockSpec((1, NUM_SUBLANES, bk), kseg_idx),
        ]
    # ---- dq: same grid walk as the forward -------------------------------
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, G=G, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BK, G, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, bq, D), q_row_idx),
                pl.BlockSpec((1, bk, D), kv_idx),
                pl.BlockSpec((1, bk, D), kv_idx),
                *seg_specs,
                pl.BlockSpec((1, 1, bq, D), q_row_idx),
                pl.BlockSpec((1, 1, bq, NUM_LANES), q_row_idx),
                pl.BlockSpec((1, 1, bq, NUM_LANES), q_row_idx),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, D), q_row_idx),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((BK, G, Sq, D), q.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=cfg.interpret,
    )(info, slopes, q, k, v, qseg_b, kseg_b, do, lse_b, di_b)

    # ---- dk/dv: k-blocks outer, (group, q-block) accumulated in scratch --
    def kv_col_idx(b, j, g, i, *_):
        return (b, j, 0)

    def q_bwd_idx(b, j, g, i, info, slopes):
        if cfg.causal:
            i = lax.select(_should_run(cfg, i, j, info), i, nq - 1)
        return (b, g, i, 0)

    seg_specs2 = [None, None]
    if cfg.use_seg:
        def qseg_bwd_idx(b, j, g, i, info, slopes):
            if cfg.causal:
                i = lax.select(_should_run(cfg, i, j, info), i, nq - 1)
            return (b // kvH, i, 0)
        seg_specs2 = [
            pl.BlockSpec((1, bq, NUM_LANES), qseg_bwd_idx),
            pl.BlockSpec((1, NUM_SUBLANES, bk),
                         lambda b, j, g, i, *_: (b // kvH, 0, j)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg, G=G, nq=nq),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BK, nk, G, nq),
            in_specs=[
                pl.BlockSpec((1, 1, bq, D), q_bwd_idx),
                pl.BlockSpec((1, bk, D), kv_col_idx),
                pl.BlockSpec((1, bk, D), kv_col_idx),
                *seg_specs2,
                pl.BlockSpec((1, 1, bq, D), q_bwd_idx),
                pl.BlockSpec((1, 1, bq, NUM_LANES), q_bwd_idx),
                pl.BlockSpec((1, 1, bq, NUM_LANES), q_bwd_idx),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D), kv_col_idx),
                pl.BlockSpec((1, bk, D), kv_col_idx),
            ],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)]),
        out_shape=[jax.ShapeDtypeStruct((BK, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((BK, Sk, D), v.dtype)],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary")),
        interpret=cfg.interpret,
    )(info, slopes, q, k, v, qseg_b, kseg_b, do, lse_b, di_b)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP binding
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: FlashConfig, q, k, v, qseg_b, kseg_b, slopes, info):
    o, lse = _fwd_call(cfg, q, k, v, qseg_b, kseg_b, slopes, info)
    return o, lse


def _flash_fwd(cfg, q, k, v, qseg_b, kseg_b, slopes, info):
    o, lse = _fwd_call(cfg, q, k, v, qseg_b, kseg_b, slopes, info)
    return (o, lse), (q, k, v, qseg_b, kseg_b, slopes, info, o, lse)


def _flash_bwd(cfg, res, cts):
    q, k, v, qseg_b, kseg_b, slopes, info, o, lse = res
    do, dlse = cts  # a discarded LSE output arrives as a zero array
    dq, dk, dv = _bwd_call(cfg, q, k, v, qseg_b, kseg_b, slopes, info,
                           o, lse, do, dlse)
    return dq, dk, dv, None, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public entry points ([B, S, H, D] layout, matching attention.py)
# ---------------------------------------------------------------------------


def _auto_interpret() -> bool:
    return jax.default_backend() == "cpu"


def supports(q_shape, k_shape, block_q: int = 128, block_k: int = 128,
             compiled: bool = True) -> bool:
    """Shape gate. ``compiled=True`` (the TPU path) additionally requires
    MXU-aligned k-tiles (128-multiple key length); ``compiled=False`` (the
    interpret path driven on CPU test meshes) accepts anything the clamped
    blocks divide evenly."""
    B, Sq, H, D = q_shape
    Sk, kvH = k_shape[1], k_shape[2]
    if H % kvH:
        return False
    if D > NUM_LANES and D % NUM_LANES:
        return False
    bq, bk = min(block_q, Sq), min(block_k, Sk)
    if Sq % bq or Sk % bk:
        return False
    return bk % NUM_LANES == 0 or not compiled


def _prepare(q, k, v, causal, scale, segment_ids, q_segment_ids,
             alibi_slopes, window, q_offset, block_q, block_k, interpret):
    B, Sq, H, D = q.shape
    Sk, kvH = k.shape[1], k.shape[2]
    if H % kvH:
        raise ValueError(f"query heads {H} not a multiple of kv heads {kvH}")
    G = H // kvH
    bq, bk = min(block_q, Sq), min(block_k, Sk)
    if Sq % bq or Sk % bk:
        raise ValueError(f"seq lengths ({Sq}, {Sk}) not divisible by "
                         f"blocks ({bq}, {bk})")
    if window is not None and not causal:
        raise ValueError("sliding window is causal-only")
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    interp = _auto_interpret() if interpret is None else interpret
    cfg = FlashConfig(
        causal=bool(causal), scale=scale,
        use_seg=segment_ids is not None,
        use_alibi=alibi_slopes is not None,
        use_window=window is not None,
        kv_heads=kvH, block_q=bq, block_k=bk, interpret=bool(interp))

    # GQA-folded layout
    q4 = q.transpose(0, 2, 1, 3).reshape(B * kvH, G, Sq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * kvH, Sk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * kvH, Sk, D)

    qseg_b = kseg_b = None
    if segment_ids is not None:
        qseg = q_segment_ids if q_segment_ids is not None else segment_ids
        qseg_b = lax.broadcast_in_dim(
            qseg.astype(jnp.int32), (B, Sq, NUM_LANES), (0, 1))
        kseg_b = lax.broadcast_in_dim(
            segment_ids.astype(jnp.int32), (B, NUM_SUBLANES, Sk), (0, 2))
    if alibi_slopes is not None:
        # ALiBi slopes are a positional SCHEDULE (the fixed geometric
        # sequence of Press et al. — explicitly not learned), so the
        # kernel treats them as constants: their cotangent is zero BY
        # CONTRACT, made explicit here rather than left to the custom-VJP
        # None. Training slopes as parameters requires the XLA path.
        slopes = lax.stop_gradient(
            jnp.asarray(alibi_slopes, jnp.float32).reshape(H))
    else:
        slopes = jnp.zeros((1,), jnp.float32)
    # bottom-right causal alignment, same contract as _xla_attention
    if q_offset is None:
        q_offset = Sk - Sq
    info = jnp.stack([
        jnp.asarray(q_offset, jnp.int32).reshape(()),
        jnp.asarray(window if window is not None else 0,
                    jnp.int32).reshape(()),
    ])
    return cfg, q4, k3, v3, qseg_b, kseg_b, slopes, info, (B, H, kvH, G)


def flash_attention_with_lse(
        q: jax.Array, k: jax.Array, v: jax.Array, *,
        causal: bool = True, scale: Optional[float] = None,
        segment_ids: Optional[jax.Array] = None,
        q_segment_ids: Optional[jax.Array] = None,
        alibi_slopes: Optional[jax.Array] = None,
        window: Optional[jax.Array] = None,
        q_offset=None, block_q: int = 128, block_k: int = 128,
        interpret: Optional[bool] = None
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning ``(out [B, Sq, H, D], lse [B, H, Sq])``.

    ``lse`` is the per-row logsumexp of the masked scaled logits (fp32;
    rows with no unmasked key hold the finite ``MASK_VALUE`` sentinel) —
    the partial-softmax state ring attention accumulates across hops.
    Differentiable in q/k/v including through ``lse``.
    """
    B, Sq, H, D = q.shape
    cfg, q4, k3, v3, qseg_b, kseg_b, slopes, info, dims = _prepare(
        q, k, v, causal, scale, segment_ids, q_segment_ids, alibi_slopes,
        window, q_offset, block_q, block_k, interpret)
    _, _, kvH, G = dims
    o, lse = _flash(cfg, q4, k3, v3, qseg_b, kseg_b, slopes, info)
    out = o.reshape(B, kvH, G, Sq, D).reshape(B, H, Sq, D)
    out = out.transpose(0, 2, 1, 3)
    return out, lse.reshape(B, H, Sq)


def flash_attention_kernel(
        q: jax.Array, k: jax.Array, v: jax.Array, *,
        causal: bool = True, scale: Optional[float] = None,
        segment_ids: Optional[jax.Array] = None,
        q_segment_ids: Optional[jax.Array] = None,
        alibi_slopes: Optional[jax.Array] = None,
        window: Optional[jax.Array] = None,
        q_offset=None, block_q: int = 128, block_k: int = 128,
        interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention, ``[B, S, H, D]`` in and out — the drop-in training
    kernel `attention.flash_attention` dispatches to at long sequence."""
    out, _ = flash_attention_with_lse(
        q, k, v, causal=causal, scale=scale, segment_ids=segment_ids,
        q_segment_ids=q_segment_ids, alibi_slopes=alibi_slopes,
        window=window, q_offset=q_offset, block_q=block_q, block_k=block_k,
        interpret=interpret)
    return out


def merge_partials(o_a, lse_a, o_b, lse_b):
    """Exactly merge two partial attention results over DISJOINT key sets.

    Inputs/outputs: ``o [B, S, H, D]``, ``lse [B, H, S]`` (fp32, with the
    ``MASK_VALUE`` sentinel for empty rows). This is the LSE-accumulation
    step ring attention applies across ppermute hops: because both partial
    outputs are already normalized by their own softmax sums, the merged
    output is the lse-weighted convex combination — no re-normalization of
    past hops, no NaNs when one (or both) sides saw only masked keys.
    """
    lse_m = jnp.maximum(lse_a, lse_b)
    ea = jnp.exp(lse_a - lse_m)
    eb = jnp.exp(lse_b - lse_m)
    lse_out = lse_m + jnp.log(ea + eb)
    wa = (ea / (ea + eb)).astype(o_a.dtype)
    wb = (eb / (ea + eb)).astype(o_b.dtype)
    # [B, H, S] -> [B, S, H, 1] to weight [B, S, H, D]
    expand = lambda w: w.transpose(0, 2, 1)[..., None]
    return o_a * expand(wa) + o_b * expand(wb), lse_out
