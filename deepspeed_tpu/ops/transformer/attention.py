"""Attention ops.

The training-attention slot of the reference's kernel stack
(``csrc/transformer/softmax_kernels.cu`` + inference ``blocked_flash``). On
TPU the hot path is a Pallas flash-attention kernel (MXU-tiled, fp32
accumulation); off-TPU (CPU test meshes) we fall back to a pure-XLA
implementation with identical semantics so tests validate numerics everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   scale: Optional[float], segment_ids: Optional[jax.Array]) -> jax.Array:
    """Reference-semantics attention in pure XLA. q,k,v: [B, S, H, D]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        q_pos = jnp.arange(q_len)[:, None] + (k_len - q_len)
        mask = q_pos >= jnp.arange(k_len)[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.lru_cache(None)
def _pallas_flash_available() -> bool:
    if jax.default_backend() == "cpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Multi-head attention, [B, S, H, D] layout, GQA-aware.

    Dispatches to the Pallas TPU flash kernel when shapes allow, else XLA.
    """
    num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
    if num_kv_heads != num_q_heads:
        assert num_q_heads % num_kv_heads == 0, (num_q_heads, num_kv_heads)
        k = jnp.repeat(k, num_q_heads // num_kv_heads, axis=2)
        v = jnp.repeat(v, num_q_heads // num_kv_heads, axis=2)

    head_dim = q.shape[-1]
    if (_pallas_flash_available() and segment_ids is None and head_dim % 128 == 0
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0):
        from jax.experimental.pallas.ops.tpu import flash_attention as fa
        sm_scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
        # pallas kernel uses [B, H, S, D]
        out = fa.flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            causal=causal, sm_scale=sm_scale)
        return out.transpose(0, 2, 1, 3)
    return _xla_attention(q, k, v, causal, scale, segment_ids)
