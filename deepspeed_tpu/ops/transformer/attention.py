"""Attention ops.

The training-attention slot of the reference's kernel stack
(``csrc/transformer/softmax_kernels.cu`` + inference ``blocked_flash``). On
TPU the long-sequence hot path is the in-repo Pallas flash-attention kernel
pair (``pallas_flash.py`` — MXU-tiled, fp32 accumulation, blockwise fwd AND
bwd); off-TPU (CPU test meshes) we fall back to a pure-XLA implementation
with identical semantics so tests validate numerics everywhere.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


def alibi_slopes(num_heads: int):
    """Per-head ALiBi slopes (Press et al.; matches HF BLOOM's
    ``build_alibi_tensor`` closest-power-of-2 construction)."""
    import math

    import numpy as np

    closest = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = base ** np.arange(1, closest + 1, dtype=np.float32)
    if closest != num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        n_extra = min(closest, num_heads - closest)
        extra = extra_base ** np.arange(1, 1 + 2 * n_extra, 2, dtype=np.float32)
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def sliding_window_allowed(q_pos: jax.Array, k_pos: jax.Array,
                           window) -> jax.Array:
    """True where key ``k_pos`` is within the causal sliding window of query
    ``q_pos`` (broadcasting); ``window`` is a (possibly traced) scalar,
    <= 0 = global. ONE definition shared by the training kernel and all
    three paged serving programs so the four paths cannot diverge."""
    w = jnp.asarray(window, jnp.int32)
    return (w <= 0) | ((q_pos - k_pos) < w)


def _xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   scale: Optional[float], segment_ids: Optional[jax.Array],
                   alibi: Optional[jax.Array] = None,
                   window: Optional[jax.Array] = None,
                   q_offset: Optional[jax.Array] = None,
                   q_segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Reference-semantics attention in pure XLA, GQA-NATIVE: K/V keep
    their kv_heads — query heads are grouped for the contractions, so
    grouped-query models never materialize a repeated KV.

    Layout: inputs transpose to [B, H, S, D] up front so both einsums are
    plain batch matmuls over contiguous minor dims. Measured end-to-end on
    the gpt2-125m train bench (v5e, interleaved A/B runs): +11% step
    throughput over contracting directly in the model's [B, S, H, D]
    layout, where XLA schedules the head-middle contraction worse.
    """
    B, Sq, H, D = q.shape
    kvH = k.shape[2]
    G = H // kvH
    k_len = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    qt = q.transpose(0, 2, 1, 3).reshape(B, kvH, G, Sq, D)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    logits = jnp.einsum("bhgqd,bhkd->bhgqk", qt, kt,
                        preferred_element_type=jnp.float32) * scale
    # named for the attention-only remat policy (models/transformer.py
    # "attention_only"): the [B, H, Sq, Sk] buffers are the ONLY tensors
    # recomputed in backward — everything else is saved
    logits = checkpoint_name(logits, "attn_big")
    # q_offset: absolute position of q row 0 (the chunked path passes the
    # chunk's start); default = bottom-right alignment for Sq < k_len
    if q_offset is None:
        q_offset = k_len - Sq
    q_pos = jnp.arange(Sq)[:, None] + q_offset
    k_pos = jnp.arange(k_len)[None, :]
    if alibi is not None:
        # bias = slope * (key_pos - query_pos): row-shifted form of HF
        # BLOOM's slope * key_pos (softmax is shift-invariant per row)
        rel = (k_pos - q_pos).astype(jnp.float32)  # [Sq, K]
        logits = logits + alibi.reshape(kvH, G)[None, :, :, None, None] * rel
    if causal:
        mask = q_pos >= k_pos
        if window is not None:
            # traced scalar — one compiled block serves gpt-neo's
            # alternating global/local pattern through the layer scan
            mask = mask & sliding_window_allowed(q_pos, k_pos, window)
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    if segment_ids is not None:
        q_seg = q_segment_ids if q_segment_ids is not None else segment_ids
        seg_mask = q_seg[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    probs = checkpoint_name(probs, "attn_big")
    out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, vt)
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


def _xla_attention_chunked(q: jax.Array, k: jax.Array, v: jax.Array,
                           causal: bool, scale: Optional[float],
                           segment_ids: Optional[jax.Array],
                           alibi: Optional[jax.Array] = None,
                           window: Optional[jax.Array] = None,
                           chunk: int = 1024) -> jax.Array:
    """Query-chunked XLA attention: the long-context path.

    Identical math to :func:`_xla_attention`, but a ``lax.scan`` over
    query chunks bounds the materialized scores to [B, H, chunk, S_k]
    instead of [B, H, S, S] — the buffer that makes plain XLA a compile
    OOM at seq >= 4096 full depth. Keeps XLA's fused-matmul attention
    speed (measured +24% over the Pallas flash kernel at 2k, r4), paying
    masked-out key flops instead of kernel inefficiency: measured 4k/8k
    full-depth (tools/longseq_ab.py r5), chunked-XLA beats both the
    stock flash kernel and splash at micro-batch 1.
    """
    B, Sq, H, D = q.shape
    # Auto-size the chunk so the per-chunk fp32 score transient
    # [B, H, chunk, S_k] stays under ~512 MB — larger transients crash
    # this environment's remote compile helper at 8k/micro>1 (measured:
    # 1 GB per-chunk scores 500s, 512 MB compiles). DSTPU_CHUNK_Q
    # overrides.
    env_chunk = os.environ.get("DSTPU_CHUNK_Q")
    if env_chunk:
        chunk = int(env_chunk)
    else:
        budget = 512 * 1024 * 1024
        per_row = H * k.shape[1] * 4  # fp32 logits bytes per (b, q-row)
        cap = max(128, budget // max(B * per_row, 1))
        while chunk > cap:
            chunk //= 2
    if Sq % chunk:
        # keep the memory bound: shrink to the largest divisor of Sq
        # rather than silently re-materializing the full [B, H, S, S]
        # buffer this path exists to avoid
        c = chunk
        while c > 1 and Sq % c:
            c -= 1
        chunk = c
        if chunk < 128:  # degenerate (prime-ish Sq): one-shot is honest
            return _xla_attention(q, k, v, causal, scale, segment_ids,
                                  alibi, window)
    nc = Sq // chunk
    qc = q.reshape(B, nc, chunk, H, D).transpose(1, 0, 2, 3, 4)
    sq_c = None
    if segment_ids is not None:
        sq_c = (segment_ids.reshape(B, nc, chunk)
                .transpose(1, 0, 2))  # [nc, B, chunk]
    # bottom-right causal alignment, same contract as _xla_attention:
    # q row 0 sits at absolute position k_len - Sq
    offsets = (k.shape[1] - Sq) + jnp.arange(nc, dtype=jnp.int32) * chunk

    unroll = os.environ.get("DSTPU_CHUNK_UNROLL", "1") == "1"
    if unroll:
        # UNROLLED chunk loop (default): a lax.scan here nests inside the
        # model's layer scan + remat, which crashes this environment's
        # remote compile helper (HTTP 500) at 4k full depth; the unrolled
        # form is the same program repeated nc times and compiles. Bonus:
        # offsets are static, so each causal chunk STATICALLY slices K/V
        # to its visible prefix — the flash-style flop skip (half the
        # attention flops on average), no kernel needed.
        base = k.shape[1] - Sq
        outs = []
        for i in range(nc):
            off = base + i * chunk
            end = off + chunk if causal else k.shape[1]
            outs.append(_xla_attention(
                qc[i], k[:, :end], v[:, :end], causal, scale,
                segment_ids[:, :end] if segment_ids is not None else None,
                alibi, window, q_offset=off,
                q_segment_ids=(sq_c[i] if sq_c is not None else None)))
        out = jnp.stack(outs)
    else:
        if segment_ids is not None:
            def body(_, args):
                qi, off, sqi = args
                return None, _xla_attention(qi, k, v, causal, scale,
                                            segment_ids, alibi, window,
                                            q_offset=off, q_segment_ids=sqi)
            xs = (qc, offsets, sq_c)
        else:
            def body(_, args):
                qi, off = args
                return None, _xla_attention(qi, k, v, causal, scale, None,
                                            alibi, window, q_offset=off)
            xs = (qc, offsets)
        _, out = jax.lax.scan(body, None, xs)
    # [nc, B, chunk, H, D] -> [B, Sq, H, D]
    return out.transpose(1, 0, 2, 3, 4).reshape(B, Sq, H, D)


@functools.lru_cache(None)
def _flash_kernel_importable() -> bool:
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def attn_mode() -> str:
    """The validated ``DSTPU_ATTN`` value — ONE reader shared by this
    dispatch and ring attention so no caller can silently accept a typo
    ("XLA", "chunked"): an escape hatch that ignores a misspelling is no
    escape hatch at all."""
    mode = os.environ.get("DSTPU_ATTN", "")
    if mode not in ("", "xla", "pallas"):
        raise ValueError(f"DSTPU_ATTN must be ''|'xla'|'pallas', got "
                         f"{mode!r}")
    return mode


# At and above this query length the flash kernel is the DEFAULT: the
# XLA path's materialized scores ([B, H, S, S] fp32, 2.1 GiB per unit
# batch at 4k) fail to compile next to a full-depth train state —
# measured round 4, full-depth TinyLlama-1.1B on one v5e: XLA wins by
# 24% at 2k, is a compile OOM at 4k/8k, while flash trains both
# (tools/longseq_ab.py, docs/PERF_NOTES_R4.md).
FLASH_DEFAULT_MIN_SEQ = 4096


def _pallas_flash_available(seq_len: int = 0) -> bool:
    """DSTPU_PALLAS_FLASH=1 forces the kernel ON, =0 forces it OFF; unset,
    it auto-enables at seq >= FLASH_DEFAULT_MIN_SEQ where the XLA path
    cannot compile at scale. Below that, XLA stays the hot path: measured
    on the attached v5e (round 2), the stock Pallas flash kernel ran
    5-14x slower than XLA's fused attention at short seq. Only the import
    probe is cached — the env read stays live so toggling mid-process
    works (per-trace: jitted callers keep the path they traced with)."""
    import os
    flag = os.environ.get("DSTPU_PALLAS_FLASH", "")
    if flag == "0":
        return False
    if flag != "1" and seq_len < FLASH_DEFAULT_MIN_SEQ:
        return False
    if jax.default_backend() == "cpu":
        return False
    return _flash_kernel_importable()


@functools.lru_cache(maxsize=64)
def _splash_kernel(s_q: int, s_k: int, groups: int, causal: bool,
                   interpret: bool):
    """GQA-native splash kernel for one (b, kv_head) slice: q [G, Sq, D],
    k/v [Sk, D]. Cached per shape — mask construction is host work."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as sm)
    mask = (sm.CausalMask((s_q, s_k)) if causal
            else sm.FullMask((s_q, s_k)))
    mmask = sm.MultiHeadMask([mask] * groups)
    kw = {}
    if interpret:
        bs = sk.BlockSizes(block_q=min(128, s_q), block_kv=min(128, s_k),
                           block_kv_compute=min(128, s_k),
                           block_q_dkv=min(128, s_q),
                           block_kv_dkv=min(128, s_k),
                           block_kv_dkv_compute=min(128, s_k),
                           block_q_dq=min(128, s_q),
                           block_kv_dq=min(128, s_k))
        kw = {"block_sizes": bs, "interpret": True}
    return sk.make_splash_mqa_single_device(mmask, **kw)


def _splash_gqa(q, k, v, causal: bool, scale: float,
                interpret: bool = False) -> jax.Array:
    """GQA-NATIVE flash: K/V are loaded once per kv head (the reference's
    blocked-flash consumes GQA natively, blocked_flash.py:64). The stock
    pallas flash kernel needs matched head counts — broadcasting K/V up
    8x (TinyLlama 32q/4kv) multiplied KV HBM traffic and memory in
    exactly the long-seq regime where the kernel is the only path
    (VERDICT r4 missing #4)."""
    B, S, H, D = q.shape
    kvH = k.shape[2]
    G = H // kvH
    kernel = _splash_kernel(S, k.shape[1], G, causal, interpret)
    # [B, S, H, D] -> q [B, kvH, G, S, D]; k/v [B, kvH, S, D]
    qg = (q * scale).transpose(0, 2, 1, 3).reshape(B, kvH, G, S, D)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = jax.vmap(jax.vmap(kernel))(qg, kt, vt)   # [B, kvH, G, S, D]
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    segment_ids: Optional[jax.Array] = None,
                    alibi_slopes: Optional[jax.Array] = None,
                    window: Optional[jax.Array] = None) -> jax.Array:
    """Multi-head attention, [B, S, H, D] layout, GQA-aware.

    Long sequences (>= FLASH_DEFAULT_MIN_SEQ on TPU) dispatch to the
    IN-REPO Pallas flash kernel pair (pallas_flash.py: blockwise forward
    and backward, GQA-native, full feature matrix — causal, sliding
    window, segment ids, ALiBi, q_offset); ``DSTPU_ATTN=xla`` is the
    escape hatch back to query-chunked XLA and ``DSTPU_ATTN=pallas``
    forces the kernel at any length (interpret mode off-TPU). Short
    sequences keep the one-shot XLA path (measured faster at <= 2k). The
    legacy stock/splash-kernel knobs remain honored — see
    docs/LONG_CONTEXT.md for the full decision table.
    ``alibi_slopes`` [num_heads] adds the ALiBi positional bias (bloom);
    ``window`` (0 = global) is the causal sliding window.
    """
    head_dim = q.shape[-1]
    # Path selection (docs/LONG_CONTEXT.md). DSTPU_ATTN is the primary
    # switch: '' (auto) routes long sequences to the IN-REPO Pallas flash
    # kernel pair (ops/transformer/pallas_flash.py — blockwise fwd+bwd,
    # full feature matrix: causal/GQA/window/segment-ids/ALiBi/q_offset);
    # 'xla' is the escape hatch back to the round-5 chunked-XLA path;
    # 'pallas' forces the in-repo kernel at ANY length (interpret mode on
    # CPU test meshes). The legacy knobs (DSTPU_LONGSEQ_ATTN,
    # DSTPU_PALLAS_FLASH) still steer the round-5 routes when set.
    mode = attn_mode()
    if mode != "xla":
        from . import pallas_flash as _pf
        on_cpu = jax.default_backend() == "cpu"
        force = mode == "pallas"
        # force mode runs the kernel wherever it CAN run (interpret mode
        # relaxes the 128-wide k-tile requirement to plain divisibility)
        kernel_ok = _pf.supports(q.shape, k.shape,
                                 compiled=not (force and on_cpu))
        auto = (mode == "" and q.shape[1] >= FLASH_DEFAULT_MIN_SEQ
                and not on_cpu
                and os.environ.get("DSTPU_LONGSEQ_ATTN") is None
                and os.environ.get("DSTPU_PALLAS_FLASH", "") != "1")
        if kernel_ok and (force or auto):
            _log_path_once("pallas_flash_inrepo")
            return _pf.flash_attention_kernel(
                q, k, v, causal=causal, scale=scale,
                segment_ids=segment_ids, alibi_slopes=alibi_slopes,
                window=window)
        if force:
            # an explicit DSTPU_ATTN=pallas that cannot be honored must
            # not pass silently (round-1 review: perf regressions hide in
            # silent fallbacks)
            _log_path_once(f"xla (DSTPU_ATTN=pallas REFUSED: shapes "
                           f"q={q.shape} k={k.shape} unsupported)")
    # Long-seq XLA fallback (r5, tools/longseq_ab.py): query-chunked XLA —
    # the XLA attention path's speed with bounded score memory.
    if (q.shape[1] >= FLASH_DEFAULT_MIN_SEQ
            and (mode == "xla"
                 or os.environ.get("DSTPU_PALLAS_FLASH", "") != "1")
            and (mode == "xla"
                 or os.environ.get("DSTPU_LONGSEQ_ATTN", "chunked")
                 == "chunked")
            and jax.default_backend() != "cpu"):
        _log_path_once("xla_chunked")
        return _xla_attention_chunked(q, k, v, causal, scale, segment_ids,
                                      alibi_slopes, window)
    # head_dim 64 (gpt2) is supported by the stock kernel — Mosaic pads the
    # lane dim; requiring %128 hid the Pallas path from the benched model
    if (mode != "xla" and _pallas_flash_available(q.shape[1])
            and segment_ids is None
            and alibi_slopes is None and window is None and head_dim % 64 == 0
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0):
        num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
        sm_scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
        if (num_kv_heads != num_q_heads
                # splash's CausalMask is top-left aligned; the XLA path's
                # causal mask is bottom-right aligned (q_pos offset by
                # k_len - Sq) — only identical lengths agree, and training
                # always has Sq == Sk
                and q.shape[1] == k.shape[1]
                and os.environ.get("DSTPU_SPLASH", "1") != "0"):
            assert num_q_heads % num_kv_heads == 0, (num_q_heads, num_kv_heads)
            _log_path_once("splash_gqa")
            return _splash_gqa(q, k, v, causal, sm_scale)
        if num_kv_heads != num_q_heads:
            # DSTPU_SPLASH=0 escape hatch: broadcast K/V for the stock kernel
            k = jnp.repeat(k, num_q_heads // num_kv_heads, axis=2)
            v = jnp.repeat(v, num_q_heads // num_kv_heads, axis=2)
        from jax.experimental.pallas.ops.tpu import flash_attention as fa
        _log_path_once("pallas_flash")
        # pallas kernel uses [B, H, S, D]
        out = fa.flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            causal=causal, sm_scale=sm_scale)
        return out.transpose(0, 2, 1, 3)
    _log_path_once("xla")
    return _xla_attention(q, k, v, causal, scale, segment_ids, alibi_slopes,
                          window)


@functools.lru_cache(None)
def _log_path_once(path: str) -> None:
    """Perf regressions hide in silent fallbacks (round-1 review): say
    which attention implementation this process is using, once per path."""
    from ...utils.logging import logger
    logger.info(f"flash_attention: using {path} path "
                f"(backend={jax.default_backend()})")
