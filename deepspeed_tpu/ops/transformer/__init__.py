from .attention import flash_attention  # noqa: F401
from .pallas_flash import (  # noqa: F401
    flash_attention_kernel, flash_attention_with_lse, merge_partials)
