from .attention import flash_attention  # noqa: F401
