"""DS4Sci Evoformer attention (AlphaFold-style MSA/pair attention).

Counterpart of the reference ``ops/deepspeed4science/evoformer_attn.py``
(``DS4Sci_EvoformerAttention`` :88 — a CUTLASS fused kernel with a custom
autograd Function): attention over 5-D activations with up to two additive
biases —

- ``bias1`` ``[B, N, 1, 1, S]``: per-key mask/bias (MSA row attention's
  sequence mask), broadcast over heads and queries;
- ``bias2`` ``[B, 1, H, S, S]``: pair bias (triangle/pair representation
  injected into MSA attention), broadcast over the N dim.

TPU-first form: one fused XLA computation in heads-major layout — the
reference needs a handwritten kernel + manual backward because torch would
materialize every intermediate; XLA fuses the bias adds and softmax into
the matmul pipeline and autodiff provides the backward, so there is
nothing left for a custom kernel to win (and fp32 logits accumulation is
kept, matching the CUTLASS kernel's accumulator).

Q/K/V: ``[B, N, S, H, D]`` (batch, group/row dim, sequence, heads, head
dim) — the reference's ``[*, L, H, D]`` with two leading dims.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp


def DS4Sci_EvoformerAttention(Q: jax.Array, K: jax.Array, V: jax.Array,
                              biases: List[Optional[jax.Array]]) -> jax.Array:
    assert len(biases) <= 2, "at most two biases (mask bias + pair bias)"
    biases = list(biases) + [None] * (2 - len(biases))
    bias1, bias2 = biases

    B, N, S, H, D = Q.shape
    if bias1 is not None:
        assert bias1.shape == (B, N, 1, 1, S), \
            f"bias1 shape {bias1.shape} != {(B, N, 1, 1, S)}"
    if bias2 is not None:
        assert bias2.shape == (B, 1, H, S, S), \
            f"bias2 shape {bias2.shape} != {(B, 1, H, S, S)}"

    scale = 1.0 / (D ** 0.5)
    # heads-major: [B, N, H, S, D]
    q = Q.transpose(0, 1, 3, 2, 4)
    k = K.transpose(0, 1, 3, 2, 4)
    v = V.transpose(0, 1, 3, 2, 4)
    logits = jnp.einsum("bnhqd,bnhkd->bnhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias1 is not None:
        # [B, N, 1, 1, S] already broadcasts over (heads, queries)
        logits = logits + bias1.astype(jnp.float32)
    if bias2 is not None:
        # [B, 1, H, S, S] broadcasts over N
        logits = logits + bias2.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(Q.dtype)
    out = jnp.einsum("bnhqk,bnhkd->bnhqd", probs, v)
    return out.transpose(0, 1, 3, 2, 4)
