from .evoformer_attn import DS4Sci_EvoformerAttention  # noqa: F401
