"""Block-sparse attention layouts.

Counterpart of the reference ``ops/sparse_attention/sparsity_config.py``
(``SparsityConfig`` :10 and subclasses): each config produces a BLOCK
LAYOUT — a ``[num_heads, S/block, S/block]`` 0/1 matrix saying which key
blocks each query block attends. The reference feeds layouts to Triton
block-sparse matmuls; here the layout drives a gather of active key blocks
(``sparse_self_attention.py``), computing only the allowed tiles.

Patterns (same semantics and knob names as the reference):
- ``DenseSparsityConfig``  — all blocks (debug/fallback).
- ``FixedSparsityConfig``  — local windows of ``num_local_blocks`` plus
  attention to each window's trailing ``num_global_blocks`` summary blocks.
- ``VariableSparsityConfig`` — explicit global block indices + local windows.
- ``BigBirdSparsityConfig`` — random + sliding-window + global blocks.
- ``BSLongformerSparsityConfig`` — sliding window + leading global blocks.
"""

from __future__ import annotations

import numpy as np


class SparsityConfig:

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=np.int64)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _finalize(self, layout: np.ndarray, causal: bool = False) -> np.ndarray:
        if causal:
            n = layout.shape[1]
            layout = layout * np.tril(np.ones((n, n), np.int64))[None]
        return layout


class DenseSparsityConfig(SparsityConfig):

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[...] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w, g = self.num_local_blocks, self.num_global_blocks
        for h in range(self.num_heads):
            pat = (h % self.num_different_global_patterns
                   if self.different_layout_per_head else 0)
            for qi in range(n):
                win = qi // w
                lo = win * w
                layout[h, qi, lo:min(lo + w, n)] = 1        # local window
                # global: the last g blocks of each PRECEDING window
                # (reference: representative blocks carry summary info)
                for pw in range(win):
                    s = pw * w + max(w - g - pat, 0)
                    layout[h, qi, s:pw * w + w] = 1
            if self.horizontal_global_attention:
                for pw in range(n // w):
                    s = pw * w + max(w - g, 0)
                    layout[h, :, s:pw * w + w] = 1
        return self._finalize(layout, self.attention == "unidirectional")


class VariableSparsityConfig(SparsityConfig):

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = np.random.default_rng(0)
        # local: consecutive windows of the configured sizes (last repeats)
        sizes = list(self.local_window_blocks)
        for h in range(self.num_heads):
            qi = 0
            i = 0
            while qi < n:
                w = sizes[min(i, len(sizes) - 1)]
                lo, hi = qi, min(qi + w, n)
                layout[h, lo:hi, lo:hi] = 1
                qi = hi
                i += 1
            # globals: whole columns (and rows when horizontal)
            if self.global_block_end_indices:
                spans = zip(self.global_block_indices,
                            self.global_block_end_indices)
            else:
                spans = ((g, g + 1) for g in self.global_block_indices)
            for s, e in spans:
                layout[h, :, s:e] = 1
                if self.horizontal_global_attention:
                    layout[h, s:e, :] = 1
            for qi in range(n):
                if self.num_random_blocks:
                    cols = rng.choice(n, min(self.num_random_blocks, n),
                                      replace=False)
                    layout[h, qi, cols] = 1
        return self._finalize(layout, self.attention == "unidirectional")


class BigBirdSparsityConfig(SparsityConfig):

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks
        g = self.num_global_blocks
        rng = np.random.default_rng(0)
        for h in range(self.num_heads):
            for qi in range(n):
                lo = max(0, qi - w // 2)
                layout[h, qi, lo:min(n, qi + w // 2 + 1)] = 1   # window
                cols = rng.choice(n, min(self.num_random_blocks, n),
                                  replace=False)
                layout[h, qi, cols] = 1                          # random
            layout[h, :, :g] = 1                                 # global cols
            layout[h, :g, :] = 1                                 # global rows
        return self._finalize(layout, self.attention == "unidirectional")


class BSLongformerSparsityConfig(SparsityConfig):

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks
        for h in range(self.num_heads):
            for qi in range(n):
                lo = max(0, qi - w // 2)
                layout[h, qi, lo:min(n, qi + w // 2 + 1)] = 1
            if self.global_block_end_indices:
                spans = zip(self.global_block_indices,
                            self.global_block_end_indices)
            else:
                spans = ((g, g + 1) for g in self.global_block_indices)
            for s, e in spans:
                layout[h, :, s:e] = 1
                layout[h, s:e, :] = 1
        return self._finalize(layout, self.attention == "unidirectional")
