from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,  # noqa: F401
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention  # noqa: F401
