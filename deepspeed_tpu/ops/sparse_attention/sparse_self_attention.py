"""Block-sparse self attention, gather-based.

Counterpart of the reference ``ops/sparse_attention/sparse_self_attention.py``
(``SparseSelfAttention`` :18) + its Triton block-sparse matmul/softmax
(``matmul.py``/``softmax.py``). TPU-first form: instead of custom sparse
GEMMs, each query block GATHERS its active key/value blocks (per the
layout) and runs dense attention over just those tiles — compute and HBM
traffic scale with ``nnz(layout)``, the tiles stay MXU-shaped, and XLA sees
only static gathers/einsums. Padding rows (layouts are ragged per query
block) are masked at softmax.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import SparsityConfig


def _layout_gather_plan(layout: np.ndarray):
    """layout [H, n, n] -> (idx [H, n, A], mask [H, n, A]) with A = max
    active key blocks over all (head, row)."""
    H, n, _ = layout.shape
    A = max(1, int(layout.sum(-1).max()))
    idx = np.zeros((H, n, A), np.int32)
    mask = np.zeros((H, n, A), bool)
    for h in range(H):
        for i in range(n):
            cols = np.nonzero(layout[h, i])[0]
            idx[h, i, :len(cols)] = cols
            mask[h, i, :len(cols)] = True
    return idx, mask


def sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     layout: np.ndarray, block: int,
                     causal: bool = False,
                     scale: Optional[float] = None) -> jax.Array:
    """q/k/v ``[B, S, H, D]``; layout ``[H, S/block, S/block]`` 0/1."""
    B, S, H, D = q.shape
    n = S // block
    assert layout.shape == (H, n, n), (layout.shape, (H, n, n))
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    idx_np, amask_np = _layout_gather_plan(layout)
    idx = jnp.asarray(idx_np)
    amask = jnp.asarray(amask_np)

    # [H, B, n, b, D]
    qh = q.reshape(B, n, block, H, D).transpose(3, 0, 1, 2, 4)
    kh = k.reshape(B, n, block, H, D).transpose(3, 0, 1, 2, 4)
    vh = v.reshape(B, n, block, H, D).transpose(3, 0, 1, 2, 4)

    q_pos = (jnp.arange(n)[:, None] * block + jnp.arange(block)[None, :])

    def one_head(qh, kh, vh, idx, amask):
        kg = kh[:, idx]                      # [B, n, A, b, D]
        vg = vh[:, idx]
        logits = jnp.einsum("bnqd,bnakd->bnqak", qh, kg,
                            preferred_element_type=jnp.float32) * scale
        neg = jnp.asarray(-1e30, logits.dtype)
        logits = jnp.where(amask[None, :, None, :, None], logits, neg)
        if causal:
            k_pos = idx[:, :, None] * block + jnp.arange(block)[None, None, :]
            ok = q_pos[:, :, None, None] >= k_pos[:, None, :, :]  # [n,b,A,b]
            logits = jnp.where(ok[None], logits, neg)
        flat = logits.reshape(*logits.shape[:3], -1)              # [B,n,b,A*b]
        probs = jax.nn.softmax(flat, axis=-1).reshape(logits.shape).astype(qh.dtype)
        return jnp.einsum("bnqak,bnakd->bnqd", probs, vg)

    out = jax.vmap(one_head)(qh, kh, vh, idx, amask)   # [H, B, n, b, D]
    return out.transpose(1, 2, 3, 0, 4).reshape(B, S, H, D)


class SparseSelfAttention:
    """Config-driven wrapper (reference ``SparseSelfAttention`` :18); caches
    the gather plan per sequence length. The reference's ``attn_mask_mode``/
    ``max_seq_length`` knobs are deliberately NOT accepted: external
    attention masks are unsupported here, and silently ignoring the
    arguments would be worse than a TypeError for code being ported."""

    def __init__(self, sparsity_config: SparsityConfig):
        self.sparsity_config = sparsity_config
        self._layouts = {}

    def layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q: jax.Array, k: jax.Array, v: jax.Array,
                 causal: Optional[bool] = None) -> jax.Array:
        if causal is None:
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
        return sparse_attention(q, k, v, self.layout(q.shape[1]),
                                self.sparsity_config.block, causal=causal)
