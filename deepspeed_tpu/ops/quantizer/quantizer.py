"""Block quantization kernels.

TPU-native counterpart of the reference quantizer
(``csrc/quantization/{quantize.cu,dequantize.cu,pt_binding.cpp:270-297}``):
symmetric/asymmetric blockwise int8/int4 quantization used by ZeRO++
quantized-weight all-gather (qwZ), quantized-gradient all-to-all reduce
(qgZ), and inference weight-only quantization.

Layout: input is reshaped to [groups, group_size]; each group gets a scale
(and zero-point when asymmetric). int4 values are packed two-per-int8 —
this is the COLLECTIVE WIRE format (last-axis two's-complement nibbles, a
per-message transient); the weight STORAGE format lives in
inference/quantization/quantization.py (gs-axis bias-8 nibbles) — the two
serve different layouts and are intentionally separate. The
ops are pure XLA — packing/unpacking is shift/mask arithmetic the TPU VPU
handles well, and XLA fuses quantize into the producing op and dequantize
into the consuming matmul. (A Pallas variant is only warranted fused into
larger kernels, which pallas flash-attention handles for the decode path.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.groups import DATA_AXIS
from ...utils.jax_compat import axis_size


def gather_in_row_chunks(gather_one, x: jax.Array, n: int,
                         n_chunks: int) -> jax.Array:
    """Split a shard's leading dim into ``n_chunks`` equal launches of
    ``gather_one`` (a tiled all-gather over ``n`` members) and interleave
    the per-chunk results back into the single-launch layout
    (concat-over-members of the whole shard). THE chunk-layout invariant of
    the ZeRO overlap schedule — shared by the quantized and plain
    collectives so it lives in exactly one place."""
    if x.shape[0] % n_chunks:
        raise ValueError(f"n_chunks={n_chunks} must divide the shard's "
                         f"leading dim {x.shape[0]}")
    ck = x.shape[0] // n_chunks
    parts = [gather_one(x[c * ck:(c + 1) * ck]) for c in range(n_chunks)]
    # parts[c] is concat-over-members of chunk c; interleave back to
    # concat-over-members of the whole shard: [n, C, ck, ...] -> rows
    stacked = jnp.stack([p.reshape((n, ck) + x.shape[1:]) for p in parts],
                        axis=1)
    return stacked.reshape((n * x.shape[0],) + x.shape[1:])


def scatter_in_row_chunks(scatter_one, x: jax.Array, n: int,
                          n_chunks: int) -> jax.Array:
    """Split a reduce-scatter input ([n*s0, ...]) along the DESTINATION
    rows into ``n_chunks`` equal launches of ``scatter_one`` — each launch
    scatters a slice of every member's output; output layout matches the
    single launch. Companion of :func:`gather_in_row_chunks`."""
    s0 = x.shape[0] // n
    if s0 % n_chunks:
        raise ValueError(f"n_chunks={n_chunks} must divide the output's "
                         f"leading dim {s0}")
    ck = s0 // n_chunks
    xr = x.reshape((n, s0) + x.shape[1:])
    parts = [scatter_one(
                 xr[:, c * ck:(c + 1) * ck].reshape((n * ck,) + x.shape[1:]))
             for c in range(n_chunks)]
    return jnp.concatenate(parts, axis=0)


def quantize_blockwise(x: jax.Array, num_bits: int = 8, group_size: int = 256,
                       symmetric: bool = True) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Quantize to int8 storage (int4 packed 2/byte).

    Returns (q, scale, zero_point); scale/zero_point are [groups] fp32 (zero
    point all-zeros when symmetric).
    """
    assert num_bits in (4, 8)
    orig_size = x.size
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-orig_size) % group_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    groups = flat.reshape(-1, group_size)

    qmax = (1 << (num_bits - 1)) - 1  # 127 / 7
    qmin = -qmax - 1
    if symmetric and num_bits == 8:
        # fused quantize+pack Pallas kernel (ISSUE 10 satellite): one
        # launch computes absmax/scale/round/cast per group-row block —
        # byte-identical to the XLA chain below (pallas_quant.py's
        # contract), so the transport planner's wire payloads and the
        # committed Layer-C wire budgets are unchanged. Lane-aligned
        # int8-symmetric only; everything else keeps the XLA ops.
        from .pallas_quant import quant_kernel_enabled, quantize_rows_int8
        if quant_kernel_enabled(group_size, num_bits, symmetric):
            q, scale = quantize_rows_int8(groups)
            return q, scale, jnp.zeros_like(scale)
    if symmetric:
        absmax = jnp.max(jnp.abs(groups), axis=1, keepdims=True)
        scale = absmax / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = jnp.zeros_like(scale)
    else:
        gmax = jnp.max(groups, axis=1, keepdims=True)
        gmin = jnp.min(groups, axis=1, keepdims=True)
        scale = (gmax - gmin) / (qmax - qmin)
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = qmin - gmin / scale
    q = jnp.clip(jnp.round(groups / scale + zero), qmin, qmax).astype(jnp.int8)

    if num_bits == 4:
        q = q.reshape(-1, group_size // 2, 2)
        lo = (q[..., 0] & 0x0F).astype(jnp.uint8)
        hi = ((q[..., 1] & 0x0F) << 4).astype(jnp.uint8)
        q = (lo | hi).astype(jnp.uint8)
        q = q.reshape(-1, group_size // 2)
    return q, scale[:, 0], zero[:, 0]


def dequantize_blockwise(q: jax.Array, scale: jax.Array, zero: jax.Array,
                         num_bits: int = 8, group_size: int = 256,
                         out_size: int = None, out_shape=None,
                         dtype=jnp.float32) -> jax.Array:
    assert num_bits in (4, 8)
    if num_bits == 4:
        lo = (q & 0x0F).astype(jnp.int8)
        hi = ((q >> 4) & 0x0F).astype(jnp.int8)
        # sign-extend 4-bit two's complement
        lo = jnp.where(lo >= 8, lo - 16, lo)
        hi = jnp.where(hi >= 8, hi - 16, hi)
        vals = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    else:
        vals = q
    out = (vals.astype(jnp.float32) - zero[:, None]) * scale[:, None]
    out = out.reshape(-1)
    if out_size is not None:
        out = out[:out_size]
    if out_shape is not None:
        out = out.reshape(out_shape)
    return out.astype(dtype)


def quantize_blockwise_fp8(x: jax.Array, group_size: int = 256
                           ) -> Tuple[jax.Array, jax.Array]:
    """Scaled-fp8 wire format (EQuARX's low-precision transport alternative
    to int8): each group is scaled so its absmax lands at fp8-e4m3's max
    normal (448) and cast to ``float8_e4m3fn``. One fp32 scale per group,
    no zero point (the format is signed and symmetric). Returns
    (q [groups, group_size] f8, scale [groups] f32)."""
    orig_size = x.size
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-orig_size) % group_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    groups = flat.reshape(-1, group_size)
    fp8_max = 448.0  # e4m3fn max normal
    absmax = jnp.max(jnp.abs(groups), axis=1, keepdims=True)
    scale = absmax / fp8_max
    scale = jnp.where(scale == 0, 1.0, scale)
    q = (groups / scale).astype(jnp.float8_e4m3fn)
    return q, scale[:, 0]


def dequantize_blockwise_fp8(q: jax.Array, scale: jax.Array,
                             out_size: int = None, out_shape=None,
                             dtype=jnp.float32) -> jax.Array:
    out = q.astype(jnp.float32) * scale[:, None]
    out = out.reshape(-1)
    if out_size is not None:
        out = out[:out_size]
    if out_shape is not None:
        out = out.reshape(out_shape)
    return out.astype(dtype)


def quantize_with_feedback(x: jax.Array, err: jax.Array, num_bits: int = 8,
                           group_size: int = 256):
    """Error-feedback quantization (the compensation step of EF-SGD /
    1-bit Adam, reference ``compressed_allreduce`` server_error):
    quantize the COMPENSATED signal ``x + err`` and carry the new
    residual forward. Over accumulated steps the residuals telescope:
    sum(dequant_t) = sum(x_t) + err_0 - err_T, so the accumulated
    reduction error is bounded by ONE step's quantization error instead
    of growing with the step count. Returns (q, scale, zero, new_err);
    ``new_err`` has ``x``'s shape/f32."""
    comp = x.astype(jnp.float32) + err.astype(jnp.float32)
    q, scale, zero = quantize_blockwise(comp, num_bits, group_size)
    roundtrip = dequantize_blockwise(
        q, scale, zero, num_bits, group_size,
        out_size=comp.size, out_shape=comp.shape)
    return q, scale, zero, comp - roundtrip


def quantized_all_gather(x: jax.Array, axis: str = DATA_AXIS, num_bits: int = 8,
                         group_size: int = 256, n_chunks: int = 1) -> jax.Array:
    """ZeRO++ qwZ-style all-gather: quantize the local shard, gather int8
    over the mesh axis, dequantize (reference quantized weights all-gather,
    ``partition_parameters.py:1101`` + quantizer kernels). Call inside
    shard_map; halves (int8) or quarters (int4) the gather bytes on ICI.

    ``n_chunks > 1`` splits the shard's leading dim into that many equal
    launches (the layer-granular overlap schedule's ``allgather_bucket_size``
    pipelining: a huge leaf becomes several smaller gathers the scheduler
    can slide under compute). The reassembled result is laid out exactly
    like the unchunked gather; numerics may differ at quantization-group
    boundaries when the chunk size is not a group multiple."""
    if n_chunks > 1:
        if x.shape[0] % n_chunks:  # validate BEFORE touching the mesh axis
            raise ValueError(f"n_chunks={n_chunks} must divide the shard's "
                             f"leading dim {x.shape[0]}")
        return gather_in_row_chunks(
            lambda c: quantized_all_gather(c, axis, num_bits, group_size),
            x, axis_size(axis), n_chunks)
    # Effective group size: never pad a small shard up to a full group —
    # the padding would travel the wire. int4 packs two values per byte, so
    # its groups must stay even.
    group_size = max(1, min(group_size, x.size))
    if num_bits == 4:
        group_size = max(2, group_size - group_size % 2)
    q, scale, zero = quantize_blockwise(x, num_bits, group_size)
    q_g = jax.lax.all_gather(q, axis, axis=0, tiled=True)
    s_g = jax.lax.all_gather(scale, axis, axis=0, tiled=True)
    z_g = jax.lax.all_gather(zero, axis, axis=0, tiled=True)
    n = axis_size(axis)
    # Each shard's segment carries its own group padding at its tail; slice
    # per segment, not once at the end (segments are x.size rounded up to a
    # group multiple).
    out = dequantize_blockwise(q_g, s_g, z_g, num_bits, group_size)
    padded = -(-x.size // group_size) * group_size
    out = out.reshape(n, padded)[:, :x.size]
    return out.reshape((x.shape[0] * n,) + x.shape[1:]).astype(x.dtype)


def quantized_reduce_scatter(x: jax.Array, axis: str = DATA_AXIS, num_bits: int = 8,
                             group_size: int = 256, n_chunks: int = 1) -> jax.Array:
    """ZeRO++ qgZ-style gradient reduction (reference
    ``all_to_all_quant_reduce``, coalesced_collectives.py:31): quantize,
    all-to-all the shards, dequantize, local-sum. Trades ICI bytes for
    quantization error exactly like the reference.

    ``n_chunks > 1`` splits along the DESTINATION rows (each member's 1/n
    output) into equal launches — the ``reduce_bucket_size`` pipelining of
    the overlap schedule. Output layout matches the unchunked call."""
    n = axis_size(axis)
    assert x.shape[0] % n == 0
    if n_chunks > 1:
        return scatter_in_row_chunks(
            lambda c: quantized_reduce_scatter(c, axis, num_bits, group_size),
            x, n, n_chunks)
    # Quantize each destination chunk separately so the all-to-all splits on
    # exact chunk boundaries even when chunk size is not a group multiple
    # (padding lives at each chunk's tail; zeros quantize exactly under
    # symmetric quant, so summed padding stays zero).
    chunk = x.size // n
    # Effective group size: tiny chunks (biases, norms) must not pad up to a
    # full group — at group_size=256 and dp=8 that is an 8-32x inflation of
    # the bytes on the wire for small params.
    group_size = max(1, min(group_size, chunk))
    if num_bits == 4:
        group_size = max(2, group_size - group_size % 2)
    xr = x.astype(jnp.float32).reshape(n, chunk)
    pad = (-chunk) % group_size
    if pad:
        xr = jnp.pad(xr, ((0, 0), (0, pad)))
    q, scale, zero = quantize_blockwise(xr, num_bits, group_size)
    q_t = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s_t = jax.lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    z_t = jax.lax.all_to_all(zero, axis, split_axis=0, concat_axis=0, tiled=True)
    shard = dequantize_blockwise(q_t, s_t, z_t, num_bits, group_size)
    shard = shard.reshape(n, chunk + pad)[:, :chunk]
    out = jnp.sum(shard, axis=0)
    return out.reshape((x.shape[0] // n,) + x.shape[1:]).astype(x.dtype)


def _dest_chunk_group_size(chunk: int, group_size: int, num_bits: int) -> int:
    """Effective per-destination-chunk group size (see the inline comments
    in :func:`quantized_reduce_scatter` — tiny chunks must not pad up to a
    full group, int4 groups must stay even)."""
    group_size = max(1, min(group_size, chunk))
    if num_bits == 4:
        group_size = max(2, group_size - group_size % 2)
    return group_size


def ef_quantized_reduce_scatter(x: jax.Array, err: jax.Array,
                                axis=DATA_AXIS, num_bits: int = 8,
                                group_size: int = 256
                                ) -> Tuple[jax.Array, jax.Array]:
    """:func:`quantized_reduce_scatter` with error feedback: the residual
    of THIS member's quantization is returned and must be fed back on the
    next reduction of the same bucket (``err`` starts as zeros of
    ``x``'s shape). The wire format and output layout are identical to
    the plain call — only the quantized VALUES differ (they carry the
    compensated signal x + err). ``err`` has ``x``'s shape (zeros on the
    first step) and so does the returned residual — the pair is a valid
    scan/jit carry; group padding stays internal (a padded position's
    signal and residual are both zero, so its residual is exactly zero
    and dropping it loses nothing)."""
    n = axis_size(axis)
    assert x.shape[0] % n == 0
    chunk = x.size // n
    group_size = _dest_chunk_group_size(chunk, group_size, num_bits)
    xr = x.astype(jnp.float32).reshape(n, chunk)
    er = err.astype(jnp.float32).reshape(n, chunk)
    pad = (-chunk) % group_size
    if pad:
        xr = jnp.pad(xr, ((0, 0), (0, pad)))
        er = jnp.pad(er, ((0, 0), (0, pad)))
    q, scale, zero, new_err = quantize_with_feedback(
        xr, er, num_bits, group_size)
    new_err = new_err[:, :chunk].reshape(x.shape)
    q_t = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s_t = jax.lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    z_t = jax.lax.all_to_all(zero, axis, split_axis=0, concat_axis=0, tiled=True)
    shard = dequantize_blockwise(q_t, s_t, z_t, num_bits, group_size)
    shard = shard.reshape(n, chunk + pad)[:, :chunk]
    out = jnp.sum(shard, axis=0)
    return (out.reshape((x.shape[0] // n,) + x.shape[1:]).astype(x.dtype),
            new_err)


def fp8_reduce_scatter(x: jax.Array, axis=DATA_AXIS,
                       group_size: int = 256, n_chunks: int = 1) -> jax.Array:
    """:func:`quantized_reduce_scatter` with the scaled-fp8 wire format:
    same all-to-all + local-sum structure, same layout, but values travel
    as ``float8_e4m3fn`` (1 byte) with one fp32 scale per group and no
    zero-point sideband."""
    n = axis_size(axis)
    assert x.shape[0] % n == 0
    if n_chunks > 1:
        return scatter_in_row_chunks(
            lambda c: fp8_reduce_scatter(c, axis, group_size), x, n, n_chunks)
    chunk = x.size // n
    group_size = _dest_chunk_group_size(chunk, group_size, num_bits=8)
    xr = x.astype(jnp.float32).reshape(n, chunk)
    pad = (-chunk) % group_size
    if pad:
        xr = jnp.pad(xr, ((0, 0), (0, pad)))
    q, scale = quantize_blockwise_fp8(xr, group_size)
    q_t = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s_t = jax.lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    shard = dequantize_blockwise_fp8(q_t, s_t)
    shard = shard.reshape(n, chunk + pad)[:, :chunk]
    out = jnp.sum(shard, axis=0)
    return out.reshape((x.shape[0] // n,) + x.shape[1:]).astype(x.dtype)


def fp8_all_gather(x: jax.Array, axis=DATA_AXIS, group_size: int = 256,
                   n_chunks: int = 1) -> jax.Array:
    """:func:`quantized_all_gather` with the scaled-fp8 wire format."""
    if n_chunks > 1:
        if x.shape[0] % n_chunks:
            raise ValueError(f"n_chunks={n_chunks} must divide the shard's "
                             f"leading dim {x.shape[0]}")
        return gather_in_row_chunks(
            lambda c: fp8_all_gather(c, axis, group_size),
            x, axis_size(axis), n_chunks)
    group_size = max(1, min(group_size, x.size))
    q, scale = quantize_blockwise_fp8(x, group_size)
    q_g = jax.lax.all_gather(q, axis, axis=0, tiled=True)
    s_g = jax.lax.all_gather(scale, axis, axis=0, tiled=True)
    n = axis_size(axis)
    out = dequantize_blockwise_fp8(q_g, s_g)
    padded = -(-x.size // group_size) * group_size
    out = out.reshape(n, padded)[:, :x.size]
    return out.reshape((x.shape[0] * n,) + x.shape[1:]).astype(x.dtype)


def quantized_ppermute(t: jax.Array, perm, axis, num_bits: int = 8,
                       group_size: int = 256) -> jax.Array:
    """Quantized point-to-point permutation (ring hops): quantize, permute
    the int8 payload + fp32 scale sideband, dequantize on arrival.

    Gradient contract (straight-through): the backward pass permutes the
    cotangent along the INVERSE ring at full width — quantization is
    treated as identity by AD. Without this, ``round`` would zero every
    gradient flowing through a rotating K/V block and ring attention
    would stop training its keys/values."""
    group_size = max(1, min(group_size, t.size))
    if num_bits == 4:
        group_size = max(2, group_size - group_size % 2)

    @jax.custom_vjp
    def hop(x):
        return _hop_fwd_only(x)

    def _hop_fwd_only(x):
        q, scale, zero = quantize_blockwise(x, num_bits, group_size)
        q = jax.lax.ppermute(q, axis, perm)
        scale = jax.lax.ppermute(scale, axis, perm)
        zero = jax.lax.ppermute(zero, axis, perm)
        return dequantize_blockwise(q, scale, zero, num_bits, group_size,
                                    out_size=x.size, out_shape=x.shape,
                                    dtype=x.dtype)

    def fwd(x):
        return _hop_fwd_only(x), None

    def bwd(_, g):
        inv = [(dst, src) for src, dst in perm]
        return (jax.lax.ppermute(g, axis, inv),)

    hop.defvjp(fwd, bwd)
    return hop(t)


def quantized_all_reduce(x: jax.Array, axis=DATA_AXIS, num_bits: int = 8,
                         group_size: int = 256, outer=(),
                         fp8: bool = False) -> jax.Array:
    """EQuARX-style quantized all-reduce (arXiv:2506.17615): decompose the
    all-reduce into quantize -> reduce-scatter (all-to-all wire + local
    sum) -> [full-width all-reduce over ``outer`` tiers] -> quantized
    all-gather. Both wire legs move 8-bit payloads; the optional ``outer``
    leg (the DCN tier of a hierarchical plan) reduces the already-1/n
    shard at full width — cross-tier bytes shrink by the inner axis size
    AND the wire width together (*The Big Send-off*, arXiv:2504.18658).

    Input may be any shape; it is flattened and padded to an axis-size
    multiple for the scatter leg (zero padding is exact under symmetric
    quantization)."""
    n = axis_size(axis)
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    if fp8:
        part = fp8_reduce_scatter(flat, axis, group_size)
    else:
        part = quantized_reduce_scatter(flat, axis, num_bits, group_size)
    if outer:
        part = jax.lax.psum(part, outer)
    if fp8:
        full = fp8_all_gather(part, axis, group_size)
    else:
        full = quantized_all_gather(part, axis, num_bits, group_size)
    if pad:
        full = full[:x.size]
    return full.reshape(x.shape).astype(x.dtype)
