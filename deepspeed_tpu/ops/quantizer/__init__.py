from .quantizer import (dequantize_blockwise, quantize_blockwise,  # noqa: F401
                        quantized_all_gather, quantized_reduce_scatter)
