from .quantizer import (dequantize_blockwise, quantize_blockwise,  # noqa: F401
                        quantized_all_gather, quantized_reduce_scatter,
                        dequantize_blockwise_fp8, quantize_blockwise_fp8,
                        ef_quantized_reduce_scatter, fp8_all_gather,
                        fp8_reduce_scatter, quantize_with_feedback,
                        quantized_all_reduce)
