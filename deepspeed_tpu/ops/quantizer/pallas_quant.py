"""Fused quantize+pack Pallas kernel for the int8 collective wire.

The PR 8 transport planner's grad wire (``quantized_reduce_scatter`` /
``quantized_all_gather`` / ``quantized_all_reduce``) quantizes with
``quantize_blockwise``: on the XLA path the group absmax reduction, the
scale select, the round/clip and the int8 cast are separate ops the
compiler may or may not fuse across the reshape boundaries — each miss is
an extra HBM round trip on a buffer that exists only to be put on the
wire. This kernel does the whole pass per group-row block in one launch:
read the fp32 groups once, write the packed int8 payload + fp32 scale
sideband once (the "pack" half: payload and scales emerge launch-ready for
the all-to-all, no separate gather/cast program).

BYTE-IDENTITY CONTRACT: the kernel computes exactly
``quantize_blockwise``'s symmetric int8 math (absmax/127 scale, zero-scale
-> 1, round-half-even, clip [-128, 127]) so the wire payload is
byte-identical to the XLA path — ``DSTPU_COMM_QUANT=0`` and existing
committed wire budgets are untouched. Enforced by
tests/unit/ops/test_opt_kernels.py::TestQuantKernel. The contract is
stated (and tested) for JITTED programs — every wire path runs inside a
jitted shard_map region — because XLA's divide-by-constant rewrite may
differ by one ulp between an eager op-by-op run and any compiled program;
within compiled programs both paths resolve identically.

Dispatch rides ``DSTPU_QUANT_KERNEL`` with the shared semantics of
``DSTPU_OPT_KERNEL`` (``''``=auto: Pallas on TPU, XLA on CPU meshes;
``'xla'``/``'pallas'`` force — see ops/adam/pallas_adam.py). Only the
symmetric int8 lane-aligned case takes the kernel; int4 packing,
asymmetric zero-points and sub-lane group sizes keep the XLA path (they
are not on the default wire). Dequantize stays XLA on purpose: it feeds
the local sum / consuming matmul directly and fuses there — the quantize
side was the extra pass."""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..adam.pallas_adam import opt_kernel_interpret, opt_kernel_mode

_ROW_BLOCK = 32   # group rows per grid step (int8 sublane tile)


def quant_kernel_enabled(group_size: int, num_bits: int,
                         symmetric: bool) -> bool:
    """True when the fused kernel serves this quantization geometry."""
    return (num_bits == 8 and symmetric and group_size % 128 == 0
            and opt_kernel_mode("DSTPU_QUANT_KERNEL") == "pallas")


def _quant_rows_kernel(x_ref, q_out, s_out):
    x = x_ref[:].astype(jnp.float32)           # [bm, gs]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -128, 127).astype(jnp.int8)
    q_out[:] = q
    s_out[:] = scale[:, 0]


def quantize_rows_int8(groups: jax.Array, *, interpret=None
                       ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 row quantization of ``groups`` [G, group_size] in
    one fused launch. Returns ``(q int8 [G, gs], scale f32 [G])`` —
    byte-identical to the ``quantize_blockwise`` XLA path. Zero-padded
    rows (added to reach the row-block multiple) quantize to q=0/scale=1
    and are sliced off."""
    if interpret is None:
        interpret = opt_kernel_interpret()
    G, gs = groups.shape
    bm = min(_ROW_BLOCK, G)
    Gp = -(-G // bm) * bm
    x = groups.astype(jnp.float32)
    if Gp != G:
        x = jnp.pad(x, ((0, Gp - G), (0, 0)))
    spec = pl.BlockSpec((bm, gs), lambda i: (i, 0))
    q, s = pl.pallas_call(
        functools.partial(_quant_rows_kernel),
        grid=(Gp // bm,),
        in_specs=[spec],
        out_specs=[spec, pl.BlockSpec((bm,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((Gp, gs), jnp.int8),
                   jax.ShapeDtypeStruct((Gp,), jnp.float32)],
        interpret=interpret,
    )(x)
    if Gp != G:
        q, s = q[:G], s[:G]
    return q, s
