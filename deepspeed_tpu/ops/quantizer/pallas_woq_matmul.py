"""Builder-written Pallas weight-only-quantized matmul kernel.

The TPU counterpart of the reference's dequant+GEMM inference kernels
(``inference/v2/kernels/core_ops/cuda_linear`` and
``csrc/quantization``): int8 groupwise-quantized weights stream
HBM→VMEM at ONE byte per element and are dequantized in-register inside
the matmul — the bf16 weight tensor never exists in HBM.

Why this kernel exists (measured, tools/woq_matmul_ab.py, v5e,
2026-07-31): at decode shapes (M=8, llama2-7b MLP dims) XLA's einsum
form of the same math runs 1.5x SLOWER than plain bf16-dense — the
int8→bf16 convert + per-group partial products do not fuse into the
dot's operand stream, so quantization saves HBM *capacity* but loses
*latency*. Fusing the dequant into the matmul's VMEM pipeline makes the
weight traffic half of dense, which is the whole point of WOQ serving
on a bandwidth-bound decode.

Measured outcome on the attached chip (chained-scan probe, interleaved,
best-of-3): dense bf16 1.13 ms/step, XLA int8 1.58, THIS KERNEL 1.48
(shallow per-group dots, bn 5504/2048), deep-dot variants 1.64-1.74.
The kernel beats the XLA quantized path (~7%) but not dense — every
path sits ~5-10x above its HBM-bandwidth ideal, i.e. this environment
imposes a per-matmul floor that dominates decode shapes (the same floor
the paged-decode crossover hit). Disposition mirrors that kernel:
parity-tested, opt-in via ``DSTPU_PALLAS_WOQ=1`` in
``quantized_matmul``, default XLA until the floor is re-measured on a
direct-attached TPU.

Layout contract (the ``quantize_kernel`` format, quantization.py:73):
  q     [G, gs, N] int8/int4    scale [G, 1, N]
  x     [M, K]  (K = G*gs)  →  out [M, N] = Σ_g (x_g @ q_g) · scale_g

Grid: (N / bn, G) — G minor, so each n-tile's group partials accumulate
sequentially in a VMEM f32 scratch (TPU-guaranteed grid order); the
tile writes out once at g == G-1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128   # minor-dim granularity for every block
_MIN_M = 16   # bf16 sublane minimum: x rows pad up to 16


def _woq_kernel(x_ref, q_ref, s_ref, out_ref, acc_ref):
    """One program: gk groups of K against one N tile; the int8 block is
    dequantized (convert + per-group scale) in VMEM, one dot per block.
    The DEFAULT is gk=1 (shallow, one gs-deep dot per program) — the
    measured-fastest form on the attached chip (1.48 ms/step vs 1.64-1.74
    for deeper bk tiles; see module docstring) — deeper tiles are the
    ``bk`` experiment knob. Either way HBM moves one byte per weight."""
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                   # [Mp, gk*gs] bf16
    q = q_ref[...]                                   # [gk, gs, bn] int8
    gk, gs, bn = q.shape
    if gk == 1:
        # shallow form (the default, measured fastest): scale the PARTIAL
        # PRODUCT — M*bn multiplies instead of gs*bn on the weight tile
        part = jax.lax.dot_general(
            x, q[0].astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [Mp, bn]
        acc_ref[...] += part * s_ref[0].astype(jnp.float32)
    else:
        # deep form (bk experiment knob): dequant the block in VMEM so
        # one bk-deep dot replaces gk shallow ones
        w = q.astype(x.dtype) * s_ref[...].astype(x.dtype)
        acc_ref[...] += jax.lax.dot_general(
            x, w.reshape(gk * gs, bn), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [Mp, bn]

    @pl.when(kb == pl.num_programs(1) - 1)
    def _finalize():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _pick_bn(n: int, gs: int, mp: int = _MIN_M,
             vmem_budget: int = 1100 * 1024) -> int:
    """Largest lane-multiple tile of N that divides it and keeps the int8
    weight block + f32 accumulator (sized with the ACTUAL padded M, not
    the minimum) comfortably inside VMEM."""
    if n % _LANE:
        raise ValueError(f"N={n} is not a multiple of {_LANE}")
    best = 0
    for mult in range(1, n // _LANE + 1):
        bn = mult * _LANE
        if n % bn:
            continue
        if gs * bn + 4 * mp * bn > vmem_budget:
            break
        best = bn
    if not best:
        raise ValueError(
            f"no N tile fits the VMEM budget: even bn={_LANE} needs "
            f"{gs * _LANE + 4 * mp * _LANE} bytes (gs={gs}, Mp={mp}) > "
            f"{vmem_budget}; reduce the quantization group size or M")
    return best


@functools.partial(jax.jit, static_argnames=("interpret", "bn", "bk"))
def woq_matmul(x: jax.Array, q: jax.Array, scale: jax.Array,
               interpret: bool = False, bn: int | None = None,
               bk: int | None = None) -> jax.Array:
    """x [M, K] @ groupwise-quantized [K, N] weights -> [M, N].

    ``q`` [G, gs, N] int8, ``scale`` [G, 1, N] (the quantize_kernel
    format). M is padded to the bf16 sublane minimum internally. ``bn``
    overrides the N tile (must divide N; lane multiple); ``bk`` the K
    tile (a multiple of gs dividing K).
    """
    M, K = x.shape
    G, gs, N = q.shape
    assert K == G * gs, (K, G, gs)
    Mp = max(_MIN_M, -(-M // 8) * 8)
    bn = bn or _pick_bn(N, gs, Mp)
    bk = bk or _pick_bk(K, gs)
    gk = bk // gs
    assert bk % gs == 0 and G % gk == 0, (bk, gs, G)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))

    out = pl.pallas_call(
        _woq_kernel,
        grid=(N // bn, G // gk),
        in_specs=[
            pl.BlockSpec((Mp, bk), lambda n, kb: (0, kb)),
            pl.BlockSpec((gk, gs, bn), lambda n, kb: (kb, 0, n)),
            pl.BlockSpec((gk, 1, bn), lambda n, kb: (kb, 0, n)),
        ],
        out_specs=pl.BlockSpec((Mp, bn), lambda n, kb: (0, n)),
        scratch_shapes=[
            pltpu.VMEM((Mp, bn), jnp.float32),
        ],
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        interpret=interpret,
    )(x, q, scale)
    return out[:M]


def _pick_bk(k: int, gs: int) -> int:
    """Default K tile = one group (the shallow form). Deeper tiles trade
    per-group dots for one deep dot after a VMEM dequant — measured
    SLOWER on the attached v5e (1.64-1.74 vs 1.48 ms/step at llama MLP
    decode shapes, 2026-07-31), so depth is opt-in via the bk argument."""
    del k
    return gs
