from .fused_adam import fused_adam_reference, fused_adam_update  # noqa: F401
