"""Host (CPU) Adam for offloaded optimizer state.

Counterpart of the reference ``ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``)
over the C++ kernel in ``csrc/optimizers/cpu_optimizers.cpp`` (reference
``csrc/adam/cpu_adam_impl.cpp`` AVX path). Operates in place on flat numpy
fp32 buffers — the ZeRO-Offload layout where the host owns the master
params + moments and the TPU only sees bf16 params.

Since ISSUE 10 these classes are legacy-API shims over the shared kernel
dispatch: when no C++ toolchain is available, the fallback math routes
through the HOST backend of :mod:`.pallas_adam` (``host_adam_step`` /
``host_lion_step`` / ``host_adagrad_step``) — one statement of the update
shared with the Pallas bucket kernels, so the reference surface cannot
drift from the engine's fused path. Direct construction warns once; the
sanctioned internal users (``runtime/zero/offload_optimizer.py``,
``runtime/zero/param_stream.py``) pass ``_sanctioned=True``.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from ...utils.logging import warning_once
from ..op_builder.all_ops import CPUAdamBuilder
from .pallas_adam import host_adagrad_step, host_adam_step, host_lion_step


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _warn_direct(name: str, sanctioned: bool) -> None:
    if not sanctioned:
        warning_once(
            f"ops.adam.cpu_adam.{name} is a legacy shim (reference "
            "DeepSpeedCPUAdam surface); the offload/paged engines reach it "
            "through runtime/zero — its fallback math is the shared host "
            "backend of ops/adam/pallas_adam.py (DSTPU_OPT_KERNEL owns "
            "the device-side dispatch)")


class DeepSpeedCPUAdam:

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 fp32_optimizer_states: bool = True, _sanctioned: bool = False):
        _warn_direct("DeepSpeedCPUAdam", _sanctioned)
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self._lib = CPUAdamBuilder().load()
        self.step_count = 0

    @property
    def using_native(self) -> bool:
        return self._lib is not None

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             exp_avg_sq: np.ndarray, step: Optional[int] = None,
             lr: Optional[float] = None) -> None:
        """One in-place Adam step on flat contiguous fp32 arrays."""
        if step is None:
            self.step_count += 1
            step = self.step_count
        lr = self.lr if lr is None else lr
        for a in (params, grads, exp_avg, exp_avg_sq):
            assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"], \
                "cpu_adam needs contiguous fp32 buffers"
        if self._lib is not None:
            self._lib.ds_cpu_adam_step(
                _fp(params), _fp(exp_avg), _fp(exp_avg_sq), _fp(grads),
                params.size, lr, self.beta1, self.beta2, self.eps,
                self.weight_decay, step, int(self.adamw_mode))
            return
        # shared host backend (same math as the Pallas bucket kernel)
        host_adam_step(params, grads, exp_avg, exp_avg_sq, step=step, lr=lr,
                       beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                       weight_decay=self.weight_decay, adamw=self.adamw_mode)


class DeepSpeedCPULion:
    """Reference ``ops/lion/cpu_lion.py`` over csrc lion kernel."""

    def __init__(self, lr: float = 1e-4, betas=(0.9, 0.99),
                 weight_decay: float = 0.0, _sanctioned: bool = False):
        _warn_direct("DeepSpeedCPULion", _sanctioned)
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.weight_decay = weight_decay
        self._lib = CPUAdamBuilder().load()

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_cpu_lion_step(_fp(params), _fp(exp_avg), _fp(grads),
                                       params.size, lr, self.beta1, self.beta2,
                                       self.weight_decay)
            return
        host_lion_step(params, grads, exp_avg, lr=lr, beta1=self.beta1,
                       beta2=self.beta2, weight_decay=self.weight_decay)


class DeepSpeedCPUAdagrad:
    """Reference ``ops/adagrad/cpu_adagrad.py``."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, _sanctioned: bool = False):
        _warn_direct("DeepSpeedCPUAdagrad", _sanctioned)
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = CPUAdamBuilder().load()

    def step(self, params: np.ndarray, grads: np.ndarray, sq_sum: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_cpu_adagrad_step(_fp(params), _fp(sq_sum), _fp(grads),
                                          params.size, lr, self.eps,
                                          self.weight_decay)
            return
        host_adagrad_step(params, grads, sq_sum, lr=lr, eps=self.eps,
                          weight_decay=self.weight_decay)
