"""Host (CPU) Adam for offloaded optimizer state.

Counterpart of the reference ``ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``)
over the C++ kernel in ``csrc/optimizers/cpu_optimizers.cpp`` (reference
``csrc/adam/cpu_adam_impl.cpp`` AVX path). Operates in place on flat numpy
fp32 buffers — the ZeRO-Offload layout where the host owns the master
params + moments and the TPU only sees bf16 params. Falls back to a numpy
implementation when no C++ toolchain is available.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from ..op_builder.all_ops import CPUAdamBuilder


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 fp32_optimizer_states: bool = True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self._lib = CPUAdamBuilder().load()
        self.step_count = 0

    @property
    def using_native(self) -> bool:
        return self._lib is not None

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             exp_avg_sq: np.ndarray, step: Optional[int] = None,
             lr: Optional[float] = None) -> None:
        """One in-place Adam step on flat contiguous fp32 arrays."""
        if step is None:
            self.step_count += 1
            step = self.step_count
        lr = self.lr if lr is None else lr
        for a in (params, grads, exp_avg, exp_avg_sq):
            assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"], \
                "cpu_adam needs contiguous fp32 buffers"
        if self._lib is not None:
            self._lib.ds_cpu_adam_step(
                _fp(params), _fp(exp_avg), _fp(exp_avg_sq), _fp(grads),
                params.size, lr, self.beta1, self.beta2, self.eps,
                self.weight_decay, step, int(self.adamw_mode))
            return
        # numpy fallback (same math as the kernel)
        g = grads if self.adamw_mode else grads + self.weight_decay * params
        exp_avg *= self.beta1
        exp_avg += (1 - self.beta1) * g
        exp_avg_sq *= self.beta2
        exp_avg_sq += (1 - self.beta2) * g * g
        bc1 = 1.0 / (1.0 - self.beta1 ** step)
        bc2 = 1.0 / (1.0 - self.beta2 ** step)
        update = (exp_avg * bc1) / (np.sqrt(exp_avg_sq * bc2) + self.eps)
        if self.adamw_mode:
            update = update + self.weight_decay * params
        params -= lr * update


class DeepSpeedCPULion:
    """Reference ``ops/lion/cpu_lion.py`` over csrc lion kernel."""

    def __init__(self, lr: float = 1e-4, betas=(0.9, 0.99),
                 weight_decay: float = 0.0):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.weight_decay = weight_decay
        self._lib = CPUAdamBuilder().load()

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_cpu_lion_step(_fp(params), _fp(exp_avg), _fp(grads),
                                       params.size, lr, self.beta1, self.beta2,
                                       self.weight_decay)
            return
        c = self.beta1 * exp_avg + (1 - self.beta1) * grads
        params -= lr * (np.sign(c) + self.weight_decay * params)
        exp_avg *= self.beta2
        exp_avg += (1 - self.beta2) * grads


class DeepSpeedCPUAdagrad:
    """Reference ``ops/adagrad/cpu_adagrad.py``."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = CPUAdamBuilder().load()

    def step(self, params: np.ndarray, grads: np.ndarray, sq_sum: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_cpu_adagrad_step(_fp(params), _fp(sq_sum), _fp(grads),
                                          params.size, lr, self.eps,
                                          self.weight_decay)
            return
        g = grads + self.weight_decay * params
        sq_sum += g * g
        params -= lr * g / (np.sqrt(sq_sum) + self.eps)
