"""Fused Pallas optimizer-update kernels: Adam/AdamW (LAMB rides the same
kernel with a trust-ratio epilogue).

TPU-native replacement for the per-leaf elementwise ``update()`` tree in
``runtime/optimizers.py`` — the port target named by the SNIPPETS header
(rewrite ``csrc/adam/multi_tensor_adam.cu`` as a Pallas kernel). One launch
serves a flat dtype-BUCKET of leaves (the fused-buffer discipline of
``runtime/zero/overlap.py``: small leaves concatenate into one lane-padded
flat buffer, huge leaves stand alone), reading grad + fp32 master + both
moments once, computing the whole chain in fp32 in-register, and writing

- the new fp32 master,
- the bf16 compute-param cast (same pass — no separate recast program),
- both moments at their STORED dtype with **in-kernel stochastic
  rounding** for bf16 stores,

collapsing the ~6 HBM round-trips per leaf per slot the XLA elementwise
tree could pay (g, p, m, v read + m, v, p, cast written across fusion
boundaries) to one read/write per buffer. The fusion discipline is
EQuARX's (arXiv:2506.17615) applied to the moment update: do the
narrow-width math inside the launch instead of as separate XLA ops.

Stochastic rounding
-------------------
The SR noise comes from an in-kernel counter-based hash PRNG
(triple32-style xorshift-multiply over ``seed ^ element_index``), seeded
from ``(step, slot, bucket)`` — replacing the host-side ``_sr_to_bf16``
tree pass and its per-leaf ``fold_in`` keys. A portable hash is used
instead of ``pltpu.prng_random_bits`` deliberately: the Mosaic PRNG has no
CPU interpret lowering at this jax version, and the hash produces
IDENTICAL bits in interpret and compiled mode, so the fixed-seed
determinism tests pin the exact draws production uses. The rounding rule
matches ``_sr_to_bf16`` exactly (add uniform low 16 bits, truncate), so
both paths are unbiased with the same variance; only the draw realization
differs (covered by the mean-preservation tests on BOTH paths,
tests/unit/ops/test_opt_kernels.py).

Dispatch
--------
``DSTPU_OPT_KERNEL`` gates every step path (fused engine step, pipelined
ZeRO micro, offload dev-step — all funnel through ``Optimizer.update``):

- ``''`` (default): auto — Pallas on TPU backends, XLA elementwise tree on
  CPU meshes (the audit mesh and tier-1 run the pre-PR program bitwise).
- ``'xla'``: bitwise escape hatch to the elementwise tree everywhere.
- ``'pallas'``: force the kernel (interpret mode on CPU — the tests' path).

The host numpy backend (``host_adam_step``) serves the legacy
``DeepSpeedCPUAdam`` shim and the ZeRO-Offload runner so the reference API
surface shares ONE statement of the math with the kernel dispatch.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_LANES = 128          # TPU lane width; bucket rows are [R, 128]
_BLOCK_ROWS = 512     # rows per grid step: 64k elems = 256 KB fp32/operand
_SR_SALT = 0x51AB51AB  # matches the 0x51AB key family of _sr_to_bf16


# ---------------------------------------------------------------------------
# dispatch resolution (shared by adam/lion/quantizer kernels)
# ---------------------------------------------------------------------------

def opt_kernel_mode(env_var: str = "DSTPU_OPT_KERNEL") -> str:
    """Resolve an optimizer/quantizer kernel gate to 'pallas' | 'xla'.

    ''/'auto' = Pallas on TPU, XLA elsewhere (CPU meshes keep the escape
    hatch as the DEFAULT, so tier-1 and the audit mesh run the pre-PR
    program bitwise); 'xla' and 'pallas' force."""
    mode = os.environ.get(env_var, "").strip().lower()
    if mode not in ("", "auto", "xla", "pallas"):
        raise ValueError(f"{env_var} must be ''|'auto'|'xla'|'pallas', "
                         f"got {mode!r}")
    if mode in ("xla", "pallas"):
        return mode
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def opt_kernel_interpret() -> bool:
    """Pallas interpret mode off-TPU (CPU tests compile the kernel body to
    plain HLO — the same program GSPMD partitions for the lint entry)."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# counter-hash PRNG + stochastic rounding
# ---------------------------------------------------------------------------

def _hash32(x):
    """triple32 (Wellons) avalanche hash on uint32 — plain VPU arithmetic,
    identical under interpret and Mosaic compilation."""
    x = x ^ (x >> 17)
    x = x * jnp.uint32(0xED5AD4BB)
    x = x ^ (x >> 11)
    x = x * jnp.uint32(0xAC4C1B51)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x31848BAB)
    x = x ^ (x >> 14)
    return x


def sr_seed(step, slot: int, bucket: int):
    """The (step, slot, bucket) stream seed. ``slot`` follows the
    ``_narrow_state_tree`` numbering (exp_avg=1, exp_avg_sq=2, sum_sq=3)
    so the two SR slots of one step never share a stream; ``bucket`` is
    the launch index within the step. Traced on ``step``."""
    s = jnp.asarray(step, jnp.uint32) ^ jnp.uint32(_SR_SALT)
    s = _hash32(s ^ jnp.uint32((slot * 0x9E3779B9) & 0xFFFFFFFF))
    s = _hash32(s ^ jnp.uint32((bucket * 0x85EBCA6B) & 0xFFFFFFFF))
    return s


def _sr_to_bf16_bits(x_f32, noise_u32):
    """The _sr_to_bf16 rounding rule on explicit noise: add uniform low
    16 bits, truncate to the bf16 prefix. E[stored] == value."""
    bits = jax.lax.bitcast_convert_type(x_f32, jnp.uint32)
    bits = (bits + (noise_u32 & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(bits, jnp.float32).astype(jnp.bfloat16)


def _store(x_f32, dtype, seed_scalar, idx_u32, use_sr: bool):
    """Narrow ``x`` to its stored dtype. bf16 stores are stochastically
    rounded from the (seed, element-index) hash stream; everything else is
    the plain RTN cast — exactly ``_narrow_state_tree``'s rule."""
    if use_sr and jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16):
        return _sr_to_bf16_bits(x_f32, _hash32(idx_u32 ^ seed_scalar))
    return x_f32.astype(dtype)


def _global_idx(block_elems: int, shape) -> jax.Array:
    """uint32 global element index of each position in the current block
    (stable under block-size changes: index = bucket-flat offset)."""
    base = (pl.program_id(0) * block_elems).astype(jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    return base + rows * jnp.uint32(shape[1]) + cols


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _adam_kernel(g_ref, p_ref, m_ref, v_ref, scal_ref, seed_ref, *out_refs,
                 mode, beta1, beta2, eps, weight_decay,
                 sr_m, sr_v, m_dtype, v_dtype, param_dtype, block_elems):
    """One block of the fused step. ``scal`` = [lr, bcd1, bcd2, gscale]
    (bias-correction DENOMINATORS 1-b^t, matching the elementwise tree's
    division form so the fp32 math is bit-identical to optimizers.py).
    ``mode``: 'adam' (coupled wd) | 'adamw' (decoupled) | 'lamb' (no bias
    correction; emits the un-trust-scaled update for the XLA epilogue)."""
    f32 = jnp.float32
    lr = scal_ref[0]
    bcd1 = scal_ref[1]
    bcd2 = scal_ref[2]
    g = g_ref[:].astype(f32) * scal_ref[3]
    p = p_ref[:].astype(f32)
    m = m_ref[:].astype(f32)
    v = v_ref[:].astype(f32)

    if mode == "adam" and weight_decay:
        g = g + weight_decay * p
    m2 = beta1 * m + (1.0 - beta1) * g
    v2 = beta2 * v + (1.0 - beta2) * g * g

    if mode == "lamb":
        u = m2 / (jnp.sqrt(v2) + eps) + weight_decay * p
        refs = list(out_refs)
        refs.pop(0)[:] = u
    else:
        mhat = m2 / bcd1
        vhat = v2 / bcd2
        u = mhat / (jnp.sqrt(vhat) + eps)
        if mode == "adamw" and weight_decay:
            u = u + weight_decay * p
        p2 = p - lr * u
        refs = list(out_refs)
        refs.pop(0)[:] = p2
        if param_dtype is not None:
            refs.pop(0)[:] = p2.astype(param_dtype)

    idx = _global_idx(block_elems, g.shape) if (sr_m or sr_v) else None
    refs.pop(0)[:] = _store(m2, m_dtype, seed_ref[0], idx, sr_m)
    refs.pop(0)[:] = _store(v2, v_dtype, seed_ref[1], idx, sr_v)


def _pad_to_rows(x: jax.Array, padded: int) -> jax.Array:
    """Flat 1-D -> [R, 128] with inert zero tail padding (zeros are a
    fixed point of every supported update: g=p=m=v=0 -> all outputs 0)."""
    if x.size != padded:
        x = jnp.pad(x.reshape(-1), (0, padded - x.size))
    return x.reshape(padded // _LANES, _LANES)


def bucket_geometry(n: int, block_rows: int = _BLOCK_ROWS
                    ) -> Tuple[int, int, int]:
    """(padded_elems, block_rows, grid) for an n-element flat bucket."""
    rows = -(-n // _LANES)
    bm = min(block_rows, rows)
    rows_p = -(-rows // bm) * bm
    return rows_p * _LANES, bm, rows_p // bm


def adam_bucket_update(grads: jax.Array, master: jax.Array,
                       exp_avg: jax.Array, exp_avg_sq: jax.Array, *,
                       step, lr, beta1: float = 0.9, beta2: float = 0.999,
                       eps: float = 1e-8, weight_decay: float = 0.0,
                       mode: str = "adamw", grad_scale=None,
                       seed_m=None, seed_v=None,
                       m_dtype=jnp.float32, v_dtype=jnp.float32,
                       param_dtype=None, sr: bool = True,
                       block_rows: int = _BLOCK_ROWS,
                       interpret: bool = False, alias: bool = True):
    """One fused step on a flat bucket. Returns
    ``(master_out, param_cast, m_store, v_store)`` where ``master_out`` is
    the new fp32 master for 'adam'/'adamw' and the UN-trust-scaled LAMB
    update for 'lamb' (apply :func:`lamb_trust_epilogue` per leaf);
    ``param_cast`` is None unless ``param_dtype`` is given (or lamb).

    ``alias``: when the bucket needs no padding, the master/moment
    operands alias their outputs (``input_output_aliases``) so the jitted
    caller's donation is a true in-place update — the fp32 moments never
    exist twice at peak. The lint entry ``fused-optimizer-step`` machine-
    checks exactly this via the dead-donation rule."""
    assert grads.ndim == 1, "bucket updates operate on flat buffers"
    assert mode in ("adam", "adamw", "lamb"), mode
    n = grads.shape[0]
    padded, bm, grid = bucket_geometry(n, block_rows)
    stepf = jnp.asarray(step, jnp.float32)
    scal = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        1.0 - jnp.asarray(beta1, jnp.float32) ** stepf,
        1.0 - jnp.asarray(beta2, jnp.float32) ** stepf,
        jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32),
    ])
    zero_seed = jnp.zeros((), jnp.uint32)
    seeds = jnp.stack([zero_seed if seed_m is None else seed_m,
                       zero_seed if seed_v is None else seed_v])

    sr_m = sr and jnp.dtype(m_dtype) == jnp.dtype(jnp.bfloat16)
    sr_v = sr and jnp.dtype(v_dtype) == jnp.dtype(jnp.bfloat16)
    g2 = _pad_to_rows(grads, padded)
    p2 = _pad_to_rows(master, padded)
    m2 = _pad_to_rows(exp_avg, padded)
    v2 = _pad_to_rows(exp_avg_sq, padded)

    spec = pl.BlockSpec((bm, _LANES), lambda i: (i, 0))
    svec = pl.BlockSpec((4,), lambda i: (0,))
    seed_spec = pl.BlockSpec((2,), lambda i: (0,))
    rows_p = padded // _LANES
    shp = lambda dt: jax.ShapeDtypeStruct((rows_p, _LANES), dt)
    lamb = mode == "lamb"
    want_pc = param_dtype is not None and not lamb
    out_shape = [shp(jnp.float32)]
    if want_pc:
        out_shape.append(shp(param_dtype))
    out_shape += [shp(m_dtype), shp(v_dtype)]
    out_specs = [spec] * len(out_shape)

    aliases = {}
    if alias and padded == n:
        # operand indices: g=0 p=1 m=2 v=3; outputs: [p2, (pc), m, v].
        # p/m/v alias in->out when dtypes agree (they always do for the
        # moments — stored dtype in, stored dtype out); the dead grad
        # aliases the param cast when the compute dtype matches.
        pc_off = 1 if want_pc else 0
        if not lamb and jnp.dtype(master.dtype) == jnp.dtype(jnp.float32):
            aliases[1] = 0
        if want_pc and jnp.dtype(grads.dtype) == jnp.dtype(param_dtype):
            aliases[0] = 1
        if jnp.dtype(exp_avg.dtype) == jnp.dtype(m_dtype):
            aliases[2] = 1 + pc_off
        if jnp.dtype(exp_avg_sq.dtype) == jnp.dtype(v_dtype):
            aliases[3] = 2 + pc_off

    outs = pl.pallas_call(
        functools.partial(
            _adam_kernel, mode=mode, beta1=float(beta1), beta2=float(beta2),
            eps=float(eps), weight_decay=float(weight_decay),
            sr_m=sr_m, sr_v=sr_v, m_dtype=jnp.dtype(m_dtype),
            v_dtype=jnp.dtype(v_dtype),
            param_dtype=jnp.dtype(param_dtype) if want_pc else None,
            block_elems=bm * _LANES),
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, svec, seed_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(g2, p2, m2, v2, scal, seeds)

    outs = [o.reshape(-1)[:n] for o in outs]
    if lamb:
        return outs[0], None, outs[1], outs[2]
    if want_pc:
        return outs[0], outs[1], outs[2], outs[3]
    return outs[0], None, outs[1], outs[2]


def lamb_trust_epilogue(p_f32: jax.Array, update: jax.Array, *, lr,
                        min_coeff: float, max_coeff: float) -> jax.Array:
    """Per-leaf LAMB trust scaling over one leaf's slice of the bucket
    update (norms are per-LEAF reductions, so they stay an XLA epilogue —
    the elementwise chain that dominated the HBM traffic is in-kernel).
    Mirrors ``Optimizer._lamb_leaf``'s trust clause exactly."""
    w_norm = jnp.linalg.norm(p_f32)
    u_norm = jnp.linalg.norm(update)
    trust = jnp.where((w_norm > 0) & (u_norm > 0),
                      jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
    return p_f32 - lr * trust * update


# ---------------------------------------------------------------------------
# host (numpy) backend — the DeepSpeedCPUAdam / ZeRO-Offload statement of
# the same math (one source; the shims route here)
# ---------------------------------------------------------------------------

def host_adam_step(params: np.ndarray, grads: np.ndarray,
                   exp_avg: np.ndarray, exp_avg_sq: np.ndarray, *,
                   step: int, lr: float, beta1: float = 0.9,
                   beta2: float = 0.999, eps: float = 1e-8,
                   weight_decay: float = 0.0, adamw: bool = True) -> None:
    """In-place Adam/AdamW on flat contiguous fp32 host buffers (the
    ZeRO-Offload layout). Same math as :func:`_adam_kernel` mode
    'adam'/'adamw' in the multiply-by-reciprocal form the C++ kernel uses."""
    g = grads if adamw else grads + weight_decay * params
    exp_avg *= beta1
    exp_avg += (1 - beta1) * g
    exp_avg_sq *= beta2
    exp_avg_sq += (1 - beta2) * g * g
    bc1 = 1.0 / (1.0 - beta1 ** step)
    bc2 = 1.0 / (1.0 - beta2 ** step)
    update = (exp_avg * bc1) / (np.sqrt(exp_avg_sq * bc2) + eps)
    if adamw:
        update = update + weight_decay * params
    params -= lr * update


def host_lion_step(params: np.ndarray, grads: np.ndarray,
                   exp_avg: np.ndarray, *, lr: float, beta1: float = 0.9,
                   beta2: float = 0.99, weight_decay: float = 0.0) -> None:
    """In-place Lion on flat fp32 host buffers (see ``host_adam_step``)."""
    c = beta1 * exp_avg + (1 - beta1) * grads
    params -= lr * (np.sign(c) + weight_decay * params)
    exp_avg *= beta2
    exp_avg += (1 - beta2) * grads


def host_adagrad_step(params: np.ndarray, grads: np.ndarray,
                      sq_sum: np.ndarray, *, lr: float, eps: float = 1e-10,
                      weight_decay: float = 0.0) -> None:
    """In-place Adagrad on flat fp32 host buffers (see ``host_adam_step``)."""
    g = grads + weight_decay * params
    sq_sum += g * g
    params -= lr * g / (np.sqrt(sq_sum) + eps)
