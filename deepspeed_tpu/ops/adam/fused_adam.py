"""Legacy fused-Adam shim over the bucket kernel dispatch.

The reference-API surface (``FusedAdam`` over ``csrc/adam/
multi_tensor_adam.cu``, ``fused_adam_frontend.cpp:22``) kept alive as a
thin router: since ISSUE 10 the actual kernel lives in
:mod:`.pallas_adam` (one launch per flat bucket, in-kernel SR, aliasing)
and the engine dispatches it through ``Optimizer.update`` behind
``DSTPU_OPT_KERNEL`` — direct calls here warn once and forward to the
same kernel so the two surfaces cannot drift numerically.

``fused_adam_reference`` (the pure-jnp mirror the parity tests pin) is
unchanged.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.logging import warning_once
from .pallas_adam import adam_bucket_update

_BLOCK = 1024 * 128  # legacy block size in ELEMENTS (multiple of (8,128))


def fused_adam_update(grads: jax.Array, params: jax.Array, exp_avg: jax.Array,
                      exp_avg_sq: jax.Array, step: jax.Array, lr, beta1=0.9,
                      beta2=0.999, eps=1e-8, weight_decay=0.0, adamw: bool = True,
                      interpret: bool = False,
                      block_size: int = _BLOCK) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One Adam step on flat fp32 buffers. Returns (params, m, v).

    Legacy entry point — routes through the ISSUE 10 bucket kernel
    (:func:`.pallas_adam.adam_bucket_update`, fp32 moments, no SR). New
    code should let ``Optimizer.update`` dispatch (``DSTPU_OPT_KERNEL``)
    so moment dtypes, stochastic rounding and the param cast ride along."""
    warning_once(
        "ops.adam.fused_adam_update is a legacy shim; the engine "
        "dispatches the fused optimizer kernels via runtime/optimizers.py "
        "(DSTPU_OPT_KERNEL) — routing this call through ops/adam/"
        "pallas_adam.py")
    assert grads.ndim == 1, "fused_adam_update operates on flat shards"
    p, _, m, v = adam_bucket_update(
        grads.astype(jnp.float32), params.astype(jnp.float32),
        exp_avg, exp_avg_sq, step=step, lr=lr, beta1=beta1, beta2=beta2,
        eps=eps, weight_decay=weight_decay,
        mode="adamw" if adamw else "adam", sr=False,
        block_rows=max(1, block_size // 128), interpret=interpret)
    return p, m, v


def fused_adam_reference(grads, params, m, v, step, lr, beta1=0.9, beta2=0.999,
                         eps=1e-8, weight_decay=0.0, adamw=True):
    """Pure-jnp reference for parity tests (mirrors the kernel math)."""
    g = grads.astype(jnp.float32)
    if not adamw:
        g = g + weight_decay * params
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    stepf = step.astype(jnp.float32)
    mhat = m2 / (1 - beta1 ** stepf)
    vhat = v2 / (1 - beta2 ** stepf)
    update = mhat / (jnp.sqrt(vhat) + eps)
    if adamw:
        update = update + weight_decay * params
    return params - lr * update, m2, v2
