"""Pallas fused Adam.

TPU-native counterpart of the reference's multi-tensor fused Adam
(``csrc/adam/multi_tensor_adam.cu``, ``fused_adam_frontend.cpp:22``): one
kernel pass updating params + both moments in place over a flat shard,
avoiding one HBM round-trip per tensor per quantity that a naive chain of
elementwise jnp ops could incur if XLA declined to fuse.

The kernel runs on 1-D flat buffers (the ZeRO flat-partition layout) tiled
into VMEM blocks; bias correction is precomputed on the host side of the
trace (scalars). On CPU (tests) the kernel runs in interpret mode with
identical semantics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK = 1024 * 128  # elements per grid step; multiple of (8,128) tiles


def _adam_kernel(g_ref, p_ref, m_ref, v_ref, scal_ref,
                 p_out, m_out, v_out):
    lr = scal_ref[0]
    beta1 = scal_ref[1]
    beta2 = scal_ref[2]
    eps = scal_ref[3]
    wd = scal_ref[4]
    bc1 = scal_ref[5]  # 1 / (1 - b1^t)
    bc2 = scal_ref[6]  # 1 / (1 - b2^t)
    decoupled = scal_ref[7]  # 1.0 => adamw

    g = g_ref[:]
    p = p_ref[:]
    # adam-style (coupled) weight decay folds into the gradient
    g = jnp.where(decoupled > 0, g, g + wd * p)
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    update = (m * bc1) / (jnp.sqrt(v * bc2) + eps)
    update = jnp.where(decoupled > 0, update + wd * p, update)
    p_out[:] = p - lr * update
    m_out[:] = m
    v_out[:] = v


@functools.partial(jax.jit, static_argnames=("adamw", "interpret", "block_size"))
def fused_adam_update(grads: jax.Array, params: jax.Array, exp_avg: jax.Array,
                      exp_avg_sq: jax.Array, step: jax.Array, lr, beta1=0.9,
                      beta2=0.999, eps=1e-8, weight_decay=0.0, adamw: bool = True,
                      interpret: bool = False,
                      block_size: int = _BLOCK) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One Adam step on flat fp32 buffers. Returns (params, m, v)."""
    assert grads.ndim == 1, "fused_adam_update operates on flat shards"
    n = grads.shape[0]
    stepf = step.astype(jnp.float32)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        1.0 / (1.0 - jnp.asarray(beta1, jnp.float32) ** stepf),
        1.0 / (1.0 - jnp.asarray(beta2, jnp.float32) ** stepf),
        jnp.asarray(1.0 if adamw else 0.0, jnp.float32),
    ])

    block = min(block_size, n)
    if n % block != 0:  # pad to a whole number of blocks
        pad = block - n % block
        grads = jnp.pad(grads, (0, pad))
        params_p = jnp.pad(params, (0, pad))
        m_p = jnp.pad(exp_avg, (0, pad))
        v_p = jnp.pad(exp_avg_sq, (0, pad))
    else:
        pad = 0
        params_p, m_p, v_p = params, exp_avg, exp_avg_sq

    total = grads.shape[0]
    grid = (total // block,)
    spec = pl.BlockSpec((block,), lambda i: (i,))
    scal_spec = pl.BlockSpec((8,), lambda i: (0,))
    out_shape = [jax.ShapeDtypeStruct((total,), jnp.float32)] * 3
    p_new, m_new, v_new = pl.pallas_call(
        _adam_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec, scal_spec],
        out_specs=[spec, spec, spec],
        out_shape=out_shape,
        interpret=interpret,
    )(grads.astype(jnp.float32), params_p.astype(jnp.float32), m_p, v_p, scalars)
    if pad:
        p_new, m_new, v_new = p_new[:n], m_new[:n], v_new[:n]
    return p_new, m_new, v_new


def fused_adam_reference(grads, params, m, v, step, lr, beta1=0.9, beta2=0.999,
                         eps=1e-8, weight_decay=0.0, adamw=True):
    """Pure-jnp reference for parity tests (mirrors the kernel math)."""
    g = grads.astype(jnp.float32)
    if not adamw:
        g = g + weight_decay * params
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    stepf = step.astype(jnp.float32)
    mhat = m2 / (1 - beta1 ** stepf)
    vhat = v2 / (1 - beta2 ** stepf)
    update = mhat / (jnp.sqrt(vhat) + eps)
    if adamw:
        update = update + weight_decay * params
    return params - lr * update, m2, v2
