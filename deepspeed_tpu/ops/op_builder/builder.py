"""Native-op JIT build + load layer.

Counterpart of the reference ``op_builder/builder.py`` (``OpBuilder.load``
:462,480 — JIT-compile native sources on first use via
``torch.utils.cpp_extension.load``, else use prebuilt). Torch-free TPU
version: sources under ``csrc/`` compile with g++ into a shared library in a
per-machine cache dir, loaded via ctypes. Python wrappers keep numpy
fallbacks so every op degrades gracefully when no toolchain exists
(reference ``is_compatible`` checks, ``op_builder/no_impl.py``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from pathlib import Path
from typing import List, Optional

from ...utils.logging import logger


def get_default_compute_capabilities() -> str:
    """Reference API parity; meaningless on TPU (no CUDA arch list)."""
    return ""


def _csrc_root() -> Path:
    # repo layout: <root>/csrc next to the deepspeed_tpu package
    return Path(__file__).resolve().parents[3] / "csrc"


def _cache_dir() -> Path:
    base = os.environ.get("DSTPU_OP_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "deepspeed_tpu", "ops"))
    p = Path(base)
    p.mkdir(parents=True, exist_ok=True)
    return p


class OpBuilder:
    NAME = "op"
    SOURCES: List[str] = []        # relative to csrc/
    EXTRA_FLAGS: List[str] = []

    _loaded: Optional[ctypes.CDLL] = None
    _load_failed = False

    def sources(self) -> List[Path]:
        return [_csrc_root() / s for s in self.SOURCES]

    def is_compatible(self) -> bool:
        return shutil.which("g++") is not None and all(
            s.exists() for s in self.sources())

    def _lib_path(self) -> Path:
        h = hashlib.sha256()
        for s in self.sources():
            h.update(s.read_bytes())
        h.update(" ".join(self.EXTRA_FLAGS).encode())
        return _cache_dir() / f"lib{self.NAME}_{h.hexdigest()[:12]}.so"

    def build(self) -> Path:
        lib = self._lib_path()
        if lib.exists():
            return lib
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
               "-o", str(lib)] + [str(s) for s in self.sources()] + self.EXTRA_FLAGS
        logger.info(f"building native op '{self.NAME}': {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:  # pragma: no cover
            # -march=native can fail on exotic hosts; retry portable
            cmd.remove("-march=native")
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e2:
                raise RuntimeError(
                    f"native build of {self.NAME} failed:\n{e.stderr}\n{e2.stderr}")
        return lib

    def load(self) -> Optional[ctypes.CDLL]:
        """JIT-or-cached load (reference builder.py:462). Returns None when
        the op can't be built (callers fall back to pure numpy/jnp)."""
        cls = type(self)
        if cls._loaded is not None:
            return cls._loaded
        if cls._load_failed:
            return None
        if not self.is_compatible():
            cls._load_failed = True
            logger.warning(f"native op '{self.NAME}' unavailable (no toolchain "
                           f"or sources); using fallback")
            return None
        try:
            lib = ctypes.CDLL(str(self.build()))
            self._bind(lib)
            cls._loaded = lib
            return lib
        except Exception as e:  # pragma: no cover
            cls._load_failed = True
            logger.warning(f"native op '{self.NAME}' failed to load ({e}); "
                           f"using fallback")
            return None

    def _bind(self, lib: ctypes.CDLL) -> None:
        """Declare argtypes/restypes; subclasses override."""
