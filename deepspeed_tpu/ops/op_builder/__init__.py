from .builder import OpBuilder, get_default_compute_capabilities  # noqa: F401
from .all_ops import ALL_OPS, AsyncIOBuilder, CPUAdagradBuilder, CPUAdamBuilder, CPULionBuilder  # noqa: F401
