"""Registry of native op builders (reference ``op_builder/all_ops.py:33``
``ALL_OPS``)."""

from __future__ import annotations

import ctypes

from .builder import OpBuilder

c_i64 = ctypes.c_int64
c_f32 = ctypes.c_float
c_fp = ctypes.POINTER(ctypes.c_float)


class AsyncIOBuilder(OpBuilder):
    """Reference ``op_builder/async_io.py`` — csrc/aio."""
    NAME = "dstpu_aio"
    SOURCES = ["aio/dstpu_aio.cpp"]
    EXTRA_FLAGS = ["-pthread"]

    def _bind(self, lib):
        lib.aio_create.argtypes = [c_i64, ctypes.c_int, ctypes.c_int]
        lib.aio_create.restype = ctypes.c_void_p
        lib.aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.aio_pread, lib.aio_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           c_i64, c_i64]
            fn.restype = c_i64
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        lib.aio_wait.restype = c_i64
        lib.aio_pending.argtypes = [ctypes.c_void_p]
        lib.aio_pending.restype = c_i64
        for fn in (lib.aio_read_sync, lib.aio_write_sync):
            fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, c_i64, c_i64, c_i64]
            fn.restype = ctypes.c_int


class CPUAdamBuilder(OpBuilder):
    """Reference ``op_builder/cpu_adam.py`` — csrc/adam/cpu_adam.cpp."""
    NAME = "dstpu_cpu_optimizers"
    SOURCES = ["optimizers/cpu_optimizers.cpp"]
    EXTRA_FLAGS = ["-fopenmp-simd", "-ffast-math"]

    def _bind(self, lib):
        lib.ds_cpu_adam_step.argtypes = [c_fp, c_fp, c_fp, c_fp, c_i64, c_f32,
                                         c_f32, c_f32, c_f32, c_f32, c_i64,
                                         ctypes.c_int]
        lib.ds_cpu_lion_step.argtypes = [c_fp, c_fp, c_fp, c_i64, c_f32, c_f32,
                                         c_f32, c_f32]
        lib.ds_cpu_adagrad_step.argtypes = [c_fp, c_fp, c_fp, c_i64, c_f32,
                                            c_f32, c_f32]


class CPULionBuilder(CPUAdamBuilder):
    """Same shared library; separate name for registry parity
    (reference ``op_builder/cpu_lion.py``)."""


class CPUAdagradBuilder(CPUAdamBuilder):
    """Reference ``op_builder/cpu_adagrad.py``."""


ALL_OPS = {
    "async_io": AsyncIOBuilder,
    "cpu_adam": CPUAdamBuilder,
    "cpu_lion": CPULionBuilder,
    "cpu_adagrad": CPUAdagradBuilder,
}
