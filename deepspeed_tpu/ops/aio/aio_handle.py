"""Python wrapper over the native AIO engine.

Counterpart of the reference ``aio_handle``
(``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp``): async pread/pwrite of
tensors to files with explicit synchronize, the primitive under ZeRO-Infinity
NVMe swapping (``runtime/swap_tensor``). Buffers are numpy arrays (host
memory — the TPU equivalent of the reference's pinned CPU tensors); a pure-
Python thread-pool fallback keeps the API available without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
from concurrent.futures import Future, ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from ..op_builder.all_ops import AsyncIOBuilder


def aio_available() -> bool:
    return AsyncIOBuilder().load() is not None


class AsyncIOHandle:
    """API mirror of the reference aio_handle: async_pread/async_pwrite
    accumulate in-flight ops; wait() blocks for all and returns the count."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 num_threads: int = 2):
        self.block_size = block_size
        self._lib = AsyncIOBuilder().load()
        self._handle = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._futures: List[Future] = []
        # The native engine reads/writes the buffer from worker threads via a
        # raw pointer; callers routinely pass temporaries
        # (np.ascontiguousarray(...).reshape(-1)), so the handle must keep
        # them alive until wait() or the C++ side reads freed memory.
        self._inflight: List[np.ndarray] = []
        if self._lib is not None:
            self._handle = self._lib.aio_create(block_size, queue_depth, num_threads)
        else:  # pure-python fallback
            self._pool = ThreadPoolExecutor(max_workers=num_threads)

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _buf(arr: np.ndarray):
        assert arr.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
        return arr.ctypes.data_as(ctypes.c_char_p)

    # -- async ops -----------------------------------------------------------
    def async_pwrite(self, buffer: np.ndarray, path: str, file_offset: int = 0) -> None:
        if self._handle is not None:
            ptr = self._buf(buffer)  # may reject; don't pin a rejected buffer
            self._inflight.append(buffer)
            self._lib.aio_pwrite(self._handle, ptr,
                                 path.encode(), buffer.nbytes, file_offset)
        else:
            def write(b=buffer, p=path, off=file_offset):
                with open(p, "r+b" if os.path.exists(p) else "wb") as f:
                    f.seek(off)
                    f.write(b.tobytes())
            self._futures.append(self._pool.submit(write))

    def async_pread(self, buffer: np.ndarray, path: str, file_offset: int = 0) -> None:
        if self._handle is not None:
            ptr = self._buf(buffer)  # may reject; don't pin a rejected buffer
            self._inflight.append(buffer)
            self._lib.aio_pread(self._handle, ptr,
                                path.encode(), buffer.nbytes, file_offset)
        else:
            def read(b=buffer, p=path, off=file_offset):
                with open(p, "rb") as f:
                    f.seek(off)
                    data = f.read(b.nbytes)
                b[...] = np.frombuffer(data, dtype=b.dtype).reshape(b.shape)
            self._futures.append(self._pool.submit(read))

    def wait(self) -> int:
        """Block until all in-flight ops complete; returns completed count.
        Raises OSError on any IO failure (reference: negative return)."""
        if self._handle is not None:
            rc = self._lib.aio_wait(self._handle)
            self._inflight.clear()
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc))
            return int(rc)
        # Drain EVERY future before raising (the native engine also waits
        # for completed == submitted before reporting an error): clearing on
        # the first failure would leave ops still running in the pool while
        # the caller believes the handle is idle and reuses their buffers.
        n, first_err = 0, None
        for f in self._futures:
            try:
                f.result()
                n += 1
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_err is None:
                    first_err = e
        self._futures.clear()
        if first_err is not None:
            raise first_err
        return n

    def pending(self) -> int:
        if self._handle is not None:
            return int(self._lib.aio_pending(self._handle))
        return sum(0 if f.done() else 1 for f in self._futures)

    # -- sync ops ------------------------------------------------------------
    def sync_pwrite(self, buffer: np.ndarray, path: str, file_offset: int = 0) -> None:
        self.async_pwrite(buffer, path, file_offset)
        self.wait()

    def sync_pread(self, buffer: np.ndarray, path: str, file_offset: int = 0) -> None:
        self.async_pread(buffer, path, file_offset)
        self.wait()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.aio_destroy(self._handle)  # joins worker threads
            self._handle = None
            self._inflight.clear()
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
