"""Checkpoint persistence.

Counterpart of the reference's engine save/load path (``engine.py:3050``
``save_checkpoint`` → tag dirs + ``latest`` file; ``:2688`` ``load_checkpoint``)
and the pluggable ``CheckpointEngine`` (checkpoint_engine.py:9).

Layout (tag-based dirs like the reference):

    <dir>/<tag>/state.npz        # flattened pytree leaves (gathered to host)
    <dir>/<tag>/meta.json        # treedef paths, dtypes, checksums, client state
    <dir>/latest                 # text file holding the newest tag

Single-process runs save leaves *unsharded* (``jax.device_get`` gathers).
Multi-host runs save per-process shard files (``state.rank{p}.npz``) — each
process writes only the pieces whose ``replica_id == 0`` live on its
devices (the reference's per-dp-rank zero shards, ``engine.py:3467``),
because remote shards are not addressable and a full gather would be both
impossible and wasteful. On load the rank files reassemble by global index
and leaves are re-placed with the engine's sharding tree, so a checkpoint
written under one topology/process count loads under any other — the
"universal checkpoint" property the reference needs a whole offline tool
for (``checkpoint/ds_to_universal.py``) falls out of addressing params by
logical name.

Durability contract (dstpu-resilience, docs/RESILIENCE.md):

- every data file lands via temp-name + ``os.replace`` (+ fsync) — a kill
  at any instruction leaves either the old bytes or the new bytes, never
  a torn file under a committed name;
- ``meta.json`` is the commit record, written after the data it describes
  and carrying a crc32 per data file; ``latest`` repoints after that;
- transient ``OSError`` s retry with exponential backoff
  (``DSTPU_CKPT_RETRIES`` / ``DSTPU_CKPT_BACKOFF_S``);
- :func:`load_checkpoint` verifies checksums (hatch:
  ``DSTPU_CKPT_VERIFY=0``) and, when ``latest`` names a tag that fails
  verification, falls back to the newest tag that passes — and raises
  rather than silently re-initializing when none does;
- :func:`retire_old_tags` implements keep-last-N retention without ever
  deleting the tag ``latest`` names.

Fault-injection seams (``resilience/fault_plan.py``) hook the write path
at ``ckpt_io`` (before an attempt) and ``ckpt_tmp`` (between temp write
and rename) — host-side only.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..resilience.fault_plan import fault_point
from ..utils.logging import logger


def _flatten_with_paths(tree) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = leaf
    return flat


def _owned_pieces(i: int, v) -> Dict[str, np.ndarray]:
    """This process's canonical pieces of leaf i: addressable shards with
    ``replica_id == 0`` (exactly one copy of every byte exists across all
    rank files). Piece key encodes the global index:
    ``leaf_{i}__{start}_{stop}__{start}_{stop}...``."""
    out = {}
    for s in v.addressable_shards:
        if s.replica_id != 0:
            continue
        idx = s.index if s.index else ()
        spans = "__".join(
            f"{sl.start or 0}_{sl.stop if sl.stop is not None else v.shape[d]}"
            for d, sl in enumerate(idx))
        out[f"leaf_{i}__{spans}" if spans else f"leaf_{i}__full"] = (
            np.asarray(s.data))
    return out


def stage_state(state) -> Tuple[list, Dict[str, np.ndarray]]:
    """Pull the state to host NOW (device buffers may be donated by the
    next step) — the synchronous half of a write-behind save. Returns
    ``(sorted keys, host arrays by key)``."""
    flat = _flatten_with_paths(state)
    keys = sorted(flat.keys())
    return keys, {k: np.asarray(jax.device_get(flat[k])) for k in keys}


# ---------------------------------------------------------------------------
# durable-write primitives
# ---------------------------------------------------------------------------
def _io_retries() -> int:
    return int(os.environ.get("DSTPU_CKPT_RETRIES", "3"))


def _io_backoff_s() -> float:
    return float(os.environ.get("DSTPU_CKPT_BACKOFF_S", "0.05"))


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _atomic_write(path: str, payload: Callable[[str], None],
                  suffix: str = ".tmp") -> int:
    """Write ``path`` crash-consistently: payload to a temp name, fsync,
    crc, rename. Transient ``OSError`` s (including injected ones) retry
    with exponential backoff; the temp file of a failed attempt is
    removed. Returns the crc32 of the durable bytes."""
    retries, backoff = _io_retries(), _io_backoff_s()
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        tmp = f"{path}.{os.getpid()}{suffix}"
        try:
            fault_point("ckpt_io", path=path)
            payload(tmp)
            _fsync_file(tmp)
            crc = _crc32_file(tmp)
            # torn-write injection lands HERE: between a complete temp
            # file and the rename — the window the protocol closes
            fault_point("ckpt_tmp", path=path, tmp=tmp)
            os.replace(tmp, path)
            return crc
        except OSError as e:
            last = e
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            if attempt >= retries:
                break
            delay = backoff * (2 ** attempt)
            logger.warning(
                f"checkpoint write of {os.path.basename(path)} failed "
                f"({e}); retry {attempt + 1}/{retries} in {delay:.3f}s")
            time.sleep(delay)
    raise OSError(
        f"checkpoint write of {path} failed after {retries + 1} attempts"
    ) from last


def _atomic_savez(path: str, arrays: Dict[str, np.ndarray]) -> int:
    # np.savez appends '.npz' to names missing it — the temp suffix must
    # keep the extension or the rename source won't exist
    return _atomic_write(path, lambda tmp: np.savez(tmp, **arrays),
                         suffix=".tmp.npz")


def _atomic_json(path: str, obj: Any) -> int:
    def payload(tmp: str) -> None:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=2, default=str)
    return _atomic_write(path, payload)


def _atomic_text(path: str, text: str) -> int:
    def payload(tmp: str) -> None:
        with open(tmp, "w") as f:
            f.write(text)
    return _atomic_write(path, payload)


def write_latest(save_dir: str, tag: str) -> None:
    """Atomically repoint ``latest`` — the commit point of a checkpoint.
    Callers must only invoke this after every data file of ``tag`` is
    durable (the async engine orders it last in the same worker task)."""
    _atomic_text(os.path.join(save_dir, "latest"), tag)


# ---------------------------------------------------------------------------
# last-known-good pinning (dstpu-guardian, docs/RESILIENCE.md)
# ---------------------------------------------------------------------------
#: sibling of ``latest``: the newest tag the numerics guardian has
#: declared clean (committed only after a verified-clean window). The
#: rollback target — retention never retires it, and the corrupt-
#: ``latest`` fallback prefers it over "newest verified".
KNOWN_GOOD_FILE = "known_good"


def pin_known_good(save_dir: str, tag: str) -> None:
    """Atomically pin ``tag`` as the last-known-good checkpoint. The
    guardian calls this only after ``clean_window_for_pin`` consecutive
    clean steps — a tag written during an anomaly streak never becomes
    the rollback target."""
    _atomic_text(os.path.join(save_dir, KNOWN_GOOD_FILE), tag)


def read_known_good(save_dir: str) -> Optional[str]:
    """The pinned tag, or ``None`` when nothing was ever pinned (or the
    pin file is unreadable — a torn pin must not fail a load)."""
    path = os.path.join(save_dir, KNOWN_GOOD_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            tag = f.read().strip()
    except OSError:
        return None
    return tag or None


def rollback_to_known_good(save_dir: str) -> Optional[str]:
    """Repoint ``latest`` at the pinned known-good tag so the next resume
    (elastic restart or in-process reload) loads it. Returns the tag, or
    ``None`` when no pin exists or the pinned bytes no longer verify —
    the caller then falls back to plain ``latest`` resolution (which
    itself refuses to silently re-initialize)."""
    tag = read_known_good(save_dir)
    if tag is None:
        return None
    ok, reason = verify_tag(os.path.join(save_dir, tag))
    if not ok:
        logger.error(f"guardian rollback: pinned tag '{tag}' fails "
                     f"verification ({reason}); leaving `latest` alone")
        return None
    write_latest(save_dir, tag)
    logger.warning(f"guardian rollback: `latest` repointed to pinned "
                   f"known-good tag '{tag}'")
    return tag


def write_staged(save_dir: str, tag: str, keys, host: Dict[str, np.ndarray],
                 client_state: Dict[str, Any], save_latest: bool = True,
                 extra_checksums: Optional[Dict[str, int]] = None) -> None:
    """Write an already-staged (host-resident) single-process checkpoint:
    data, then meta.json (the commit record, carrying the data files'
    checksums), then — optionally — the ``latest`` repoint. The IO half
    of a write-behind save; runs on the async engine's worker thread.

    ``extra_checksums`` folds sidecar data files written BEFORE this call
    (the offload optimizer sidecar) into the commit record, so
    ``verify_tag`` covers them: a tag whose sidecar was torn after commit
    fails verification instead of loading half a master state."""
    path = os.path.join(save_dir, tag)
    os.makedirs(path, exist_ok=True)
    # npz keys cannot contain some chars; index them
    crc = _atomic_savez(os.path.join(path, "state.npz"),
                        {f"leaf_{i}": host[k] for i, k in enumerate(keys)})
    # an elastic restart may re-save a tag previously written at
    # another process count — stale rank files (and their checksum
    # sidecars, see the multi-host branch) must not shadow this
    import glob as _glob
    for f in _glob.glob(os.path.join(path, "state.rank*.npz*")):
        os.remove(f)
    meta = {
        "keys": keys,
        "dtypes": {k: str(host[k].dtype) for k in keys},
        "shapes": {k: list(host[k].shape) for k in keys},
        "num_shard_files": 0,
        "checksums": {"state.npz": crc, **(extra_checksums or {})},
        "client_state": client_state,
    }
    _atomic_json(os.path.join(path, "meta.json"), meta)
    if save_latest:
        write_latest(save_dir, tag)


def save_checkpoint(save_dir: str, tag: str, state, client_state: Dict[str, Any],
                    save_latest: bool = True,
                    extra_checksums: Optional[Dict[str, int]] = None) -> None:
    pcount = jax.process_count()
    if pcount == 1:
        keys, host = stage_state(state)
        write_staged(save_dir, tag, keys, host, client_state,
                     save_latest=save_latest,
                     extra_checksums=extra_checksums)
        return
    path = os.path.join(save_dir, tag)
    os.makedirs(path, exist_ok=True)
    flat = _flatten_with_paths(state)
    keys = sorted(flat.keys())
    # multi-host: remote shards are not addressable — every process
    # writes its replica-0 pieces; the union across rank files tiles
    # each leaf exactly once
    pieces: Dict[str, np.ndarray] = {}
    for i, k in enumerate(keys):
        v = flat[k]
        if hasattr(v, "addressable_shards"):
            pieces.update(_owned_pieces(i, v))
        elif jax.process_index() == 0:  # host scalars/ndarrays
            pieces[f"leaf_{i}__full"] = np.asarray(v)
    fname = f"state.rank{jax.process_index()}.npz"
    crc = _atomic_savez(os.path.join(path, fname), pieces)
    # checksum handoff without a device collective: each rank drops a
    # sidecar next to its shard file; rank 0 folds them into meta.json
    # after the fence (the checkpoint dir is shared storage by
    # construction — _PieceReader already requires it)
    _atomic_text(os.path.join(path, fname + ".crc"), str(crc))
    # commit fence: every rank's shard file must be on disk before rank
    # 0 writes meta.json and repoints `latest` — otherwise a crash in
    # the window leaves `latest` naming an unreadable checkpoint
    from ..comm import comm as _comm
    _comm.barrier()
    if jax.process_index() == 0:
        single = os.path.join(path, "state.npz")
        if os.path.exists(single):  # stale single-process format
            os.remove(single)
        checksums = {}
        for p in range(pcount):
            fn = f"state.rank{p}.npz"
            crc_path = os.path.join(path, fn + ".crc")
            with open(crc_path) as f:
                checksums[fn] = int(f.read().strip())
            os.remove(crc_path)
        meta = {
            "keys": keys,
            "dtypes": {k: str(np.dtype(flat[k].dtype)) for k in keys},
            "shapes": {k: list(np.shape(flat[k])) for k in keys},
            "num_shard_files": pcount,
            "checksums": {**checksums, **(extra_checksums or {})},
            "client_state": client_state,
        }
        _atomic_json(os.path.join(path, "meta.json"), meta)
        if save_latest:
            write_latest(save_dir, tag)
    # second fence: non-zero ranks must not return (and possibly
    # load_checkpoint) until rank 0 has committed meta.json/latest
    _comm.barrier()


# ---------------------------------------------------------------------------
# verification / retention / fallback
# ---------------------------------------------------------------------------
def verify_tag(path: str) -> Tuple[bool, str]:
    """Is the tag directory at ``path`` a complete, uncorrupted
    checkpoint? Checks the commit record (meta.json parses), that every
    data file it names exists, and — when the meta carries checksums
    (everything written since the durability contract landed) — that each
    file's crc32 matches. Pre-contract checkpoints verify by existence
    only. ``DSTPU_CKPT_VERIFY=0`` skips the byte scan (existence checks
    remain)."""
    meta_path = os.path.join(path, "meta.json")
    if not os.path.exists(meta_path):
        return False, "no meta.json (tag never committed)"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"meta.json unreadable: {e}"
    n = int(meta.get("num_shard_files") or 0)
    files = ([f"state.rank{p}.npz" for p in range(n)] if n
             else ["state.npz"])
    checksums = meta.get("checksums") or {}
    scan = os.environ.get("DSTPU_CKPT_VERIFY", "1").strip().lower() \
        not in ("0", "off", "false")
    # sidecar data files (offload optimizer state) committed through
    # extra_checksums are part of the contract too: a load needs them
    sidecars = [fn for fn in checksums if fn not in files]
    for fn in files + sidecars:
        fp = os.path.join(path, fn)
        if not os.path.exists(fp):
            return False, f"missing data file {fn}"
        if scan and fn in checksums:
            actual = _crc32_file(fp)
            if actual != int(checksums[fn]):
                return False, (f"checksum mismatch on {fn} "
                               f"(recorded {checksums[fn]}, found {actual})")
    return True, "ok"


def _committed_tags(save_dir: str) -> List[Tuple[float, int, str]]:
    """Store-format tags under ``save_dir`` with a commit record, as
    ``(meta mtime, client global_steps, tag)`` sorted oldest-first —
    the retirement/fallback ordering."""
    out = []
    try:
        entries = os.listdir(save_dir)
    except OSError:
        return []
    for name in entries:
        meta_path = os.path.join(save_dir, name, "meta.json")
        if not os.path.isfile(meta_path):
            continue
        try:
            with open(meta_path) as f:
                steps = int(json.load(f).get("client_state", {})
                            .get("global_steps", 0) or 0)
        except (ValueError, OSError, TypeError):
            steps = 0
        out.append((os.path.getmtime(meta_path), steps, name))
    out.sort()
    return out


def find_fallback_tag(load_dir: str, exclude: str) -> Optional[str]:
    """Newest committed tag (≠ ``exclude``) that passes verification —
    the recovery target when ``latest`` names a corrupt checkpoint."""
    for _, _, tag in reversed(_committed_tags(load_dir)):
        if tag == exclude:
            continue
        ok, reason = verify_tag(os.path.join(load_dir, tag))
        if ok:
            return tag
        logger.warning(f"checkpoint fallback: tag {tag} also fails "
                       f"verification ({reason}); continuing search")
    return None


def retire_old_tags(save_dir: str, keep_last: int,
                    protect: Tuple[str, ...] = ()) -> List[str]:
    """Keep-last-N retention: delete the oldest committed tags beyond
    ``keep_last``, never touching the tag ``latest`` names, the pinned
    known-good tag (the guardian's rollback target must outlive any
    retention window), nor anything in ``protect``. Returns the removed
    tag names. ``keep_last <= 0`` disables retention."""
    if keep_last <= 0:
        return []
    keep = set(protect)
    latest_path = os.path.join(save_dir, "latest")
    if os.path.exists(latest_path):
        try:
            with open(latest_path) as f:
                keep.add(f.read().strip())
        except OSError:
            pass
    pinned = read_known_good(save_dir)
    if pinned is not None:
        keep.add(pinned)
    tags = [t for _, _, t in _committed_tags(save_dir)]
    removable = [t for t in tags if t not in keep]
    # the protected tags count toward the retention budget
    n_protected_committed = len(tags) - len(removable)
    excess = len(removable) - max(0, keep_last - n_protected_committed)
    removed = []
    for tag in removable[:max(0, excess)]:
        try:
            shutil.rmtree(os.path.join(save_dir, tag))
            removed.append(tag)
        except OSError as e:  # retention must never fail a save
            logger.warning(f"checkpoint retention: could not remove "
                           f"{tag}: {e}")
    if removed:
        logger.info(f"checkpoint retention: retired {removed} "
                    f"(keep_last={keep_last})")
    return removed


def resolve_tag(load_dir: str, tag: Optional[str]) -> Tuple[Optional[str], bool]:
    """Resolve the tag to load and verify it. Returns ``(tag, fresh)``
    where ``fresh=True`` means "no checkpoint exists — initialize from
    scratch". An *explicit* tag that fails verification raises (the
    caller asked for those bytes); a corrupt tag named by ``latest``
    falls back to the pinned known-good tag when one exists and
    verifies (the guardian vouched for those bytes — a newer tag that
    merely *verifies* may hold a numerically-poisoned state), else to
    the newest verifying tag, and raises — never silently
    re-initializes — when there is none."""
    explicit = tag is not None
    if tag is None:
        latest_path = os.path.join(load_dir, "latest")
        if not os.path.exists(latest_path):
            return None, True
        with open(latest_path) as f:
            tag = f.read().strip()
    path = os.path.join(load_dir, tag)
    ok, reason = verify_tag(path)
    if ok:
        return tag, False
    if explicit:
        if not os.path.exists(os.path.join(path, "meta.json")):
            # preserved semantics: asking for a tag that was never
            # committed means "no checkpoint", not corruption
            return None, True
        raise ValueError(
            f"checkpoint tag '{tag}' failed verification: {reason}")
    pinned = read_known_good(load_dir)
    if pinned is not None and pinned != tag and \
            verify_tag(os.path.join(load_dir, pinned))[0]:
        logger.error(
            f"checkpoint 'latest' names tag '{tag}' which failed "
            f"verification ({reason}); falling back to the PINNED "
            f"known-good tag '{pinned}' (preferred over newest verified)")
        return pinned, False
    fb = find_fallback_tag(load_dir, exclude=tag)
    if fb is not None:
        logger.error(
            f"checkpoint 'latest' names tag '{tag}' which failed "
            f"verification ({reason}); falling back to newest verified "
            f"tag '{fb}'")
        return fb, False
    if not os.path.exists(os.path.join(path, "meta.json")) and \
            not _committed_tags(load_dir):
        # nothing was ever committed here (e.g. a foreign-format dir
        # whose `latest` belongs to the paged engine) — not corruption
        return None, True
    raise RuntimeError(
        f"checkpoint 'latest' names tag '{tag}' which failed verification "
        f"({reason}) and no other tag under {load_dir} verifies — refusing "
        f"to silently re-initialize; inspect or delete the directory to "
        f"start fresh")


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from its saved string name; ml_dtypes names (bfloat16,
    int4, ...) are not always registered with np.dtype."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _reassemble_rank_shards(path: str, meta: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Rebuild FULL leaves from per-process shard files — used by single-
    process consumers (topology-collapse resume, zero_to_fp32 export).
    Multi-process resume uses :class:`_PieceReader` directly, assembling
    only each host's addressable spans (~1/n_hosts of the bytes); this is
    the read_full-over-every-leaf special case of the same reader, so one
    parser/validator covers both paths."""
    reader = _PieceReader(path, meta)
    return {k: reader.read_full(i, tuple(meta["shapes"][k]),
                                _np_dtype(meta["dtypes"][k]))
            for i, k in enumerate(meta["keys"])}


class _PieceReader:
    """Span-addressed reader over the per-process shard files: assembles an
    arbitrary global slice of a leaf from only the pieces that intersect
    it, decompressing npz members lazily — so a resuming process touches
    ~1/n_hosts of the checkpoint bytes instead of the whole state."""

    def __init__(self, path: str, meta: Dict[str, Any]):
        n = int(meta["num_shard_files"])
        self._files = [os.path.join(path, f"state.rank{p}.npz")
                       for p in range(n)]
        missing = [f for f in self._files if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(
                f"checkpoint is missing shard files {missing} — all "
                f"{n} per-process files are required")
        self._meta = meta
        # index transiently: keeping n NpzFile handles open would exhaust
        # fds at exactly the host counts this path exists for
        self._index: Dict[int, list] = {}
        for fi, f in enumerate(self._files):
            with np.load(f) as z:
                names = list(z.files)
            for piece_key in names:
                head, _, spans = piece_key.partition("__")
                i = int(head[len("leaf_"):])
                if spans == "full" or not spans:
                    bounds = tuple((0, d) for d in meta["shapes"][meta["keys"][i]])
                else:
                    bounds = tuple(tuple(map(int, s.split("_")))
                                   for s in spans.split("__"))
                self._index.setdefault(i, []).append((bounds, fi, piece_key))

    def read(self, i: int, shape, dtype, idx) -> np.ndarray:
        """Assemble the global slice ``idx`` (tuple of slices) of leaf i."""
        pieces = self._index.get(i, ())
        if not pieces:
            raise ValueError(f"leaf {i} has no pieces in any shard file — "
                             "checkpoint is inconsistent with its meta.json")
        req = tuple((sl.start or 0,
                     sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(idx, shape)) if idx else ()
        if not req:  # scalar leaf
            bounds, fi, k = pieces[0]
            with np.load(self._files[fi]) as z:
                return np.asarray(z[k], dtype)
        out = np.empty([b - a for a, b in req], dtype)
        covered = 0
        # group by file so each needed shard file opens once per read
        by_file: Dict[int, list] = {}
        for bounds, fi, k in pieces:
            inter = [(max(a, ba), min(b, bb))
                     for (a, b), (ba, bb) in zip(req, bounds)]
            if any(a >= b for a, b in inter):
                continue
            by_file.setdefault(fi, []).append((bounds, k, inter))
        for fi, items in by_file.items():
            with np.load(self._files[fi]) as z:
                for bounds, k, inter in items:
                    piece = z[k]
                    src = tuple(slice(a - ba, b - ba)
                                for (a, b), (ba, bb) in zip(inter, bounds))
                    dst = tuple(slice(a - ra, b - ra)
                                for (a, b), (ra, _) in zip(inter, req))
                    out[dst] = piece[src]
                    covered += int(np.prod([b - a for a, b in inter]))
        if covered != out.size:
            raise ValueError(
                f"leaf {i}: assembled {covered} of {out.size} elements for "
                f"slice {req} — shard files are inconsistent")
        return out

    def read_full(self, i: int, shape, dtype) -> np.ndarray:
        return self.read(i, shape, dtype,
                         tuple(slice(0, d) for d in shape))


def load_checkpoint(load_dir: str, tag: Optional[str], state_template, shardings,
                    load_optimizer_states: bool = True
                    ) -> Tuple[Optional[Any], Dict[str, Any], Optional[str]]:
    # meta.json is the commit record (written LAST, after all data files):
    # its absence means "no checkpoint"; once present, failed verification
    # (missing data file, checksum mismatch) either falls back to the
    # newest verified tag (`latest`-resolved loads) or fails loudly
    # (explicit tags) — never a silent re-initialize
    tag, fresh = resolve_tag(load_dir, tag)
    if fresh:
        return None, {}, None
    path = os.path.join(load_dir, tag)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    sharded_ckpt = int(meta.get("num_shard_files") or 0) > 0
    reader = by_key = None
    if sharded_ckpt and jax.process_count() > 1:
        # distributed resume: DON'T materialize the full state per host —
        # each process assembles only the spans its target shardings make
        # addressable (1/n_hosts of the bytes)
        reader = _PieceReader(path, meta)
    elif sharded_ckpt:
        by_key = _reassemble_rank_shards(path, meta)
    else:
        data = np.load(os.path.join(path, "state.npz"))
        by_key = {k: data[f"leaf_{i}"] for i, k in enumerate(meta["keys"])}

    template_flat = _flatten_with_paths(state_template)
    sharding_flat = _flatten_with_paths(shardings)
    leaves, treedef = jax.tree_util.tree_flatten(state_template)
    key_index = {k: i for i, k in enumerate(meta["keys"])}
    # rebuild in template order; skip optimizer states on request
    new_flat = {}
    for key, tmpl in template_flat.items():
        wanted = (load_optimizer_states or not key.startswith("opt/"))
        in_ckpt = key in key_index and (by_key is None or key in by_key)
        if in_ckpt and wanted:
            shape = tuple(meta["shapes"][key]) if reader is not None \
                else tuple(by_key[key].shape)
            if shape != tuple(np.shape(tmpl)):
                raise ValueError(
                    f"checkpoint leaf '{key}' shape {shape} != expected "
                    f"{np.shape(tmpl)}")
            sharding = sharding_flat.get(key)
            if reader is not None and sharding is not None:
                i = key_index[key]
                dtype = np.dtype(tmpl.dtype)
                arr = jax.make_array_from_callback(
                    shape, sharding,
                    lambda idx, i=i, s=shape, d=dtype:
                        reader.read(i, s, d, idx))
            else:
                value = (reader.read_full(key_index[key], shape,
                                          np.dtype(tmpl.dtype))
                         if reader is not None else by_key[key])
                arr = jax.device_put(np.asarray(value).astype(tmpl.dtype),
                                     sharding)
        else:
            arr = tmpl
        new_flat[key] = arr
    ordered = [new_flat[k] for k in template_flat.keys()]
    state = jax.tree_util.tree_unflatten(treedef, ordered)
    return state, meta.get("client_state", {}), tag
