"""Checkpoint persistence.

Counterpart of the reference's engine save/load path (``engine.py:3050``
``save_checkpoint`` → tag dirs + ``latest`` file; ``:2688`` ``load_checkpoint``)
and the pluggable ``CheckpointEngine`` (checkpoint_engine.py:9).

Layout (tag-based dirs like the reference):

    <dir>/<tag>/state.npz        # flattened pytree leaves (gathered to host)
    <dir>/<tag>/meta.json        # treedef paths, dtypes, client state
    <dir>/latest                 # text file holding the newest tag

Leaves are saved *unsharded* (gathered) in this round-1 store; sharded leaves
are fetched with ``jax.device_get`` which performs the gather. On load,
leaves are re-placed with the engine's sharding tree, so a checkpoint written
under one topology loads under any other — the "universal checkpoint"
property the reference needs a whole offline tool for (``checkpoint/
ds_to_universal.py``) falls out of addressing params by logical name.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _flatten_with_paths(tree) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = leaf
    return flat


def save_checkpoint(save_dir: str, tag: str, state, client_state: Dict[str, Any],
                    save_latest: bool = True) -> None:
    path = os.path.join(save_dir, tag)
    os.makedirs(path, exist_ok=True)
    flat = _flatten_with_paths(state)
    host = {k: np.asarray(jax.device_get(v)) for k, v in flat.items()}
    # npz keys cannot contain some chars; index them
    keys = sorted(host.keys())
    np.savez(os.path.join(path, "state.npz"), **{f"leaf_{i}": host[k] for i, k in enumerate(keys)})
    meta = {
        "keys": keys,
        "dtypes": {k: str(host[k].dtype) for k in keys},
        "client_state": client_state,
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    if save_latest:
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)


def load_checkpoint(load_dir: str, tag: Optional[str], state_template, shardings,
                    load_optimizer_states: bool = True
                    ) -> Tuple[Optional[Any], Dict[str, Any], Optional[str]]:
    if tag is None:
        latest_path = os.path.join(load_dir, "latest")
        if not os.path.exists(latest_path):
            return None, {}, None
        with open(latest_path) as f:
            tag = f.read().strip()
    path = os.path.join(load_dir, tag)
    if not os.path.exists(os.path.join(path, "state.npz")):
        return None, {}, None

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    by_key = {k: data[f"leaf_{i}"] for i, k in enumerate(meta["keys"])}

    template_flat = _flatten_with_paths(state_template)
    sharding_flat = _flatten_with_paths(shardings)
    leaves, treedef = jax.tree_util.tree_flatten(state_template)
    # rebuild in template order; skip optimizer states on request
    new_flat = {}
    for key, tmpl in template_flat.items():
        if key in by_key and (load_optimizer_states or not key.startswith("opt/")):
            value = by_key[key]
            if tuple(value.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"checkpoint leaf '{key}' shape {value.shape} != expected {tmpl.shape}")
            sharding = sharding_flat.get(key)
            arr = jax.device_put(value.astype(tmpl.dtype), sharding)
        else:
            arr = tmpl
        new_flat[key] = arr
    ordered = [new_flat[k] for k in template_flat.keys()]
    state = jax.tree_util.tree_unflatten(treedef, ordered)
    return state, meta.get("client_state", {}), tag
