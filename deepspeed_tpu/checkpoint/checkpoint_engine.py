"""Pluggable checkpoint engines.

Counterpart of the reference ``runtime/checkpoint_engine/checkpoint_engine.py``
(``CheckpointEngine`` :9 — create/save/load/commit) with two concrete
engines: the synchronous default (reference ``TorchCheckpointEngine``) and an
asynchronous write-behind engine filling the Nebula slot
(``nebula_checkpoint_engine.py:20``) — saves run on a background thread while
training continues; ``commit`` fences the tag durable.
"""

from __future__ import annotations

import os
import shutil
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import logger


class CheckpointEngine:
    """Interface (reference checkpoint_engine.py:9)."""

    def create(self, tag: str) -> None:  # pragma: no cover - trivial
        """Signal the start of a new checkpoint under ``tag``."""

    def save(self, state_dict: Dict[str, np.ndarray], path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Make ``tag`` durable; returns success."""
        return True

    def submit(self, tag: str, fn) -> Optional[Future]:
        """Run a whole checkpoint-write task. Synchronous engines run it
        inline; the async engine queues it on the worker thread — the
        task's internal ordering (data → meta → ``latest``) IS the commit
        fence, since one task runs on one thread."""
        fn()
        return None


class NpzCheckpointEngine(CheckpointEngine):
    """Synchronous npz persistence (the reference's TorchCheckpointEngine).
    Writes ride the store's durable-write primitive: temp + fsync +
    ``os.replace`` with retry-with-backoff (docs/RESILIENCE.md)."""

    def save(self, state_dict: Dict[str, np.ndarray], path: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        from .store import _atomic_savez
        if not path.endswith(".npz"):
            path += ".npz"  # np.savez's own extension behavior, kept
        _atomic_savez(path, state_dict)

    def load(self, path: str, map_location=None) -> Dict[str, np.ndarray]:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}


class AsyncCheckpointEngine(NpzCheckpointEngine):
    """Write-behind checkpointing (the Nebula slot): ``save`` stages the
    arrays and returns immediately; IO happens on a worker thread. ``commit``
    blocks until every pending save for the tag has landed, then writes a
    tag-complete marker — the durability point the reference's Nebula tier
    provides."""

    def __init__(self, num_threads: int = 2):
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self._pending: List[Future] = []
        self._lock = threading.Lock()

    def save(self, state_dict: Dict[str, np.ndarray], path: str) -> None:
        staged = {k: np.array(v, copy=True) for k, v in state_dict.items()}
        fut = self._pool.submit(super().save, staged, path)
        with self._lock:
            self._pending.append(fut)

    def submit(self, tag: str, fn) -> Future:
        """Queue a full checkpoint-write task (engine.save_checkpoint's
        write-behind path). The caller must have staged all device data to
        host already; the task records its duration as a telemetry
        checkpoint span from the worker thread."""
        from ..telemetry import get_telemetry

        def run():
            with get_telemetry().phase(f"checkpoint_write:{tag}",
                                       phase="checkpoint"):
                fn()

        fut = self._pool.submit(run)
        with self._lock:
            self._pending.append(fut)
        return fut

    def commit(self, tag: str) -> bool:
        with self._lock:
            pending, self._pending = self._pending, []
        ok = True
        for f in pending:
            try:
                f.result()
            except Exception as e:  # pragma: no cover
                logger.error(f"async checkpoint write failed: {e}")
                ok = False
        return ok

    def close(self) -> None:
        self.commit("")
        self._pool.shutdown()
