from .checkpoint_engine import (AsyncCheckpointEngine, CheckpointEngine,  # noqa: F401
                                NpzCheckpointEngine)
from .ds_to_universal import ds_to_universal, load_universal  # noqa: F401
from .store import (load_checkpoint, resolve_tag, retire_old_tags,  # noqa: F401
                    save_checkpoint, verify_tag)
