from .checkpoint_engine import (AsyncCheckpointEngine, CheckpointEngine,  # noqa: F401
                                NpzCheckpointEngine)
from .ds_to_universal import ds_to_universal, load_universal  # noqa: F401
from .store import load_checkpoint, save_checkpoint  # noqa: F401
