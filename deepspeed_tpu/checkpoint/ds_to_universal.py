"""Universal checkpoint conversion.

Counterpart of the reference ``checkpoint/ds_to_universal.py``
(``extract_zero_shards`` :87, ``merge_tp_slices`` :156): converts a training
checkpoint into a *topology-independent* layout — one directory per logical
parameter holding fp32 master weights + optimizer moments, loadable into any
DP/TP/PP arrangement.

Our store already saves leaves gathered and addressed by logical path (no
per-rank shards to merge), so conversion is a re-keying: explode the state
npz into per-parameter files under ``zero/<param-path>/{fp32,exp_avg,
exp_avg_sq}.npy`` exactly mirroring the reference's universal directory
contract, so external tooling written against that contract ports over.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Optional

import numpy as np

_SLOT_MAP = {
    "master": "fp32",
    "exp_avg": "exp_avg",
    "exp_avg_sq": "exp_avg_sq",
    "sum_sq": "exp_avg_sq",
}


def _load_state(ckpt_dir: str, tag: Optional[str]):
    if tag is None:
        with open(os.path.join(ckpt_dir, "latest")) as f:
            tag = f.read().strip()
    path = os.path.join(ckpt_dir, tag)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    return {k: data[f"leaf_{i}"] for i, k in enumerate(meta["keys"])}, meta, tag


def ds_to_universal(ckpt_dir: str, out_dir: str, tag: Optional[str] = None) -> int:
    """Write the universal layout; returns number of parameters emitted."""
    by_key, meta, tag = _load_state(ckpt_dir, tag)
    count = 0
    for key, value in by_key.items():
        parts = key.split("/")
        if parts[0] == "opt" and len(parts) >= 3 and parts[1] in _SLOT_MAP:
            slot, param_path = _SLOT_MAP[parts[1]], "/".join(parts[2:])
        elif parts[0] == "params":
            # bit16 model weights: only authoritative when no fp32 master
            slot, param_path = "bit16", "/".join(parts[1:])
        else:
            continue
        pdir = os.path.join(out_dir, "zero", param_path.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, f"{slot}.npy"), value)
        count += 1
    with open(os.path.join(out_dir, "universal_meta.json"), "w") as f:
        json.dump({"source_tag": tag, "format": "dstpu_universal_v1"}, f)
    return count


def load_universal(out_dir: str) -> Dict[str, np.ndarray]:
    """Read back {param_path: fp32_weights} (reference
    ``universal_checkpoint.py`` load hooks)."""
    zero_dir = os.path.join(out_dir, "zero")
    out = {}
    for name in sorted(os.listdir(zero_dir)):
        pdir = os.path.join(zero_dir, name)
        fp32 = os.path.join(pdir, "fp32.npy")
        bit16 = os.path.join(pdir, "bit16.npy")
        if os.path.exists(fp32):
            out[name] = np.load(fp32)
        elif os.path.exists(bit16):
            out[name] = np.load(bit16).astype(np.float32)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="Convert a DeepSpeed-TPU checkpoint "
                                            "to the universal format")
    p.add_argument("input_folder")
    p.add_argument("output_folder")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    n = ds_to_universal(args.input_folder, args.output_folder, args.tag)
    print(f"wrote {n} parameter slots to {args.output_folder}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
