"""CLIP text encoder for TPU inference.

Counterpart of the reference's CLIP container in the stable-diffusion
injection path (``model_implementations/diffusers`` + the CLIP policy in
``module_inject/containers/clip.py``): the prompt encoder of the SD
pipeline, implemented directly in JAX and loading real HF
``CLIPTextModel`` checkpoints (``text_model.*`` parameter names) — logits
parity with the torch forward is asserted in tests.

Architecture (openai/clip-vit-*/ SD text encoders): learned positions,
pre-LN transformer with CAUSAL masking (CLIP text towers are causal),
quick_gelu (SD-1.x) or gelu (SD-2.x) MLPs, final LayerNorm, and a pooled
output taken at each sequence's EOS/argmax position.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    """Field names follow HF CLIPTextConfig."""
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    hidden_act: str = "quick_gelu"   # 'quick_gelu' (SD1) | 'gelu' (SD2)
    layer_norm_eps: float = 1e-5
    eos_token_id: int = 49407
    dtype: Any = jnp.float32


def _ln(p: Params, x: jax.Array, eps: float) -> jax.Array:
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    return out * p["weight"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _lin(p: Params, x: jax.Array) -> jax.Array:
    return x @ jnp.transpose(p["weight"]).astype(x.dtype) + p["bias"].astype(x.dtype)


def _act(name: str, x: jax.Array) -> jax.Array:
    if name == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    if name == "gelu":
        return jax.nn.gelu(x, approximate=False)  # HF 'gelu' is exact
    raise ValueError(f"unsupported CLIP hidden_act {name!r} "
                     "(supported: quick_gelu, gelu)")


class CLIPTextModel:

    def __init__(self, config: CLIPTextConfig):
        self.config = config

    def _attn(self, p: Params, x: jax.Array) -> jax.Array:
        c = self.config
        B, S, C = x.shape
        H = c.num_attention_heads
        D = C // H
        q = _lin(p["q_proj"], x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = _lin(p["k_proj"], x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = _lin(p["v_proj"], x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32) / (D ** 0.5)
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        logits = jnp.where(mask[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, -1).astype(x.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        return _lin(p["out_proj"], out.transpose(0, 2, 1, 3).reshape(B, S, C))

    def apply(self, params: Params, input_ids: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """input_ids [B, S] → (last_hidden_state [B, S, C],
        pooled_output [B, C])."""
        c = self.config
        tm = params["text_model"]
        x = jnp.take(tm["embeddings"]["token_embedding"]["weight"],
                     input_ids, axis=0).astype(c.dtype)
        pos = tm["embeddings"]["position_embedding"]["weight"][:input_ids.shape[1]]
        x = x + pos.astype(c.dtype)

        for li in range(c.num_hidden_layers):
            lp = tm["encoder"]["layers"][str(li)]
            x = x + self._attn(lp["self_attn"],
                               _ln(lp["layer_norm1"], x, c.layer_norm_eps))
            h = _ln(lp["layer_norm2"], x, c.layer_norm_eps)
            h = _act(c.hidden_act, _lin(lp["mlp"]["fc1"], h))
            x = x + _lin(lp["mlp"]["fc2"], h)

        x = _ln(tm["final_layer_norm"], x, c.layer_norm_eps)
        # pooled: hidden state at each sequence's EOS. HF special-cases the
        # LEGACY configs that say eos_token_id=2 while the tokenizer's real
        # EOS is 49407 (openai/clip-vit-*, SD-1.5 text encoders): there the
        # EOS position is argmax over token ids (EOS is the largest id);
        # modern configs match eos_token_id directly (first occurrence).
        if c.eos_token_id == 2:
            eos_pos = jnp.argmax(input_ids, axis=1)
        else:
            eos_pos = jnp.argmax((input_ids == c.eos_token_id).astype(jnp.int32),
                                 axis=1)
        pooled = x[jnp.arange(x.shape[0]), eos_pos]
        return x, pooled

    __call__ = apply


from .diffusers.unet_2d_condition import _nest  # noqa: E402  (shared helper)


def clip_config_from_hf(cfg: Dict[str, Any], dtype=jnp.float32) -> CLIPTextConfig:
    return CLIPTextConfig(
        vocab_size=cfg.get("vocab_size", 49408),
        hidden_size=cfg.get("hidden_size", 768),
        intermediate_size=cfg.get("intermediate_size", 3072),
        num_hidden_layers=cfg.get("num_hidden_layers", 12),
        num_attention_heads=cfg.get("num_attention_heads", 12),
        max_position_embeddings=cfg.get("max_position_embeddings", 77),
        hidden_act=cfg.get("hidden_act", "quick_gelu"),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-5),
        eos_token_id=cfg.get("eos_token_id", 49407),
        dtype=dtype)


def load_clip_text_model(model_path: str,
                         dtype=jnp.float32) -> Tuple[CLIPTextModel, Params]:
    """HF CLIPTextModel directory (config.json + model.safetensors /
    pytorch_model.bin) → (model, params)."""
    from ..runtime.state_dict_factory import HFCheckpointLoader

    loader = HFCheckpointLoader(model_path)
    cfg = loader.config
    if "text_config" in cfg:  # full CLIPConfig: take the text tower
        cfg = cfg["text_config"]
    model = CLIPTextModel(clip_config_from_hf(cfg, dtype))
    sd = loader.load_state_dict()
    # drop the contrastive-projection head if present (text encoder only)
    sd = {k: v for k, v in sd.items() if k.startswith("text_model.")}
    return model, _nest(sd)
