from .diffusers.unet_2d_condition import (UNet2DConditionModel,  # noqa: F401
                                          UNetConfig, load_diffusers_unet)
