from .clip import CLIPTextModel, CLIPTextConfig, load_clip_text_model  # noqa: F401
from .diffusers.unet_2d_condition import (UNet2DConditionModel,  # noqa: F401
                                          UNetConfig, load_diffusers_unet)
from .diffusers.vae import (VAEDecoder, VAEDecoderConfig,  # noqa: F401
                            load_diffusers_vae_decoder)
