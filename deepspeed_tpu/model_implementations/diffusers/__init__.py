from .unet_2d_condition import (UNet2DConditionModel, UNetConfig,  # noqa: F401
                                load_diffusers_unet)
