"""Stable-Diffusion VAE decoder (AutoencoderKL) for TPU inference.

Counterpart of the reference's ``model_implementations/diffusers/vae.py``
(a CUDA-graph wrapper over the HF module): the latent→image decoder
implemented directly in JAX/NHWC, loading real diffusers
``AutoencoderKL`` checkpoints by their standard names (``decoder.*`` +
``post_quant_conv``) without the diffusers library.

Decoder topology (SD-1.x/2.x): conv_in → mid (resnet, single
full-attention block, resnet) → 4 up blocks of (layers_per_block+1)
time-embedding-free resnets with nearest-2x upsampling between → GroupNorm
→ conv_out.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .unet_2d_condition import (_conv, _group_norm, _linear,
                                _load_diffusers_weights, _nest)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VAEDecoderConfig:
    """Fields follow diffusers AutoencoderKL config.json."""
    latent_channels: int = 4
    out_channels: int = 3
    block_out_channels: Sequence[int] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32


class VAEDecoder:

    def __init__(self, config: VAEDecoderConfig):
        self.config = config

    def _resnet(self, p: Params, x: jax.Array) -> jax.Array:
        c = self.config
        h = _group_norm(p["norm1"], x, c.norm_num_groups, eps=1e-6)
        h = _conv(p["conv1"], jax.nn.silu(h))
        h = _group_norm(p["norm2"], h, c.norm_num_groups, eps=1e-6)
        h = _conv(p["conv2"], jax.nn.silu(h))
        if "conv_shortcut" in p:
            x = _conv(p["conv_shortcut"], x, padding=0)
        return x + h

    def _attn(self, p: Params, x: jax.Array) -> jax.Array:
        """VAE mid attention: single-head full attention over H*W."""
        c = self.config
        B, H, W, C = x.shape
        h = _group_norm(p["group_norm"], x, c.norm_num_groups, eps=1e-6)
        h = h.reshape(B, H * W, C)
        q = _linear(p["to_q"], h)
        k = _linear(p["to_k"], h)
        v = _linear(p["to_v"], h)
        logits = jnp.einsum("bqc,bkc->bqk", q, k,
                            preferred_element_type=jnp.float32) / math.sqrt(C)
        probs = jax.nn.softmax(logits, -1).astype(x.dtype)
        out = jnp.einsum("bqk,bkc->bqc", probs, v)
        out = _linear(p["to_out"]["0"], out).reshape(B, H, W, C)
        return x + out

    def apply(self, params: Params, latents: jax.Array,
              scale_input: bool = True) -> jax.Array:
        """latents [B, h, w, latent_channels] (NHWC) → image
        [B, 8h, 8w, out_channels] in [-1, 1]. ``scale_input`` divides by
        the diffusion scaling_factor first (diffusers ``vae.decode``
        convention)."""
        c = self.config
        x = latents.astype(c.dtype)
        if scale_input:
            x = x / c.scaling_factor
        x = _conv(params["post_quant_conv"], x, padding=0)
        d = params["decoder"]
        h = _conv(d["conv_in"], x)

        h = self._resnet(d["mid_block"]["resnets"]["0"], h)
        h = self._attn(d["mid_block"]["attentions"]["0"], h)
        h = self._resnet(d["mid_block"]["resnets"]["1"], h)

        n = len(c.block_out_channels)
        for bi in range(n):
            bp = d["up_blocks"][str(bi)]
            for li in range(c.layers_per_block + 1):
                h = self._resnet(bp["resnets"][str(li)], h)
            if bi < n - 1:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = _conv(bp["upsamplers"]["0"]["conv"], h)

        h = _group_norm(d["conv_norm_out"], h, c.norm_num_groups, eps=1e-6)
        return _conv(d["conv_out"], jax.nn.silu(h))

    __call__ = apply


def init_vae_decoder_params(config: VAEDecoderConfig, seed: int = 0,
                            scale: float = 0.02) -> Dict[str, np.ndarray]:
    """Flat diffusers-named tree for the decoder half of AutoencoderKL —
    also the loader's checkpoint schema."""
    from .unet_2d_condition import _FlatInit

    c = config
    b = _FlatInit(seed, scale)
    flat, conv, lin, norm = b.flat, b.conv, b.lin, b.norm

    def resnet(name, ci, co):
        norm(f"{name}.norm1", ci)
        conv(f"{name}.conv1", ci, co)
        norm(f"{name}.norm2", co)
        conv(f"{name}.conv2", co, co)
        if ci != co:
            conv(f"{name}.conv_shortcut", ci, co, k=1)

    conv("post_quant_conv", c.latent_channels, c.latent_channels, k=1)
    top = c.block_out_channels[-1]
    conv("decoder.conv_in", c.latent_channels, top)
    resnet("decoder.mid_block.resnets.0", top, top)
    a = "decoder.mid_block.attentions.0"
    norm(f"{a}.group_norm", top)
    for proj in ("to_q", "to_k", "to_v"):
        lin(f"{a}.{proj}", top, top)
    lin(f"{a}.to_out.0", top, top)
    resnet("decoder.mid_block.resnets.1", top, top)

    rc = list(reversed(c.block_out_channels))
    prev = top
    for bi, co in enumerate(rc):
        for li in range(c.layers_per_block + 1):
            resnet(f"decoder.up_blocks.{bi}.resnets.{li}",
                   prev if li == 0 else co, co)
        if bi < len(rc) - 1:
            conv(f"decoder.up_blocks.{bi}.upsamplers.0.conv", co, co)
        prev = co

    norm("decoder.conv_norm_out", c.block_out_channels[0])
    conv("decoder.conv_out", c.block_out_channels[0], c.out_channels)
    return flat


def load_diffusers_vae_decoder(model_path: str,
                               dtype=jnp.float32) -> Tuple[VAEDecoder, Params]:
    """AutoencoderKL directory → (VAEDecoder, params). Encoder tensors in
    the checkpoint are ignored (decode-only serving path)."""
    import json
    import os

    from ...runtime.state_dict_factory import (_load_safetensors,
                                               _load_torch_bin)

    with open(os.path.join(model_path, "config.json")) as f:
        cfg = json.load(f)
    config = VAEDecoderConfig(
        latent_channels=cfg.get("latent_channels", 4),
        out_channels=cfg.get("out_channels", 3),
        block_out_channels=tuple(cfg.get("block_out_channels",
                                         (128, 256, 512, 512))),
        layers_per_block=cfg.get("layers_per_block", 2),
        norm_num_groups=cfg.get("norm_num_groups", 32),
        scaling_factor=cfg.get("scaling_factor", 0.18215),
        dtype=dtype)

    sd = {k: v for k, v in _load_diffusers_weights(model_path).items()
          if k.startswith(("decoder.", "post_quant_conv."))}
    expected = set(init_vae_decoder_params(config))
    if expected != set(sd):
        missing = sorted(expected - set(sd))[:5]
        extra = sorted(set(sd) - expected)[:5]
        raise ValueError(
            f"checkpoint does not match the supported VAE decoder topology: "
            f"{len(expected - set(sd))} missing (e.g. {missing}), "
            f"{len(set(sd) - expected)} unsupported (e.g. {extra})")
    return VAEDecoder(config), _nest(sd)
